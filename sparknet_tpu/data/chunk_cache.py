"""Host-local content-addressed chunk cache in front of the object stores.

SparkNet kept minibatch RDDs **resident** across iterations (PAPER.md
L7) so an epoch cost one pass over the network, ever.  The TPU rewrite
deliberately streams tar shards "with no staging"
(``data/object_store.py``) — correct for a single pass, but a
multi-epoch run re-downloads every worker's partition every epoch:
network cost O(workers x epochs) where the reference paid O(1)
(ROADMAP item 5).  This module is the byte half of the fix
(``data/shuffle.py`` is the metadata half): a bounded, host-local,
content-addressed cache that fronts any ``ObjectStore``, so epoch 2+
reads the local disk and the network cost of a run is flat in epochs.

Design (deliberately the ``io/checkpoint.py`` integrity recipe, applied
to data):

- **content addressing**: an entry is keyed by
  ``sha1(store_url + name)``; the entry's sidecar manifest records the
  fetch-time ``etag``/``size`` so a changed upstream object (different
  etag or size, when the caller knows them) invalidates the entry
  instead of serving stale bytes.
- **CRC32 manifest, verified on every read**: each entry publishes
  ``<key>.meta.json`` with the chunk's CRC32 + size (exactly like
  snapshot manifests); every hit re-checksums the chunk before serving.
- **atomic publish, manifest last**: chunk bytes land via
  temp-file + ``os.replace``; the manifest publishes after — a crash
  mid-write can never leave a manifest vouching for half-written data.
- **quarantine + transparent refetch**: a hit that fails its CRC/size
  check (bit-rot, a torn write from a killed process) is renamed
  ``*.corrupt`` (forensics keep the evidence; the scan skips it) and
  the chunk is re-fetched from the backing store — the caller just
  sees bytes, one fetch slower (chaos-proved: ``runtime/chaos.py``
  ``cache_corruption``).
- **LRU eviction at a byte budget**: after each publish, oldest-read
  entries evict until the cache fits ``byte_budget`` (0 = unbounded);
  hits touch mtime so recency is on-disk state, shared across
  processes on the host.

Bit-identity contract: cached bytes are the exact bytes the store
streamed (tested), so ``RoundFeed``-fed training trajectories are
byte-identical with the cache on or off.

Telemetry: ``sparknet_cache_{hits,misses,evictions,bytes}_total``
through the shared obs registry (PR 4), ``cache_read``/``cache_fetch``
spans (cat ``cache``) on the tracer, and a ``cache_quarantine``
instant per corrupt entry.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import List, Optional, Tuple

from sparknet_tpu import obs
# ONE checksum convention across the framework: the cache's sidecar
# manifests use the same masked-CRC32 helper the snapshot manifests and
# the serving delivery watcher verify with (io/checkpoint.py is
# import-light — the read-only helpers pull no jax).
from sparknet_tpu.io.checkpoint import crc32_bytes

__all__ = [
    "ChunkCache", "CachingStore", "parse_bytes", "atomic_write_bytes",
]

_CHUNK_SUFFIX = ".chunk"
_META_SUFFIX = ".meta.json"

_UNITS = {
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_bytes(spec) -> int:
    """``"512M"``/``"8g"``/``"1073741824"`` -> bytes (0 = unbounded).
    CLI-flag helper for ``--cache_bytes``."""
    if spec is None:
        return 0
    if isinstance(spec, (int, float)):
        return int(spec)
    s = str(spec).strip().lower()
    if not s:
        return 0
    for unit in sorted(_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return int(float(s[: -len(unit)]) * _UNITS[unit])
    return int(float(s))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Publish ``data`` at ``path`` via temp-file + ``os.replace``: a
    kill mid-write never leaves a partial file under the final name
    (the ``io/checkpoint._atomic`` semantics, shared by the cache's
    chunk/manifest publishes and the chaos harness's chunk store —
    kept here because the data plane deliberately avoids importing the
    jax-heavy checkpoint module)."""
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


class CacheCorrupt(RuntimeError):
    """Internal: a cache entry failed its CRC/size verification."""


class ChunkCache:
    """Bounded content-addressed byte cache rooted at a directory.

    ``get(store, name)`` is the fetch-through read: serve verified
    local bytes on a hit, else fetch via ``store.read_with_info`` (the
    retry-hardened object-store path), publish atomically, and serve.
    ``local_path`` additionally pins a verified on-disk path (for
    consumers that need a *file*, e.g. record-DB readers).
    Thread-safe; cross-process safe by construction (atomic renames;
    a double-fetch race publishes identical content twice)."""

    def __init__(self, root: str, byte_budget: int = 0):
        self.root = os.path.abspath(root)
        self.byte_budget = int(byte_budget)
        self._dir = os.path.join(self.root, "objects")
        os.makedirs(self._dir, exist_ok=True)
        # the instance lock guards bookkeeping (stats, pin set, key-lock
        # table, eviction scans) — never a network fetch.  Per-KEY locks
        # serialize work on one entry, so a slow miss on chunk A never
        # blocks a local-disk hit on chunk B.
        self._lock = threading.Lock()
        self._key_locks: dict = {}
        # keys whose on-disk path was handed out via local_path():
        # consumers hold the real file, so LRU eviction must not unlink
        # it from under them (pinned for this instance's lifetime)
        self._pinned: set = set()
        # per-instance accounting (the obs counters are process-wide;
        # benches/tests read these)
        self.stats = {
            "hits": 0, "misses": 0, "evictions": 0, "quarantined": 0,
            "bytes_from_cache": 0, "bytes_fetched": 0,
        }
        # advisory running byte total: publishes add, the (authoritative,
        # rescanning) eviction sweep resyncs it — so a budgeted cold fill
        # scans the objects dir only when actually over budget instead of
        # once per publish (O(N), not O(N^2), in stat calls).  Drift is
        # only ever upward (republish over an existing key), which costs
        # a spurious scan, never a missed eviction.
        self._approx_bytes = self.total_bytes() if self.byte_budget else 0

    def _count(self, stat: str, n: int = 1) -> None:
        with self._lock:
            self.stats[stat] += n

    def _key_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._key_locks.setdefault(key, threading.Lock())

    # -- keying ---------------------------------------------------------
    @staticmethod
    def key_for(url: str, name: str) -> str:
        return hashlib.sha1(
            f"{url}\n{name}".encode("utf-8", "surrogatepass")
        ).hexdigest()

    def _paths(self, key: str) -> Tuple[str, str]:
        return (
            os.path.join(self._dir, key + _CHUNK_SUFFIX),
            os.path.join(self._dir, key + _META_SUFFIX),
        )

    def entry_path(self, url: str, name: str) -> Optional[str]:
        """The published chunk path for (url, name) if cached (chaos /
        forensics seam — not a verified read)."""
        p, _ = self._paths(self.key_for(url, name))
        return p if os.path.exists(p) else None

    # -- verified local read -------------------------------------------
    def _verify(self, chunk_path: str, meta_path: str) -> bytes:
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            want_crc = int(meta["crc32"])
            want_size = int(meta["size"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise CacheCorrupt(f"{meta_path}: unreadable manifest: {e}")
        try:
            with open(chunk_path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CacheCorrupt(f"{chunk_path}: unreadable chunk: {e}")
        if len(data) != want_size:
            raise CacheCorrupt(
                f"{chunk_path}: truncated ({len(data)} bytes, manifest "
                f"says {want_size})"
            )
        crc = crc32_bytes(data)
        if crc != want_crc:
            raise CacheCorrupt(
                f"{chunk_path}: CRC32 mismatch ({crc:#x} vs manifest "
                f"{want_crc:#x})"
            )
        return data

    def _meta(self, key: str) -> Optional[dict]:
        _, meta_path = self._paths(key)
        try:
            with open(meta_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _quarantine(self, key: str, name: str) -> None:
        """Rename a corrupt entry's files ``*.corrupt`` (evidence kept,
        scan skipped) and count it — the snapshot-quarantine contract,
        applied to data chunks."""
        chunk_path = self._paths(key)[0]
        try:
            gone = os.path.getsize(chunk_path)
        except OSError:
            gone = 0
        for p in self._paths(key):
            if os.path.exists(p):
                os.replace(p, p + ".corrupt")
        with self._lock:
            self._approx_bytes = max(0, self._approx_bytes - gone)
        self._count("quarantined")
        obs.instant("cache_quarantine", cat="fault", chunk=name)

    # -- publish --------------------------------------------------------
    def _publish(self, key: str, name: str, url: str, data: bytes,
                 etag: Optional[str]) -> str:
        chunk_path, meta_path = self._paths(key)
        atomic_write_bytes(chunk_path, data)
        # manifest last: a kill between the chunk and here leaves a
        # manifest-less chunk the next read treats as a miss, never a
        # manifest vouching for torn bytes
        meta = {
            "url": url, "name": name, "etag": etag, "size": len(data),
            "crc32": crc32_bytes(data),
        }
        atomic_write_bytes(meta_path, json.dumps(meta).encode())
        with self._lock:
            self._approx_bytes += len(data)
        self._evict_to_budget(keep=key)
        return chunk_path

    # -- eviction -------------------------------------------------------
    def _entries(self) -> List[Tuple[float, int, str]]:
        """(mtime, chunk_bytes, key) per published entry."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return out
        for fname in names:
            if not fname.endswith(_CHUNK_SUFFIX):
                continue
            key = fname[: -len(_CHUNK_SUFFIX)]
            try:
                st = os.stat(os.path.join(self._dir, fname))
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, key))
        return out

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def _evict_to_budget(self, keep: Optional[str] = None) -> None:
        if self.byte_budget <= 0:
            return
        with self._lock:
            if self._approx_bytes <= self.byte_budget:
                return  # cheap common case: no directory scan
            pinned = set(self._pinned)
        entries = sorted(self._entries())  # oldest-read first (LRU)
        total = sum(size for _, size, _ in entries)
        tm = obs.training_metrics()
        for _, size, key in entries:
            if total <= self.byte_budget:
                break
            if key == keep or key in pinned:
                # never evict the entry being served, nor one whose
                # on-disk path local_path() handed to a consumer (a DB
                # reader / staged view holds the real file)
                continue
            for p in self._paths(key):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            total -= size
            self._count("evictions")
            if tm is not None:
                tm.cache_evictions.inc()
        with self._lock:
            self._approx_bytes = total  # resync to the authoritative scan

    def clear(self) -> int:
        """Drop every published entry (the cold-cache chaos fault /
        operator reset); quarantined ``*.corrupt`` files stay for
        forensics.  Returns the number of entries dropped."""
        dropped = 0
        for _, _, key in self._entries():
            for p in self._paths(key):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            dropped += 1
        with self._lock:
            self._approx_bytes = 0
        return dropped

    # -- the fetch-through read ----------------------------------------
    def _fetch(self, store, name: str, url: str,
               key: str) -> Tuple[bytes, str]:
        with obs.span("cache_fetch", cat="cache", chunk=name):
            data, etag = _read_with_info(store, name)
        self._count("bytes_fetched", len(data))
        path = self._publish(key, name, url, data, etag)
        return data, path

    def get(
        self,
        store,
        name: str,
        url: Optional[str] = None,
        etag: Optional[str] = None,
        size: Optional[int] = None,
    ) -> bytes:
        """Fetch-through read: verified cached bytes, or fetch+publish.
        ``etag``/``size``, when the caller knows them, invalidate a
        stale entry (upstream object changed) before it is served."""
        data, _ = self._get_impl(store, name, url, etag, size)
        return data

    def local_path(
        self,
        store,
        name: str,
        url: Optional[str] = None,
        etag: Optional[str] = None,
        size: Optional[int] = None,
    ) -> str:
        """Like ``get`` but returns the verified on-disk chunk path
        (for consumers that need a file: DB readers, mmap).  The entry
        is PINNED against LRU eviction for this cache instance's
        lifetime — the consumer holds the real file, so the budget
        sweep must not unlink it from under them.  Streaming readers
        should use ``get`` (or ``CachingStore.open``) instead: those
        never pin, so the byte budget stays effective."""
        _, path = self._get_impl(store, name, url, etag, size, pin=True)
        return path

    def _get_impl(self, store, name, url, etag, size, pin=False):
        url = url if url is not None else getattr(store, "url", "")
        key = self.key_for(url, name)
        chunk_path, _meta_path = self._paths(key)
        tm = obs.training_metrics()
        # per-KEY serialization: two readers of the same chunk never
        # double-fetch in-process, while a miss on one chunk (network-
        # bound, possibly seconds) never blocks a hit on another.  A
        # pin lands INSIDE this section: between serve and pin no
        # publish-triggered eviction can unlink the served path.
        with obs.span("cache_read", cat="cache", chunk=name):
            with self._key_lock(key):
                if pin:
                    with self._lock:
                        self._pinned.add(key)
                meta = self._meta(key)
                stale = meta is not None and (
                    (etag is not None and meta.get("etag") not in (None, etag))
                    or (size is not None and int(meta.get("size", -1)) != size)
                )
                if meta is not None and not stale:
                    try:
                        data = self._verify(chunk_path, _meta_path)
                        self._count("hits")
                        self._count("bytes_from_cache", len(data))
                        if tm is not None:
                            tm.cache_hits.inc()
                            tm.cache_bytes.labels("hit").inc(len(data))
                        try:  # LRU recency rides the filesystem mtime
                            os.utime(chunk_path)
                        except OSError:
                            pass
                        return data, chunk_path
                    except CacheCorrupt:
                        # quarantine the evidence, then fall through to
                        # a transparent refetch — the caller never sees
                        # the corruption
                        self._quarantine(key, name)
                self._count("misses")
                if tm is not None:
                    tm.cache_misses.inc()
                data, path = self._fetch(store, name, url, key)
                if tm is not None:
                    tm.cache_bytes.labels("miss").inc(len(data))
                return data, path


def _read_with_info(store, name: str):
    """(bytes, etag) through the store's hardened read path.  Stores
    exposing ``read_with_info`` (the HTTP-backed ones) return the
    fetch-time ETag for the entry manifest; anything else degrades to
    ``read`` with no etag."""
    fn = getattr(store, "read_with_info", None)
    if fn is not None:
        return fn(name)
    return store.read(name), None


class CachingStore:
    """An ``ObjectStore`` wrapper that serves ``open``/``read`` through
    a ``ChunkCache``.  Listings stay live (cheap, freshness matters);
    object bytes are cached.  Drop-in: same duck-typed surface
    ``ImageNetLoader`` consumes."""

    def __init__(self, inner, cache: ChunkCache):
        self.inner = inner
        self.cache = cache
        self.url = getattr(inner, "url", "")

    def list(self, prefix: str = ""):
        return self.inner.list(prefix)

    def open(self, name: str):
        """A binary stream over the verified cached bytes.  Served from
        memory (``get``), NOT from a pinned file path: the tar-
        streaming hot path must leave the LRU byte budget effective —
        ``local_path`` pins, ``open`` must not."""
        import io as _io

        return _io.BytesIO(self.read(name))

    def read(self, name: str) -> bytes:
        return self.cache.get(self.inner, name, url=self.url)

    def read_with_info(self, name: str):
        data = self.read(name)
        meta = self.cache._meta(self.cache.key_for(self.url, name)) or {}
        return data, meta.get("etag")

    def local_path(self, name: str) -> str:
        return self.cache.local_path(self.inner, name, url=self.url)
