"""Real-data resolution for eval/featurization entry points.

The reference's ``caffe test`` scores whatever the net's data layers read
(LMDB sources in the prototxt); SparkNet's FeaturizerApp pulls real
minibatches from the RDD (``FeaturizerApp.scala:88-103``).  This module is
the equivalent resolver: given a net and an optional ``--data`` argument,
produce real stacked batches from

1. a CIFAR binary directory (``data_batch_*.bin`` / ``test_batch.bin``),
2. a native SNDB record DB — either named explicitly or found in the
   net's own ``Data`` layer ``data_param.source`` — with the layer's
   ``transform_param`` (mean_file/mean_value, crop, scale, mirror)
   applied, like the engine's DataLayer+DataTransformer would,
3. synthetic random batches only as an explicit last resort
   (``allow_synthetic=True``), with a loud warning — scoring noise is not
   an evaluation.
"""

from __future__ import annotations

import math
import os
import sys
from typing import Dict, Optional

import numpy as np


def synthetic_batches(net, iterations: int, seed: int = 0):
    """Random batches matching the net's feed shapes (labels in [0, 10))
    — the smoke-test generator shared with ``cli time``."""
    rng = np.random.RandomState(seed)
    out = {}
    for blob in net.feed_blobs:
        shape = net.blob_shapes[blob]
        if "label" in blob:
            out[blob] = rng.randint(
                0, 10, (iterations,) + tuple(shape)
            ).astype(np.float32)
        else:
            out[blob] = rng.randn(iterations, *shape).astype(np.float32)
    return out


def _cifar_batches(data_dir, net, iterations, phase, seed):
    from sparknet_tpu.data.cifar import CifarLoader

    feed = net.feed_blobs
    batch = net.blob_shapes[feed[0]][0]
    loader = CifarLoader(data_dir, seed=seed)
    x, y = loader.minibatches(batch, train=(phase == "TRAIN"))
    if len(x) == 0:
        raise ValueError(f"no full minibatches of {batch} in {data_dir}")
    idx = [i % len(x) for i in range(iterations)]
    out = {feed[0]: np.stack([x[i] for i in idx])}
    if len(feed) > 1:
        out[feed[1]] = np.stack([y[i] for i in idx])
    return out


def _phase_layer(netp, phase, type_name, predicate):
    """First layer of ``type_name`` satisfying ``predicate`` in the
    phase's view, using the real NetState rule filtering
    (include/exclude/legacy phase — graph.filter_net)."""
    from sparknet_tpu.config.schema import NetState
    from sparknet_tpu.graph import filter_net

    filtered = filter_net(netp, NetState(phase=phase.upper()))
    for lp in filtered.layer:
        if lp.type == type_name and predicate(lp):
            return lp
    return None


def _db_layer(netp, phase):
    """The phase's Data layer with a DB source."""
    return _phase_layer(
        netp, phase, "Data", lambda lp: lp.data_param and lp.data_param.source
    )


def _hdf5_layer(netp, phase):
    """The phase's HDF5Data layer (``hdf5_data_layer.cpp`` role)."""
    return _phase_layer(
        netp, phase, "HDF5Data", lambda lp: lp.hdf5_data_param is not None
    )


def _image_layer(netp, phase):
    """The phase's ImageData layer (``image_data_layer.cpp`` role)."""
    return _phase_layer(
        netp,
        phase,
        "ImageData",
        lambda lp: lp.image_data_param and lp.image_data_param.source,
    )


def _image_batches(lp, net, iterations, phase, seed):
    """Batches from an ImageData listfile: load + optional force-resize
    (new_height/new_width), shuffle when asked, then the standard
    DataTransformer (crop/mirror/mean/scale) — ``image_data_layer.cpp``
    load_batch semantics, cycled when iterations overrun the list."""
    from PIL import Image

    from sparknet_tpu.data.transformer import DataTransformer
    from sparknet_tpu.io import caffemodel

    p = lp.image_data_param
    entries = []
    with open(p.source) as f:
        for line in f:
            line = line.strip()
            if line:
                name, label = line.rsplit(None, 1)
                entries.append((name, int(label)))
    if not entries:
        raise ValueError(f"ImageData source {p.source!r} lists no images")
    if p.shuffle and phase == "TRAIN":
        np.random.RandomState(seed).shuffle(entries)
    if p.rand_skip:
        skip = np.random.RandomState(seed).randint(p.rand_skip)
        entries = entries[skip:] + entries[:skip]

    # effective transform: merge the legacy ImageDataParameter copies
    # into transform_param fields (SAME precedence declared_shapes uses,
    # so the served shape always matches the declared one)
    from sparknet_tpu.config.schema import TransformationParameter

    tp = lp.transform_param or TransformationParameter()
    eff = TransformationParameter(
        crop_size=tp.crop_size or p.crop_size,
        mirror=bool(tp.mirror) or bool(p.mirror),
        scale=tp.scale if tp.scale != 1.0 else p.scale,
        mean_value=list(tp.mean_value),
    )
    mean = None
    if tp.mean_file:
        mean = caffemodel.load_mean_image(tp.mean_file)
    elif p.mean_file:  # legacy location on ImageDataParameter
        mean = caffemodel.load_mean_image(p.mean_file)
    transformer = DataTransformer(
        eff, phase=phase, mean_image=mean, seed=seed
    )

    if bool(p.new_height) != bool(p.new_width):
        # the reference CHECKs both-or-neither (image_data_layer.cpp)
        raise ValueError(
            "ImageData: new_height and new_width must be set together"
        )

    # decode lazily: only the entries the requested batches will touch
    # (real listfiles are tens of thousands of images; a short eval must
    # not decode them all).  Cache only when batches actually cycle —
    # otherwise each entry is touched once and caching is pure memory.
    batch = int(p.batch_size)
    n = len(entries)
    decoded = {}
    cache = iterations * batch > n

    def image(j):
        if j in decoded:
            return decoded[j]
        name, _ = entries[j]
        img = Image.open(os.path.join(p.root_folder, name))
        img = img.convert("RGB" if p.is_color else "L")
        if p.new_height and p.new_width:
            img = img.resize((p.new_width, p.new_height), Image.BILINEAR)
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = np.ascontiguousarray(arr.transpose(2, 0, 1))
        if cache:
            decoded[j] = arr
        return arr

    tops = list(lp.top)
    xs, ys = [], []
    for i in range(iterations):
        idx = np.arange(i * batch, (i + 1) * batch) % n
        imgs = [image(j) for j in idx]
        shapes = {im.shape for im in imgs}
        if len(shapes) > 1:
            # variable-size images are fine when a crop unifies them
            # (the reference crops each cv::Mat individually); a mean
            # IMAGE cannot align to varying sizes, mean_value can
            if not eff.crop_size:
                raise ValueError(
                    f"ImageData source {p.source!r} mixes image sizes "
                    f"{shapes}; set new_height/new_width or a crop_size"
                )
            if mean is not None:
                raise ValueError(
                    "ImageData: mean_file needs uniform image sizes; "
                    "use mean_value or new_height/new_width"
                )
            xs.append(
                np.concatenate([transformer(im[None]) for im in imgs])
            )
        else:
            xs.append(transformer(np.stack(imgs)))
        ys.append(
            np.asarray([entries[j][1] for j in idx], np.float32)
        )
    out = {tops[0]: np.stack(xs)}
    if len(tops) > 1:
        out[tops[1]] = np.stack(ys)
    return out


def _window_layer(netp, phase):
    """The phase's WindowData layer (``window_data_layer.cpp`` role)."""
    return _phase_layer(
        netp,
        phase,
        "WindowData",
        lambda lp: lp.window_data_param and lp.window_data_param.source,
    )


def _window_batches(lp, net, iterations, phase, seed):
    from sparknet_tpu.data.windows import (
        WindowSampler,
        effective_window_params,
    )

    crop, mirror, scale, mean_file, mean_value = effective_window_params(lp)
    mean = None
    if mean_file:
        from sparknet_tpu.io import caffemodel

        mean = caffemodel.load_mean_image(mean_file)
    elif mean_value:
        mean = np.asarray(mean_value, np.float32)
    sampler = WindowSampler(
        lp.window_data_param,
        mean=mean,
        phase=phase,
        seed=seed,
        crop_size=crop,
        mirror=mirror,
        scale=scale,
    )
    xs, ys = [], []
    for _ in range(iterations):
        x, y = sampler.next_batch()
        xs.append(x)
        ys.append(y)
    # keyed by the layer's own tops, not feed_blobs order (another
    # host-fed layer may come first in the net)
    tops = list(lp.top)
    out = {tops[0]: np.stack(xs)}
    if len(tops) > 1:
        out[tops[1]] = np.stack(ys)
    return out


def _hdf5_batches(source, tops, shuffle, net, iterations, phase, seed):
    """Stacked batches from .h5 files whose datasets are named by the
    layer tops — concatenated across the listed files, shuffled for
    TRAIN when the layer asks (``HDF5DataLayer::Next`` semantics),
    cycled when iterations overrun the data."""
    import h5py

    from sparknet_tpu.ops.data_layers import hdf5_source_files

    files = hdf5_source_files(source)
    if not files:
        raise ValueError(f"HDF5 source {source!r} lists no files")
    parts = {top: [] for top in tops}
    for fp in files:
        with h5py.File(fp, "r") as h:
            rows = None
            for top in tops:
                if top not in h:
                    raise KeyError(f"{fp} has no dataset {top!r}")
                arr = np.asarray(h[top])
                # the reference CHECKs this per file (LoadHDF5FileData)
                if rows is not None and len(arr) != rows:
                    raise ValueError(
                        f"{fp}: dataset {top!r} has {len(arr)} rows, "
                        f"{tops[0]!r} has {rows}"
                    )
                rows = len(arr)
                parts[top].append(arr)
    arrays = {
        top: np.concatenate(p) if len(p) > 1 else p[0]
        for top, p in parts.items()
    }
    n = len(arrays[tops[0]])
    # the batch of the layer actually being served, not feed_blobs[0]
    # (another host-fed layer may come first in the net)
    batch = net.blob_shapes[tops[0]][0]
    if n < batch:
        raise ValueError(f"HDF5 source has {n} rows < batch {batch}")
    order = np.arange(n)
    if shuffle and phase == "TRAIN":
        np.random.RandomState(seed).shuffle(order)
    idx = [
        np.arange(i * batch, (i + 1) * batch) % n for i in range(iterations)
    ]
    out = {}
    for top in tops:
        shuffled = arrays[top][order]
        out[top] = np.stack([shuffled[i].astype(np.float32) for i in idx])
    return out


def _record_shape(db_path, channels, h, w):
    """(C, H, W) of the stored records.  The net only knows the post-crop
    shape; cross-check against the DB's record size and fall back to a
    square stored image when they disagree (Datum records are 1 label byte
    + C*H*W image bytes)."""
    from sparknet_tpu import runtime

    with runtime.RecordDB(db_path, "r") as db:
        if len(db) == 0:
            raise IOError(f"empty db {db_path}")
        total = len(db.read(0)[1])
    for label_w in (1, 2):  # records carry a 1- or 2-byte label
        nbytes = total - label_w
        if nbytes == channels * h * w:
            return channels, h, w
        side = math.isqrt(max(0, nbytes // channels))
        if side and channels * side * side == nbytes:
            return channels, side, side
    raise ValueError(
        f"db {db_path} records carry {total} bytes; neither "
        f"{channels}x{h}x{w} nor a square {channels}-channel image "
        "(with a 1- or 2-byte label)"
    )


def _db_batches(source, transform_param, net, iterations, phase, seed):
    from sparknet_tpu import runtime
    from sparknet_tpu.io import caffemodel, lmdb

    if lmdb.is_lmdb(source):
        # reference-created dataset (backend: LMDB): one-time import into
        # the native record format, then the normal pipeline applies
        source = lmdb.lmdb_to_record_db(source)
    else:
        from sparknet_tpu.io import leveldb

        if leveldb.is_leveldb(source):
            # backend: LEVELDB (Caffe's default) — same one-time import
            source = leveldb.leveldb_to_record_db(source)

    feed = net.feed_blobs
    shape = net.blob_shapes[feed[0]]
    batch, (c, h, w) = shape[0], tuple(shape[1:])
    tp = transform_param
    crop = int(tp.crop_size) if tp is not None else 0
    mean = None
    if tp is not None and tp.mean_file:
        mean = caffemodel.load_mean_image(tp.mean_file)
    elif tp is not None and tp.mean_value:
        mean = np.asarray(tp.mean_value, np.float32)
    rec_shape = _record_shape(source, c, h, w) if not crop else None
    if rec_shape is None:
        # crop_size given: stored records are pre-crop; infer from the DB
        rec_shape = _record_shape(source, c, 0, 0)
    pipe = runtime.DataPipeline(
        source,
        batch_size=batch,
        shape=rec_shape,
        crop=crop,
        mirror=bool(tp.mirror) if tp is not None else False,
        train=(phase == "TRAIN"),
        scale=float(tp.scale) if tp is not None else 1.0,
        mean=mean,
        seed=seed,
    )
    try:
        xs, ys = [], []
        for _ in range(iterations):
            x, y = pipe.next()
            xs.append(x)
            ys.append(y)
    finally:
        pipe.close()
    out = {feed[0]: np.stack(xs)}
    if len(feed) > 1:
        out[feed[1]] = np.stack(ys).astype(np.float32)
    return out


def resolve_batches(
    net,
    netp,
    data: Optional[str],
    iterations: int,
    phase: str = "TEST",
    seed: int = 0,
    allow_synthetic: bool = False,
) -> Dict[str, np.ndarray]:
    """Stacked real batches {feed_blob: (iterations, batch, ...)} for
    ``net`` — see module docstring for the source precedence."""
    db_lp = _db_layer(netp, phase) if netp is not None else None
    h5_lp = _hdf5_layer(netp, phase) if netp is not None else None
    if data and h5_lp is not None and data.endswith((".h5", ".hdf5", ".txt")):
        # a net fed by HDF5Data routes .h5/listfile --data through it
        return _hdf5_batches(
            data, list(h5_lp.top), bool(h5_lp.hdf5_data_param.shuffle),
            net, iterations, phase, seed,
        )
    if data:
        if os.path.isdir(data):
            import glob

            from sparknet_tpu.io import lmdb

            from sparknet_tpu.io import leveldb

            if lmdb.is_lmdb(data) or leveldb.is_leveldb(data):
                tp = db_lp.transform_param if db_lp is not None else None
                return _db_batches(data, tp, net, iterations, phase, seed)
            has_cifar = glob.glob(
                os.path.join(data, "data_batch_*.bin")
            ) or os.path.exists(os.path.join(data, "test_batch.bin"))
            if not has_cifar:
                raise ValueError(
                    f"--data={data!r} is a directory without CIFAR binary "
                    "batches (data_batch_*.bin / test_batch.bin) and not an "
                    "LMDB or LevelDB; supported forms: a CIFAR binary dir, "
                    "a Caffe LMDB or LevelDB, a record-DB file path, or a "
                    "net with data_param.source"
                )
            return _cifar_batches(data, net, iterations, phase, seed)
        if os.path.exists(data):
            # explicit DB file: still honor the net's transform_param so
            # eval preprocessing matches training
            tp = db_lp.transform_param if db_lp is not None else None
            return _db_batches(data, tp, net, iterations, phase, seed)
        raise FileNotFoundError(data)
    if db_lp is not None:
        return _db_batches(
            db_lp.data_param.source,
            db_lp.transform_param,
            net,
            iterations,
            phase,
            seed,
        )
    if h5_lp is not None and h5_lp.hdf5_data_param.source:
        return _hdf5_batches(
            h5_lp.hdf5_data_param.source,
            list(h5_lp.top),
            bool(h5_lp.hdf5_data_param.shuffle),
            net,
            iterations,
            phase,
            seed,
        )
    win_lp = _window_layer(netp, phase) if netp is not None else None
    if win_lp is not None:
        return _window_batches(win_lp, net, iterations, phase, seed)
    img_lp = _image_layer(netp, phase) if netp is not None else None
    if img_lp is not None:
        return _image_batches(img_lp, net, iterations, phase, seed)
    if not allow_synthetic:
        raise ValueError(
            "no data source: pass --data=DIR|DB or give the net a Data "
            "layer with data_param.source"
        )
    print(
        "WARNING: no data source — scoring SYNTHETIC random batches "
        "(pass --data for a real evaluation)",
        file=sys.stderr,
    )
    return synthetic_batches(net, iterations, seed)
