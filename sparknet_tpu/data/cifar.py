"""CIFAR-10 binary reader (reference: ``src/main/scala/loaders/CifarLoader
.scala``).

File format: each record is 1 label byte + 3072 image bytes (3 planes of
32x32, R then G then B).  Train files ``data_batch_{1..5}.bin`` (10k records
each), test file ``test_batch.bin``.  Like the reference, loading shuffles
the train set with a fixed permutation and computes the mean image.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

RECORD_BYTES = 1 + 3 * 32 * 32


def _read_file(path: str) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % RECORD_BYTES:
        raise ValueError(f"{path}: size {raw.size} not a multiple of {RECORD_BYTES}")
    raw = raw.reshape(-1, RECORD_BYTES)
    labels = raw[:, 0].astype(np.int32)
    images = raw[:, 1:].reshape(-1, 3, 32, 32)  # planar RGB, NCHW
    return images, labels


class CifarLoader:
    """Loads train+test splits, shuffles train, computes the train mean
    image (CifarLoader.scala:52-63)."""

    def __init__(self, data_dir: str, seed: int = 0, num_train_files: int = 5):
        train_images: List[np.ndarray] = []
        train_labels: List[np.ndarray] = []
        for i in range(1, num_train_files + 1):
            path = os.path.join(data_dir, f"data_batch_{i}.bin")
            if not os.path.exists(path):
                raise FileNotFoundError(path)
            im, lb = _read_file(path)
            train_images.append(im)
            train_labels.append(lb)
        self.train_images = np.concatenate(train_images)
        self.train_labels = np.concatenate(train_labels)
        perm = np.random.RandomState(seed).permutation(len(self.train_labels))
        self.train_images = self.train_images[perm]
        self.train_labels = self.train_labels[perm]
        test_path = os.path.join(data_dir, "test_batch.bin")
        if os.path.exists(test_path):
            self.test_images, self.test_labels = _read_file(test_path)
        else:
            self.test_images = np.zeros((0, 3, 32, 32), np.uint8)
            self.test_labels = np.zeros((0,), np.int32)
        # float mean image over the train split
        self.mean_image = self.train_images.astype(np.float64).mean(axis=0).astype(
            np.float32
        )

    @staticmethod
    def write_synthetic(
        data_dir: str,
        num_train: int = 1000,
        num_test: int = 200,
        seed: int = 0,
        separable: bool = True,
    ) -> None:
        """Write synthetic CIFAR-format files (for tests/benchmarks without
        the dataset; the class-dependent mean shift makes the task learnable
        when ``separable``)."""
        os.makedirs(data_dir, exist_ok=True)
        rng = np.random.RandomState(seed)

        def make(n):
            labels = rng.randint(0, 10, n).astype(np.uint8)
            images = rng.randint(0, 120, (n, 3, 32, 32)).astype(np.uint8)
            if separable:
                for c in range(10):
                    mask = labels == c
                    images[mask, c % 3] = np.minimum(
                        images[mask, c % 3] + 40 + 8 * c, 255
                    )
            return images, labels

        per_file = max(1, num_train // 5)
        for i in range(1, 6):
            images, labels = make(per_file)
            rec = np.concatenate(
                [labels[:, None], images.reshape(per_file, -1)], axis=1
            ).astype(np.uint8)
            rec.tofile(os.path.join(data_dir, f"data_batch_{i}.bin"))
        images, labels = make(num_test)
        rec = np.concatenate(
            [labels[:, None], images.reshape(num_test, -1)], axis=1
        ).astype(np.uint8)
        rec.tofile(os.path.join(data_dir, "test_batch.bin"))

    def minibatches(
        self,
        batch_size: int,
        train: bool = True,
        mean_subtract: bool = True,
        scale: float = 1.0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Pack the split into fixed-size minibatch arrays, dropping the
        ragged tail (ScaleAndConvert.scala:45-70 semantics).  Returns
        (num_batches, B, 3, 32, 32) float32 and (num_batches, B) labels."""
        images = self.train_images if train else self.test_images
        labels = self.train_labels if train else self.test_labels
        n = (len(labels) // batch_size) * batch_size
        x = images[:n].astype(np.float32)
        if mean_subtract:
            x = x - self.mean_image[None]
        if scale != 1.0:
            x = x * scale
        x = x.reshape(-1, batch_size, 3, 32, 32)
        y = labels[:n].astype(np.float32).reshape(-1, batch_size)
        return x, y
