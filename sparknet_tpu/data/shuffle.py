"""Cross-epoch shuffle-by-assignment: reshuffle WHO reads WHAT, not bytes.

SparkNet's data plane kept an RDD of pre-built minibatches resident
across iterations (PAPER.md L7); a reshuffle between epochs was a Spark
repartition — lineage metadata moved, the cached partitions mostly did
not.  The TPU rewrite streams shards straight off object stores
(``data/object_store.py``), so a naive cross-epoch reshuffle re-streams
*bytes*: every worker re-downloads a fresh partition each epoch and a
multi-epoch run's network cost is workers x epochs (ROADMAP item 5).

This module is the metadata half of the fix (``chunk_cache.py`` is the
byte half): a **seeded assignment service** that maps shards (or any
item list) to workers as a pure function of ``(seed, epoch)``.  A
global reshuffle between epochs moves only this assignment table — a
permutation of indices, bytes(table) ~ O(shards) — while the actual
shard bytes stay wherever the host-local chunk cache already has them.
On a single host every post-epoch-0 read is a cache hit regardless of
which worker the shard moved to; on a pod, only shards whose owner
changed *hosts* refetch (and ``assignment`` deals a seeded permutation
round-robin, so consecutive epochs move ~(1 - 1/W) of assignments —
the statistics of a full shuffle — while the cache bounds the bytes).

Determinism/resume contract: every function here is a pure function of
its arguments — no process state, no RNG objects to checkpoint.  A run
resumed at absolute round r recomputes ``epoch = r // rounds_per_epoch``
and gets the exact assignment the pre-preemption run used; replayed
rounds re-draw identically (the same property the chaos harness pins
for ``FaultPlan``).
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List, Sequence, TypeVar

T = TypeVar("T")

__all__ = [
    "permutation",
    "assign",
    "ShuffleByAssignment",
]


def _rng(seed: int, epoch: int) -> random.Random:
    # platform-stable seeding: hash the (seed, epoch) pair through
    # sha256 so nearby seeds/epochs decorrelate fully (Random(seed+epoch)
    # would alias (0,1) with (1,0)) and the draw is identical across
    # interpreters/hosts — every worker derives the same table locally,
    # no broadcast needed
    digest = hashlib.sha256(
        f"sparknet-shuffle:{int(seed)}:{int(epoch)}".encode()
    ).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def permutation(n: int, seed: int, epoch: int) -> List[int]:
    """A seeded permutation of ``range(n)``, pure in ``(seed, epoch)``.
    Epoch boundaries re-deal the whole order; the same (seed, epoch)
    always yields the same table (resume-aware by construction)."""
    idx = list(range(int(n)))
    _rng(seed, epoch).shuffle(idx)
    return idx


def assign(
    items: Sequence[T], num_workers: int, seed: int = 0, epoch: int = 0
) -> List[List[T]]:
    """Deal a seeded permutation of ``items`` round-robin over
    ``num_workers`` — the per-epoch ownership table.  Matches the
    legacy ``shards[w::n]`` split in *shape* (worker partition sizes
    differ by at most one) while re-drawing *membership* each epoch."""
    if num_workers <= 0:
        raise ValueError(f"num_workers must be positive, got {num_workers}")
    order = [items[i] for i in permutation(len(items), seed, epoch)]
    return [order[w::num_workers] for w in range(num_workers)]


class ShuffleByAssignment:
    """The cross-epoch shuffle service over a fixed item list.

    Holds the (sorted, deterministic) item list once; every epoch's
    assignment is derived on demand — nothing to persist, nothing to
    broadcast.  ``moved(e0, e1)`` counts ownership changes between two
    epochs: that count (times ~bytes/shard) is the network cost a
    byte-moving reshuffle would have paid and the cache+assignment
    design does not."""

    def __init__(
        self, items: Sequence[T], num_workers: int, seed: int = 0
    ):
        if not items:
            raise ValueError("ShuffleByAssignment needs a non-empty item list")
        self.items: List[T] = list(items)
        self.num_workers = int(num_workers)
        self.seed = int(seed)
        if self.num_workers <= 0:
            raise ValueError(
                f"num_workers must be positive, got {num_workers}"
            )

    def assignment(self, epoch: int) -> List[List[T]]:
        """Per-worker item lists for ``epoch`` (pure in (seed, epoch))."""
        return assign(self.items, self.num_workers, self.seed, epoch)

    def worker_items(self, epoch: int, worker: int) -> List[T]:
        return self.assignment(epoch)[worker]

    def table(self, epoch: int) -> Dict[T, int]:
        """The ownership table ``item -> worker`` — the ONLY thing a
        global reshuffle moves."""
        out: Dict[T, int] = {}
        for w, part in enumerate(self.assignment(epoch)):
            for item in part:
                out[item] = w
        return out

    def moved(self, epoch_a: int, epoch_b: int) -> int:
        """How many items changed owner between two epochs (what a
        byte-moving reshuffle would re-stream; the assignment service
        moves only the table)."""
        ta, tb = self.table(epoch_a), self.table(epoch_b)
        return sum(1 for item, w in ta.items() if tb[item] != w)
