"""Host-side data plane.

Replaces the reference's two data paths with one idiomatic TPU pattern:

- RDD-of-minibatches + callback pull (``MinibatchSampler.scala``,
  ``JavaDataLayer``)  ->  per-host iterators yielding ready numpy batches,
  stacked tau-deep per averaging round and pushed to device.
- DB path (LevelDB/LMDB + ``DataReader`` + ``BasePrefetchingDataLayer``)  ->
  the same prefetch thread + bounded-queue double-buffering here; the
  record-DB storage itself ships with the native runtime component.
"""

from sparknet_tpu.data.cifar import CifarLoader  # noqa: F401
from sparknet_tpu.data.chunk_cache import (  # noqa: F401
    CachingStore,
    ChunkCache,
)
from sparknet_tpu.data import shuffle  # noqa: F401
from sparknet_tpu.data.imagenet import (  # noqa: F401
    ImageNetLoader,
    ScaleAndConvert,
    compute_mean,
    reduce_mean_sums,
    write_synthetic_imagenet,
)
from sparknet_tpu.data.sampler import MinibatchSampler  # noqa: F401
from sparknet_tpu.data.transformer import DataTransformer  # noqa: F401
from sparknet_tpu.data import transforms  # noqa: F401
from sparknet_tpu.data.prefetch import (  # noqa: F401
    Prefetcher,
    PrefetchStall,
    device_prefetch,
)
from sparknet_tpu.data.round_feed import RoundFeed, stack_windows  # noqa: F401
from sparknet_tpu.data.text import (  # noqa: F401
    ByteTokenizer,
    TextWindowSampler,
    load_corpus,
    write_synthetic_corpus,
)
