"""ImageNet ingestion: shard listing, label map, tar streaming, JPEG
decode/force-resize with corrupt-image dropping, minibatch packing, and
streaming mean-image computation.

Reference roles covered (TPU-first redesign, not a translation):

- ``ImageNetLoader`` (``src/main/scala/loaders/ImageNetLoader.scala:25-86``):
  S3 object listing -> filesystem/glob shard listing (the storage role; a
  TPU-VM pod reads from NFS/GCS-fuse mounts, so "bucket" generalizes to any
  mounted path); ``train.txt`` filename->label map (``:41-54``); tar-stream
  flatMap -> ``tarfile`` streaming per shard (``:56-86``).
- ``ScaleAndConvert`` (``src/main/scala/preprocessing/ScaleAndConvert.scala:
  16-91``): ImageIO+thumbnailator force-resize -> PIL decode + force-resize,
  corrupt images dropped, partitions packed into fixed-size minibatches with
  ragged tails dropped.
- ``ComputeMean`` (``src/main/scala/preprocessing/ComputeMean.scala:40-76``):
  per-partition integer-accumulator sums reduced elementwise then divided —
  here a streaming int64 accumulator that never materializes the dataset,
  with a partition-wise variant whose partial sums are reduced exactly like
  the reference's ``RDD.reduce``.

Deliberate design delta: minibatches stay **uint8 at full size** (e.g.
256x256). Random-crop / mirror / mean-subtraction run on-device inside the
jitted train step (``sparknet_tpu.data.transforms``) — the reference's
per-pixel JVM preprocessing closures (``ImageNetApp.scala:128-180``) are a
host bottleneck this framework moves to the TPU, and uint8 feeds quarter
the host->device transfer bytes.
"""

from __future__ import annotations

import io
import os
import tarfile
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ImageNetLoader",
    "ScaleAndConvert",
    "compute_mean",
    "reduce_mean_sums",
    "write_synthetic_imagenet",
]


class ImageNetLoader:
    """Lists data shards under a root path and streams (jpeg_bytes, label)
    pairs out of tar shards or loose image files.

    The reference's S3 bucket becomes ``root`` (any mounted filesystem);
    ``prefix`` filtering matches its ListObjects-with-prefix semantics, so
    ``loader.load_shards("train.0000")`` selects the same 10-of-1000 shard
    subset the reference app selects (``ImageNetApp.scala:60-63``).
    """

    IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp")

    def __init__(
        self,
        root: str,
        cache_dir: Optional[str] = None,
        cache_bytes: int = 0,
    ):
        self.root = root
        # ``root`` may be a bucket/HTTP url — shards then stream over the
        # network (ImageNetLoader.scala:25-54 semantics).  With
        # ``cache_dir`` the store is fronted by the host-local content-
        # addressed chunk cache (``data/chunk_cache.py``): epoch 1 fills
        # it, epoch 2+ reads local disk — multi-epoch runs go I/O-flat
        # instead of I/O-linear in epochs (ROADMAP item 5).
        from sparknet_tpu.data import object_store

        self._store = (
            object_store.open_store(root)
            if object_store.is_object_store_url(root)
            else None
        )
        self.cache = None
        if self._store is not None and cache_dir:
            from sparknet_tpu.data import chunk_cache

            self.cache = chunk_cache.ChunkCache(
                cache_dir, byte_budget=cache_bytes
            )
            self._store = chunk_cache.CachingStore(self._store, self.cache)

    # -- shard listing (getFilePathsRDD analog) -------------------------
    def list_shards(self, prefix: str = "") -> List[str]:
        """All tar shards (or loose images) whose path relative to root
        starts with ``prefix``, sorted for determinism."""
        if self._store is not None:
            return [
                n
                for n in self._store.list(prefix)
                if n.endswith(".tar") or n.lower().endswith(self.IMAGE_EXTS)
            ]
        out: List[str] = []
        for dirpath, _, files in os.walk(self.root):
            for fname in files:
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, self.root)
                if not rel.startswith(prefix):
                    continue
                if fname.endswith(".tar") or fname.lower().endswith(
                    self.IMAGE_EXTS
                ):
                    out.append(full)
        return sorted(out)

    # -- label map (getLabels analog) -----------------------------------
    def load_labels(self, labels_path: str) -> Dict[str, int]:
        """Parse ``train.txt``-format lines ("<path> <label>") into a
        basename->label map (ImageNetLoader.scala:41-54)."""
        if self._store is not None:
            lines = self._store.read(labels_path).decode().splitlines()
        else:
            with open(os.path.join(self.root, labels_path), "r") as f:
                lines = f.read().splitlines()
        labels: Dict[str, int] = {}
        for line in lines:
            parts = line.split()  # any whitespace (tabs, runs of spaces)
            if not parts:
                continue
            fpath, label = parts[0], parts[-1]
            labels[os.path.basename(fpath)] = int(label)
        return labels

    # -- tar streaming (loadImagesFromTar analog) -----------------------
    def iter_shard(
        self, shard_path: str, labels: Dict[str, int]
    ) -> Iterator[Tuple[bytes, int]]:
        """Stream (image_bytes, label) out of one shard. Tar entries and
        loose files are keyed into the label map by basename; files absent
        from the map are dropped (the reference would throw — dropping keeps
        a partial label file usable, and corrupt-entry dropping is already
        the ScaleAndConvert contract)."""
        if shard_path.endswith(".tar"):
            if self._store is not None:
                # sequential streaming decode off the network socket —
                # the TarArchiveInputStream(getObjectContent) analog
                stream = self._store.open(shard_path)
                tar = tarfile.open(fileobj=stream, mode="r|*")
            else:
                stream = None
                tar = tarfile.open(shard_path, "r")
            with tar:
                for entry in tar:
                    if not entry.isfile():
                        continue
                    name = os.path.basename(entry.name)
                    if name not in labels:
                        continue
                    f = tar.extractfile(entry)
                    if f is None:
                        continue
                    yield f.read(), labels[name]
            if stream is not None:
                stream.close()
        else:
            name = os.path.basename(shard_path)
            if name in labels:
                if self._store is not None:
                    yield self._store.read(shard_path), labels[name]
                else:
                    with open(shard_path, "rb") as f:
                        yield f.read(), labels[name]

    # -- partitioned load (the RDD role) --------------------------------
    def partitions(
        self,
        prefix: str,
        labels_path: str,
        num_parts: Optional[int] = None,
        epoch: Optional[int] = None,
        shuffle_seed: int = 0,
    ) -> List[Iterator[Tuple[bytes, int]]]:
        """Shards dealt into ``num_parts`` lazy partitions (the
        reference parallelizes one partition per shard by default).

        With ``epoch=None`` (the default) the deal is the legacy
        round-robin ``shards[worker::n]``.  With an epoch index, shard
        ownership comes from the cross-epoch shuffle-by-assignment
        service (``data/shuffle.py``): a seeded permutation pure in
        ``(shuffle_seed, epoch)`` — a global reshuffle between epochs
        moves only this assignment table, and with a chunk cache in
        front repeat reads never touch the network."""
        shards = self.list_shards(prefix)
        if not shards:
            raise FileNotFoundError(
                f"no shards under {self.root!r} matching prefix {prefix!r}"
            )
        labels = self.load_labels(labels_path)
        n = num_parts or len(shards)
        if epoch is None:
            assignment = [shards[w::n] for w in range(n)]
        else:
            from sparknet_tpu.data import shuffle

            assignment = shuffle.assign(
                shards, n, seed=shuffle_seed, epoch=epoch
            )

        def part(worker: int) -> Iterator[Tuple[bytes, int]]:
            for shard in assignment[worker]:
                yield from self.iter_shard(shard, labels)

        return [part(w) for w in range(n)]


class ScaleAndConvert:
    """JPEG decode + force-resize + minibatch packing.

    ``convert_image`` mirrors ``ScaleAndConvert.convertImage``
    (ScaleAndConvert.scala:16-27): force-resize to (width, height) with no
    aspect preservation, corrupt/unreadable images -> None (dropped).
    ``make_minibatches`` mirrors ``makeMinibatchRDDWithCompression``
    (``:45-70``): fixed-size batches per partition, ragged tail dropped.
    """

    def __init__(self, batch_size: int, height: int, width: int):
        self.batch_size = batch_size
        self.height = height
        self.width = width

    def convert_image(self, data: bytes) -> Optional[np.ndarray]:
        """(3, H, W) uint8 planar RGB, or None for images that cannot be
        decoded (the corrupt-drop contract)."""
        try:
            from PIL import Image

            with Image.open(io.BytesIO(data)) as im:
                im = im.convert("RGB").resize(
                    (self.width, self.height), Image.BILINEAR
                )
                arr = np.asarray(im, dtype=np.uint8)  # (H, W, 3)
        except Exception:
            return None
        return np.ascontiguousarray(arr.transpose(2, 0, 1))

    def make_minibatches(
        self, pairs: Iterable[Tuple[bytes, int]]
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Pack a partition's (bytes, label) stream into
        ((B, 3, H, W) uint8, (B,) int32) minibatches; drop the ragged
        tail exactly like the reference."""
        images: List[np.ndarray] = []
        labels: List[int] = []
        for data, label in pairs:
            arr = self.convert_image(data)
            if arr is None:
                continue
            images.append(arr)
            labels.append(label)
            if len(images) == self.batch_size:
                yield np.stack(images), np.asarray(labels, np.int32)
                images, labels = [], []
        # ragged tail dropped (ScaleAndConvert.scala:62-64)


# ---------------------------------------------------------------------------
# Mean image
# ---------------------------------------------------------------------------


def compute_mean(
    minibatches: Iterable[Tuple[np.ndarray, np.ndarray]],
    return_sum: bool = False,
) -> Tuple[np.ndarray, int]:
    """Streaming mean image over uint8 minibatches.

    Integer (int64) accumulation like the reference's Long accumulators
    (ComputeMean.scala:42-49) — no float drift, bounded memory. Returns
    (mean float32 (3, H, W), count); with ``return_sum`` returns the raw
    (sum int64, count) pair for cross-partition reduction.
    """
    total: Optional[np.ndarray] = None
    count = 0
    for images, _ in minibatches:
        s = images.astype(np.int64).sum(axis=0)
        total = s if total is None else total + s
        count += len(images)
    if total is None:
        raise ValueError("no minibatches given")
    if return_sum:
        return total, count
    return (total.astype(np.float64) / count).astype(np.float32), count


def reduce_mean_sums(
    partials: Sequence[Tuple[np.ndarray, int]]
) -> np.ndarray:
    """Combine per-partition (sum, count) pairs — the ``RDD.reduce``
    elementwise add + divide (ComputeMean.scala:51-57). On a multi-host pod
    each host computes its partial over its shards; the reduction is tiny
    (one image-sized array per host)."""
    total = sum(s.astype(np.int64) for s, _ in partials)
    count = sum(c for _, c in partials)
    if count == 0:
        raise ValueError("no data in any partition")
    return (total.astype(np.float64) / count).astype(np.float32)


# ---------------------------------------------------------------------------
# Synthetic fixture (tests / offline demo)
# ---------------------------------------------------------------------------


def write_synthetic_imagenet(
    root: str,
    num_shards: int = 2,
    images_per_shard: int = 24,
    classes: int = 4,
    size_range: Tuple[int, int] = (40, 96),
    labels_file: str = "train.txt",
    shard_prefix: str = "train.",
    corrupt_every: int = 0,
    seed: int = 0,
) -> None:
    """Write tar shards of real JPEGs + a train.txt label map.

    Images get class-dependent channel shifts (learnable) and random sizes
    (exercising force-resize); ``corrupt_every`` > 0 interleaves undecodable
    entries (exercising the drop path).
    """
    from PIL import Image

    os.makedirs(root, exist_ok=True)
    rng = np.random.RandomState(seed)
    lines: List[str] = []
    idx = 0
    for s in range(num_shards):
        shard_path = os.path.join(root, f"{shard_prefix}{s:05d}.tar")
        with tarfile.open(shard_path, "w") as tar:
            for i in range(images_per_shard):
                label = int(rng.randint(classes))
                h = int(rng.randint(*size_range))
                w = int(rng.randint(*size_range))
                arr = rng.randint(0, 100, (h, w, 3)).astype(np.uint8)
                arr[..., label % 3] += np.uint8(60 + 20 * (label // 3))
                name = f"img_{idx:06d}.jpg"
                idx += 1
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="JPEG", quality=90)
                payload = buf.getvalue()
                if corrupt_every and (i + 1) % corrupt_every == 0:
                    payload = payload[: len(payload) // 2]  # truncated JPEG
                info = tarfile.TarInfo(name=name)
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
                lines.append(f"{name} {label}")
    with open(os.path.join(root, labels_file), "w") as f:
        f.write("\n".join(lines) + "\n")
