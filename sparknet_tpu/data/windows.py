"""R-CNN window sampling — the WindowData host pipeline.

Reference: ``caffe/src/caffe/layers/window_data_layer.cpp`` (the
fine-tuning data source of the R-CNN detection workflow).  Semantics
reproduced:

- window_file format (``:41-47``): repeated ``# idx / img_path /
  channels / height / width / num_windows`` then ``class overlap
  x1 y1 x2 y2`` rows;
- fg/bg partition by overlap threshold (fg: overlap >= fg_threshold;
  bg: 0-overlap-excluded windows under bg_threshold), batch composed of
  ``batch_size * fg_fraction`` foreground samples (labels = class) and
  the rest background (label 0), each drawn uniformly from its pool;
- context padding + warp (``:305-384``): the window is expanded by
  ``crop_size / (crop_size - 2*context_pad)`` about its center
  (squared first under ``crop_mode: "square"``), clipped to the image,
  the clipped part warped into its proportional sub-rectangle of the
  ``crop_size`` square, and the out-of-image remainder left at the
  padding value (0 after mean subtraction — the reference zeroes the
  batch, so padding pixels carry no signal);
- mirror flips the warped window AND its padding offsets; mean_file /
  mean_value subtraction and ``scale`` match DataTransformer.

The on-disk image decode goes through PIL (the reference uses OpenCV);
bilinear resize keeps the warp semantics.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from sparknet_tpu.config.schema import WindowDataParameter


@dataclass
class WindowImage:
    path: str
    channels: int
    height: int
    width: int
    # rows: (class_index, overlap, x1, y1, x2, y2)
    windows: np.ndarray = field(default_factory=lambda: np.zeros((0, 6)))


def parse_window_file(path: str, root_folder: str = "") -> List[WindowImage]:
    """Parse the R-CNN window_file format (window_data_layer.cpp:41-47)."""
    images: List[WindowImage] = []
    with open(path) as f:
        lines = [l.strip() for l in f]
    i = 0
    while i < len(lines):
        if not lines[i]:
            i += 1
            continue
        if not lines[i].startswith("#"):
            raise ValueError(
                f"{path}:{i + 1}: expected '# image_index', got {lines[i]!r}"
            )
        img_path = lines[i + 1]
        if root_folder and not os.path.isabs(img_path):
            img_path = os.path.join(root_folder, img_path)
        channels, height, width, num_windows = (
            int(lines[i + 2]),
            int(lines[i + 3]),
            int(lines[i + 4]),
            int(lines[i + 5]),
        )
        rows = []
        for j in range(num_windows):
            vals = lines[i + 6 + j].split()
            rows.append(
                (
                    int(vals[0]),
                    float(vals[1]),
                    int(vals[2]),
                    int(vals[3]),
                    int(vals[4]),
                    int(vals[5]),
                )
            )
        images.append(
            WindowImage(
                img_path,
                channels,
                height,
                width,
                np.asarray(rows, np.float64).reshape(num_windows, 6),
            )
        )
        i += 6 + num_windows
    return images


def effective_window_params(lp):
    """(crop_size, mirror, scale, mean_file, mean_value) for a
    WindowData layer, preferring ``transform_param`` (where the
    reference's canonical prototxts put them; ``window_data_layer.cpp``
    reads ``transform_param_``) over the legacy WindowDataParameter
    copies."""
    wdp = lp.window_data_param
    tp = lp.transform_param
    crop = int(tp.crop_size) if tp and tp.crop_size else int(wdp.crop_size)
    mirror = bool(tp.mirror) if tp and tp.mirror else bool(wdp.mirror)
    scale = (
        float(tp.scale)
        if tp is not None and tp.scale != 1.0
        else float(wdp.scale)
    )
    mean_file = tp.mean_file if tp and tp.mean_file else wdp.mean_file
    mean_value = list(tp.mean_value) if tp and tp.mean_value else []
    return crop, mirror, scale, mean_file, mean_value


def read_window_file_header(path: str) -> Tuple[int, int, int]:
    """(channels, height, width) of the FIRST entry only — the cheap
    read shape inference needs (real R-CNN window files list millions of
    windows; parsing them all to learn the channel count is waste)."""
    with open(path) as f:
        lines = []
        for line in f:
            line = line.strip()
            if line:
                lines.append(line)
            if len(lines) >= 5:
                break
    if len(lines) < 5 or not lines[0].startswith("#"):
        raise ValueError(f"{path}: not a window file")
    return int(lines[2]), int(lines[3]), int(lines[4])


def _load_image(path: str, channels: int) -> np.ndarray:
    from PIL import Image

    img = Image.open(path).convert("L" if channels == 1 else "RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr  # (H, W, C)


def _warp(region: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize of an (h, w, C) uint8 region."""
    from PIL import Image

    if region.shape[2] == 1:
        im = Image.fromarray(region[:, :, 0])
    else:
        im = Image.fromarray(region)
    im = im.resize((max(1, out_w), max(1, out_h)), Image.BILINEAR)
    arr = np.asarray(im, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def crop_window(
    img: np.ndarray,
    x1, y1, x2, y2,
    crop: int,
    context_pad: int = 0,
    square: bool = False,
    do_mirror: bool = False,
):
    """Crop one window from an (H, W, C) image, with R-CNN context
    padding and warp-to-square (``window_data_layer.cpp:246-375``
    semantics, shared by training batches and the Detector driver).

    Returns ``(out, pad_h, pad_w, (warped_h, warped_w))`` where ``out``
    is (crop, crop, C) float32 with zero padding outside the warped
    content region."""
    pad = int(context_pad)
    h_img, w_img = img.shape[:2]
    pad_w = pad_h = 0
    out_h = out_w = crop
    if pad > 0 or square:
        context_scale = crop / float(crop - 2 * pad)
        half_h = (y2 - y1 + 1) / 2.0
        half_w = (x2 - x1 + 1) / 2.0
        cx, cy = x1 + half_w, y1 + half_h
        if square:
            half_h = half_w = max(half_h, half_w)
        x1 = int(round(cx - half_w * context_scale))
        x2 = int(round(cx + half_w * context_scale))
        y1 = int(round(cy - half_h * context_scale))
        y2 = int(round(cy + half_h * context_scale))
        un_h, un_w = y2 - y1 + 1, x2 - x1 + 1
        pad_x1, pad_y1 = max(0, -x1), max(0, -y1)
        pad_x2 = max(0, x2 - w_img + 1)
        pad_y2 = max(0, y2 - h_img + 1)
        x1, x2 = x1 + pad_x1, x2 - pad_x2
        y1, y2 = y1 + pad_y1, y2 - pad_y2
        scale_x, scale_y = crop / float(un_w), crop / float(un_h)
        out_w = int(round((x2 - x1 + 1) * scale_x))
        out_h = int(round((y2 - y1 + 1) * scale_y))
        pad_h = int(round(pad_y1 * scale_y))
        # mirrored windows mirror their padding too (:370-375)
        pad_w = int(round((pad_x2 if do_mirror else pad_x1) * scale_x))
        out_h = min(out_h, crop - pad_h)
        out_w = min(out_w, crop - pad_w)
    region = img[int(y1):int(y2) + 1, int(x1):int(x2) + 1]
    warped = _warp(region, out_h, out_w)
    if do_mirror:
        warped = warped[:, ::-1]
    out = np.zeros((crop, crop, img.shape[2]), np.float32)
    out[pad_h:pad_h + warped.shape[0], pad_w:pad_w + warped.shape[1]] = (
        warped
    )
    return out, pad_h, pad_w, warped.shape[:2]


class WindowSampler:
    """Batch sampler with the reference's fg/bg composition and
    context-pad warp; emits (data (B, C, crop, crop) f32, label (B,))."""

    def __init__(
        self,
        param: WindowDataParameter,
        mean: Optional[np.ndarray] = None,
        phase: str = "TRAIN",
        seed: int = 0,
        crop_size: Optional[int] = None,
        mirror: Optional[bool] = None,
        scale: Optional[float] = None,
    ):
        # crop/mirror/scale may come from the layer's transform_param
        # (where the reference's canonical prototxts put them —
        # window_data_layer.cpp reads this->transform_param_; the
        # WindowDataParameter copies are the legacy location)
        self.p = param
        self.crop = int(crop_size if crop_size is not None else param.crop_size)
        self.mirror = bool(mirror if mirror is not None else param.mirror)
        self.scale = float(scale if scale is not None else param.scale)
        if self.crop <= 0:
            raise ValueError(
                "WindowData needs a positive crop_size (set it in "
                "transform_param or window_data_param)"
            )
        self.phase = phase.upper()
        self.rng = np.random.RandomState(seed)
        self.images = parse_window_file(param.source, param.root_folder)
        self.mean = mean  # (C,) mean values or (C, H, W) mean image
        fg, bg = [], []
        for idx, im in enumerate(self.images):
            for w in im.windows:
                entry = (idx,) + tuple(w)
                if w[1] >= param.fg_threshold:
                    fg.append(entry)
                elif w[1] < param.bg_threshold and w[1] >= 0:
                    bg.append(entry)
        if not fg or not bg:
            raise ValueError(
                f"window file {param.source}: need both foreground "
                f"({len(fg)}) and background ({len(bg)}) windows"
            )
        self.fg = fg
        self.bg = bg
        self._cache = {}

    def _image(self, idx: int) -> np.ndarray:
        im = self.images[idx]
        if not self.p.cache_images:
            return _load_image(im.path, im.channels)
        if idx not in self._cache:
            self._cache[idx] = _load_image(im.path, im.channels)
        return self._cache[idx]

    def _crop_window(self, img: np.ndarray, x1, y1, x2, y2, do_mirror):
        return crop_window(
            img, x1, y1, x2, y2, self.crop,
            context_pad=int(self.p.context_pad),
            square=self.p.crop_mode == "square",
            do_mirror=do_mirror,
        )

    def next_batch(self) -> Tuple[np.ndarray, np.ndarray]:
        p = self.p
        batch, crop = int(p.batch_size), self.crop
        num_fg = int(batch * p.fg_fraction)
        channels = self.images[0].channels
        data = np.zeros((batch, channels, crop, crop), np.float32)
        labels = np.zeros(batch, np.float32)
        item = 0
        for is_fg, count in ((False, batch - num_fg), (True, num_fg)):
            pool = self.fg if is_fg else self.bg
            for _ in range(count):
                idx, cls, _ov, x1, y1, x2, y2 = pool[
                    self.rng.randint(len(pool))
                ]
                do_mirror = self.mirror and (
                    self.phase == "TRAIN" and self.rng.randint(2) == 1
                )
                img = self._image(int(idx))
                out, pad_h, pad_w, (wh, ww) = self._crop_window(
                    img, x1, y1, x2, y2, do_mirror
                )
                chw = out.transpose(2, 0, 1)
                if self.mean is not None:
                    mean = np.asarray(self.mean, np.float32)
                    if mean.ndim == 1:  # mean_value per channel
                        sub = chw - mean[:, None, None]
                    else:  # mean image: center-crop window + pad offsets
                        off = (mean.shape[1] - crop) // 2
                        sub = chw - mean[
                            :, off:off + crop, off:off + crop
                        ]
                    # padding stays zero-signal like the reference's
                    # zeroed batch buffer
                    m = np.zeros((crop, crop), bool)
                    m[pad_h:pad_h + wh, pad_w:pad_w + ww] = True
                    chw = np.where(m[None], sub, 0.0)
                data[item] = chw * self.scale
                labels[item] = float(cls) if is_fg else 0.0
                item += 1
        return data, labels
