"""sparknet_tpu — a TPU-native distributed deep-learning framework.

A brand-new framework with the capabilities of SparkNet (distributed neural
networks with per-worker native engines + synchronous tau-step parameter
averaging), re-designed TPU-first:

- The per-worker Caffe/CUDA engine (reference: ``caffe/src/caffe``) becomes a
  JAX/XLA net compiler: ``NetParameter`` configs compile to pure, jitted
  ``forward``/``loss`` functions (``sparknet_tpu.net.JaxNet``).
- The Spark broadcast/reduce parameter-averaging plane and the in-node P2PSync
  GPU tree (reference: ``src/main/scala/apps/*.scala``, ``caffe/src/caffe/
  parallel.cpp``) both lower to XLA collectives (``psum``) over an ICI/DCN
  device mesh (``sparknet_tpu.parallel``).
- The JVM->native callback data layer (reference: ``caffe/src/caffe/layers/
  java_data_layer.cpp``) inverts into async host prefetch pipelines feeding
  device buffers (``sparknet_tpu.data``).

See SURVEY.md at the repo root for the full reference analysis.
"""

__version__ = "0.1.0"

from sparknet_tpu.config import (  # noqa: F401
    NetParameter,
    SolverParameter,
    LayerParameter,
    load_net_prototxt,
    load_solver_prototxt,
    parse_net_prototxt,
    parse_solver_prototxt,
)
