"""sync-in-hot-path: implicit host<->device syncs in round/producer/
comm scopes.

Flags, inside every hot scope (``hotpaths.HOT_PATHS`` plus any
thread-target function):

- ``x.item()``                         — scalar D2H sync
- ``float(x)`` / ``int(x)``            — implicit ``__float__`` D2H on a
  jax value (shape/len/constant reads are recognized as benign)
- ``np.asarray(x)`` / ``np.array(x)``  — implicit ``__array__`` D2H
- ``jax.device_get(x)``                — explicit full D2H
- ``jax.block_until_ready(x)`` / ``x.block_until_ready()`` — dispatch
  barrier

Every deliberate site carries ``# sparknet: sync-ok(<reason>)`` on a
line of the flagged statement; the suppressed list stays enumerable so
``bench.py --mode=sanitize`` can pin the complete deliberate-sync
inventory in its artifact.  The checker is intentionally type-blind
(``np.asarray`` on a host array is cheap but still gets annotated —
the annotation IS the documentation that someone checked).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from sparknet_tpu.analysis import astutil
from sparknet_tpu.analysis.findings import Finding, Markers, Report, Suppressed

CHECKER = "sync-in-hot-path"
MARKER = "sync"

# attribute reads that mean "metadata, not data" — float()/int() over
# these never sync (shape math, sizes, python scalars)
_BENIGN_ATTRS = {
    "shape", "ndim", "size", "nbytes", "dtype", "maxlen", "start",
    "stop", "step",
}
# bare-builtin calls that can be benign; METHOD calls never are —
# `float(losses.max())` is a scalar D2H reduction, exactly the careless
# sync class this checker exists to stop, and must not slip through on
# a leaf-name match
_BENIGN_NAME_CALLS = {"len", "round", "min", "max", "abs", "sum"}


def _is_benign_scalar(node: ast.AST) -> bool:
    """True when a float()/int() argument provably reads host metadata
    (constants, shape/len chains, time reads) rather than array data."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.BinOp):
        return _is_benign_scalar(node.left) and _is_benign_scalar(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_benign_scalar(node.operand)
    if isinstance(node, ast.Subscript):
        return _is_benign_scalar(node.value)
    if isinstance(node, ast.Attribute):
        if node.attr in _BENIGN_ATTRS:
            return True
        return _is_benign_scalar(node.value)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "len":
                return True  # len() reads shape, never data
            if fn.id in _BENIGN_NAME_CALLS:
                # max(x.shape) is benign; max(device_array) is a sync
                return all(_is_benign_scalar(a) for a in node.args)
        name = astutil.dotted(fn) or ""
        if name.startswith("time."):
            return True  # host clock reads
        return False
    if isinstance(node, ast.IfExp):
        return (_is_benign_scalar(node.body)
                and _is_benign_scalar(node.orelse))
    if isinstance(node, ast.BoolOp):
        return all(_is_benign_scalar(v) for v in node.values)
    if isinstance(node, ast.Compare):
        # a comparison of device values yields a device bool —
        # float(x > 0.5) is still a sync; only shape/constant
        # comparisons are benign
        return all(
            _is_benign_scalar(c)
            for c in [node.left] + list(node.comparators)
        )
    return False


def _sync_kind(call: ast.Call) -> Optional[str]:
    """The sync class of a call, or None."""
    fn = call.func
    name = astutil.dotted(fn)
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item" and not call.args and not call.keywords:
            return ".item()"
        if fn.attr == "block_until_ready":
            return "block_until_ready"
        if name in ("np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "onp.asarray", "onp.array"):
            return name
        if name in ("jax.device_get",):
            return "jax.device_get"
        if fn.attr == "device_get":
            return "device_get"
    elif isinstance(fn, ast.Name):
        if fn.id in ("float", "int") and len(call.args) == 1:
            if not _is_benign_scalar(call.args[0]):
                return f"{fn.id}()"
        elif fn.id in ("device_get", "block_until_ready"):
            return fn.id
    return None


def check_module(
    tree: ast.Module,
    relpath: str,
    markers: Markers,
    hot_scopes: Set[str],
    thread_targets: Set[str],
) -> Report:
    rep = Report()
    funcs = astutil.collect_functions(tree)

    def walk_scope(node, qual):
        """Like ast.walk, but a nested def that is ITSELF a hot scope
        or thread target is skipped — it gets its own visit under its
        own qualname (no double-count).  Other nested closures stay in:
        they run in the hot scope that defines them."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, astutil.FUNC_NODES):
                if (
                    f"{qual}.{child.name}" in hot_scopes
                    or child.name in thread_targets
                ):
                    continue
                yield from walk_scope(child, f"{qual}.{child.name}")
                continue
            yield child
            yield from walk_scope(child, qual)

    for qual, node in funcs.items():
        leaf = qual.split(".")[-1]
        if qual not in hot_scopes and leaf not in thread_targets:
            continue
        for sub in walk_scope(node, qual):
            if isinstance(sub, ast.Call):
                kind = _sync_kind(sub)
                if kind is None:
                    continue
                lo, hi = astutil.span_lines(sub)
                msg = (
                    f"{kind} syncs host<->device inside hot path "
                    f"'{qual}'"
                )
                reason = markers.covers(MARKER, lo, hi)
                if reason is not None:
                    rep.suppressed.append(Suppressed(
                        CHECKER, relpath, lo, qual, msg, reason,
                    ))
                else:
                    rep.findings.append(Finding(
                        checker=CHECKER, path=relpath, line=lo,
                        scope=qual, message=msg,
                        fixit="move the read off the steady-state round "
                        "path, or annotate the line with "
                        "# sparknet: sync-ok(<why this sync is "
                        "deliberate>)",
                    ))
    return rep
