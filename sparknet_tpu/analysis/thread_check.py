"""Thread hygiene across the framework's producer/comm/watchdog/server
threads.

Rules (marker in parentheses suppresses, with a mandatory reason):

- ``thread-anonymous`` (``thread-ok``): every ``threading.Thread(...)``
  must pass ``name=`` — anonymous threads make traces, stall reports
  and ``py-spy`` dumps unattributable (the tracer labels Perfetto
  tracks from thread names).
- ``thread-daemon`` (``thread-ok``): ``daemon=`` must be explicit.  The
  default (inherit from spawner) silently flips lifecycle semantics
  when a thread starts another thread.
- ``join-no-timeout`` (``join-ok``): ``.join()`` with no timeout
  blocks forever on a wedged thread.  Allowed inside shutdown-path
  functions (name contains stop/close/shutdown/teardown/cleanup/
  reset/finalize/__exit__/drain/wait — teardown is allowed to wait);
  anywhere else it needs a bound or a justification.
- ``except-bare`` / ``except-swallow`` (``except-ok``): a bare
  ``except:`` anywhere, or an ``except ...: pass`` inside a
  thread-target function — a producer/comm thread that swallows its
  error dies silently and the consumer hangs until a watchdog fires.
- ``lock-order-cycle`` (``lock-ok``): the cross-module lock
  acquisition-order graph (``with a_lock:`` nested inside ``with
  b_lock:``, plus one level of intra-module call propagation) must be
  acyclic; a cycle is a latent deadlock between framework threads.

Lock identity is ``module:Class.attr`` for ``self._lock``-style
attributes and ``module:function.name`` for locals — good enough to
catch real inversions without alias analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sparknet_tpu.analysis import astutil
from sparknet_tpu.analysis.findings import Finding, Markers, Report, Suppressed

CHECKER = "thread-hygiene"

SHUTDOWN_TOKENS = (
    "stop", "close", "shutdown", "teardown", "cleanup", "reset",
    "finalize", "exit", "drain", "wait", "atexit", "reap", "del",
)

_LOCK_NAME_TOKENS = ("lock", "_lock", "mutex", "cond", "nonempty")


def _is_shutdown_scope(qual: str) -> bool:
    """Underscore-segment match, not substring: ``wait`` exempts
    ``wait``/``wait_all`` but not ``await_result``."""
    leaf = qual.split(".")[-1].lower()
    segs = [s for s in leaf.split("_") if s]
    return any(tok in segs for tok in SHUTDOWN_TOKENS)


def _lock_id(expr: ast.AST, module: str, qual: str) -> Optional[str]:
    """A stable id for a lock-ish ``with`` context expression, or None
    when the expression doesn't look like a lock."""
    name = astutil.dotted(expr)
    if not name:
        return None
    leaf = name.split(".")[-1]
    if not any(tok in leaf.lower() for tok in _LOCK_NAME_TOKENS):
        return None
    if name.startswith("self."):
        cls = qual.split(".")[0] if "." in qual else qual
        return f"{module}:{cls}.{leaf}"
    return f"{module}:{name}"


class _ModuleLocks:
    """Per-module lock facts: which locks each function acquires, and
    the syntactic nesting edges."""

    def __init__(self):
        self.acquires: Dict[str, Set[str]] = {}   # qual -> lock ids
        # (lock_a, lock_b, path, line) — a held while acquiring b
        self.edges: List[Tuple[str, str, str, int, str]] = []
        self.calls_under: List[Tuple[str, str, str, int, str]] = []
        # (lock_a, called-leaf-name, path, line, qual)


def check_module(
    tree: ast.Module,
    relpath: str,
    markers: Markers,
    thread_targets: Set[str],
    module_key: Optional[str] = None,
) -> Tuple[Report, _ModuleLocks]:
    rep = Report()
    module = module_key or relpath
    locks = _ModuleLocks()
    funcs = astutil.collect_functions(tree)

    def _emit(rule: str, marker: str, node: ast.AST, qual: str,
              message: str, fixit: str) -> None:
        lo, hi = astutil.span_lines(node)
        reason = markers.covers(marker, lo, hi)
        if reason is not None:
            rep.suppressed.append(Suppressed(
                f"{CHECKER}/{rule}", relpath, lo, qual, message, reason,
            ))
        else:
            rep.findings.append(Finding(
                checker=f"{CHECKER}/{rule}", path=relpath, line=lo,
                scope=qual, message=message, fixit=fixit,
            ))

    for qual, fn in funcs.items():
        leaf = qual.split(".")[-1]
        in_thread_target = leaf in thread_targets
        held: List[str] = []

        def visit(node: ast.AST, held: List[str], qual=qual,
                  in_thread_target=in_thread_target) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested defs are separate scopes
            if isinstance(node, ast.Call):
                if astutil.is_thread_ctor(node):
                    if astutil.kwarg(node, "name") is None:
                        _emit(
                            "thread-anonymous", "thread", node, qual,
                            "threading.Thread(...) without name= — "
                            "unattributable in traces and stall dumps",
                            "pass name=\"<subsystem>-<role>\"",
                        )
                    if astutil.kwarg(node, "daemon") is None:
                        _emit(
                            "thread-daemon", "thread", node, qual,
                            "threading.Thread(...) without an explicit "
                            "daemon= policy",
                            "pass daemon=True (reaped threads) or "
                            "daemon=False (must-complete work), "
                            "deliberately",
                        )
                fnode = node.func
                if (
                    isinstance(fnode, ast.Attribute)
                    and fnode.attr == "join"
                    and not node.args
                    and not node.keywords
                    and not _is_shutdown_scope(qual)
                ):
                    _emit(
                        "join-no-timeout", "join", node, qual,
                        ".join() with no timeout outside a shutdown "
                        "path can hang the caller on a wedged thread",
                        "join(timeout=...) and handle the still-alive "
                        "case, or annotate with # sparknet: "
                        "join-ok(<why the wait is bounded>)",
                    )
                # one-level call propagation for the lock-order graph
                if held:
                    callee = astutil.dotted(node.func)
                    if callee:
                        locks.calls_under.append((
                            held[-1], callee.split(".")[-1], relpath,
                            node.lineno, qual,
                        ))
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    _emit(
                        "except-bare", "except", node, qual,
                        "bare except: catches SystemExit/"
                        "KeyboardInterrupt and hides the real error",
                        "catch Exception (or the specific error) and "
                        "record it",
                    )
                elif in_thread_target and all(
                    isinstance(b, ast.Pass) for b in node.body
                ):
                    # `except Full: continue` retry loops are the
                    # polite-put pattern, not a swallow — only a body
                    # of pure `pass` hides an error
                    _emit(
                        "except-swallow", "except", node, qual,
                        "exception swallowed (pass) inside a thread "
                        "target — the thread dies silently and the "
                        "consumer hangs until a watchdog fires",
                        "record the error for the consumer "
                        "(the Prefetcher._run pattern) or log it",
                    )
            if isinstance(node, (ast.With, ast.AsyncWith)):
                ids = []
                for item in node.items:
                    lid = _lock_id(item.context_expr, module, qual)
                    if lid is not None:
                        ids.append(lid)
                        locks.acquires.setdefault(qual, set()).add(lid)
                        if held:
                            locks.edges.append((
                                held[-1], lid, relpath,
                                item.context_expr.lineno, qual,
                            ))
                held.extend(ids)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                for _ in ids:
                    held.pop()
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, held)
    return rep, locks


def lock_cycle_findings(
    all_locks: List[Tuple[str, "_ModuleLocks"]],
    markers_by_path: Dict[str, Markers],
) -> Report:
    """Fold every module's lock facts into one acquisition-order graph
    (syntactic nesting edges + one level of call propagation within a
    module) and report each cycle once."""
    rep = Report()
    edges: Dict[str, Set[str]] = {}
    edge_site: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, path: str, line: int, qual: str) -> None:
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        edge_site.setdefault((a, b), (path, line, qual))

    for relpath, ml in all_locks:
        for a, b, path, line, qual in ml.edges:
            add_edge(a, b, path, line, qual)
        # call propagation: `with A: self.m()` where m acquires B
        acq_by_leaf: Dict[str, Set[str]] = {}
        for qual, ids in ml.acquires.items():
            acq_by_leaf.setdefault(qual.split(".")[-1], set()).update(ids)
        for a, callee_leaf, path, line, qual in ml.calls_under:
            for b in acq_by_leaf.get(callee_leaf, ()):
                add_edge(a, b, path, line, qual)

    # cycle detection: DFS with coloring; report each cycle's canonical
    # rotation once
    seen_cycles: Set[Tuple[str, ...]] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(u: str) -> None:
        color[u] = GRAY
        stack.append(u)
        for v in sorted(edges.get(u, ())):
            c = color.get(v, WHITE)
            if c == WHITE:
                dfs(v)
            elif c == GRAY:
                i = stack.index(v)
                cyc = tuple(stack[i:])
                k = min(range(len(cyc)), key=lambda j: cyc[j])
                canon = cyc[k:] + cyc[:k]
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    path, line, qual = edge_site.get(
                        (u, v), ("<graph>", 0, "<graph>")
                    )
                    msg = (
                        "lock acquisition-order cycle: "
                        + " -> ".join(canon + (canon[0],))
                    )
                    markers = markers_by_path.get(path)
                    reason = (
                        markers.covers("lock", line, line)
                        if markers else None
                    )
                    if reason is not None:
                        rep.suppressed.append(Suppressed(
                            f"{CHECKER}/lock-order-cycle", path, line,
                            qual, msg, reason,
                        ))
                    else:
                        rep.findings.append(Finding(
                            checker=f"{CHECKER}/lock-order-cycle",
                            path=path, line=line, scope=qual, message=msg,
                            fixit="pick one global order for these locks "
                            "(or drop to a single lock); a cycle is a "
                            "latent deadlock between framework threads",
                        ))
        stack.pop()
        color[u] = BLACK

    for node in sorted(edges):
        if color.get(node, WHITE) == WHITE:
            dfs(node)
    return rep
