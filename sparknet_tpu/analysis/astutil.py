"""Small AST helpers shared by the checkers: qualname-indexed function
collection, dotted-name rendering, and thread-target discovery."""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` / ``self._x`` attribute chains (None for
    anything fancier — subscripts, calls)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def collect_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """``qualname -> def node`` for every (possibly nested) function;
    nesting joins with ``.`` (``Class.method``, ``outer.inner``)."""
    out: Dict[str, ast.AST] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNC_NODES):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                out[qn] = child
                walk(child, qn)
            elif isinstance(child, ast.ClassDef):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                walk(child, qn)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def is_thread_ctor(call: ast.Call) -> bool:
    """``threading.Thread(...)`` / ``Thread(...)``."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def thread_target_names(tree: ast.Module) -> Set[str]:
    """Local function names passed as ``target=`` to a Thread ctor
    anywhere in the module (these scopes run on framework threads)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_thread_ctor(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    name = dotted(kw.value)
                    if name:
                        out.add(name.split(".")[-1])
    return out


def kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def span_lines(node: ast.AST) -> tuple:
    return node.lineno, getattr(node, "end_lineno", node.lineno)
