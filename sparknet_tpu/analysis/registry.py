"""The canonical trace/metrics name registry.

One authoritative inventory of every ``sparknet_*`` metric the
framework emits (``obs/__init__.py`` TrainingMetrics) and every
``span(...)`` name by category — the sets the folding side consumes:
``tools/trace_report.py`` (comm-span folding), ``tools/perf_gate.py``
(live-profile fields), the PERF.md "Telemetry reference" tables, and
the ``/metrics`` scrapers people build dashboards on.

``analysis/registry_audit.py`` cross-checks this module against the
code, both directions: an emitter whose name is missing here fails the
lint (a dashboard can't find it, ``trace_report`` won't fold it), and
an entry here that nothing emits fails too (documentation of a ghost).
Adding a metric/span is therefore a two-line change: the emitter and
this registry (plus the PERF.md table row, which the audit also
enforces).  Import cost discipline: this module must stay stdlib-only
— ``tools/trace_report.py`` imports it at CLI startup.
"""

from __future__ import annotations

# metric name -> label names (() = unlabeled).  Only sparknet_* series
# are canonical here; the serving stack's serve_* series live with the
# serving code (a separate registry instance per server).
CANONICAL_METRICS = {
    "sparknet_uptime_seconds": (),
    "sparknet_rounds_total": (),
    "sparknet_iters_total": (),
    "sparknet_phase_latency_seconds": ("phase",),
    "sparknet_feed_queue_depth": (),
    "sparknet_feed_stalls_total": (),
    "sparknet_io_retries_total": (),
    "sparknet_snapshots_total": (),
    "sparknet_restores_total": (),
    "sparknet_snapshots_quarantined_total": (),
    "sparknet_faults_total": ("kind",),
    "sparknet_cache_hits_total": (),
    "sparknet_cache_misses_total": (),
    "sparknet_cache_evictions_total": (),
    "sparknet_cache_bytes_total": ("src",),
    "sparknet_collective_bytes_total": ("compress",),
    "sparknet_quant_error_max_abs": ("compress",),
    "sparknet_quant_snr_db": ("compress",),
    # Pallas custom-kernel routing (ops/pallas_attention.lowerable()
    # gate): which hot paths ride fused kernels, and how many fused
    # epilogue kernel launches the comm plane issued
    "sparknet_kernel_path": ("kernel",),
    "sparknet_kernel_fused_chunks_total": ("stage",),
    "sparknet_hidden_fraction": ("kind",),
    "sparknet_worker_skew": (),
    "sparknet_straggler_worker": (),
    "sparknet_straggler_rounds_total": (),
    "sparknet_achieved_flops": (),
    "sparknet_mfu": (),
    "sparknet_jit_cache_size": (),
    "sparknet_device_bytes": (),
    "sparknet_host_rss_bytes": (),
    "sparknet_grad_norm": (),
    "sparknet_nonfinite_total": (),
    "sparknet_update_ratio": ("group",),
    "sparknet_health_anomalies_total": ("kind",),
    "sparknet_health_rollbacks_total": (),
    # elastic membership (runtime/membership.py, --elastic) — the
    # epoch-numbered worker-roster views driving the round's live_mask
    "sparknet_membership_epoch": (),
    "sparknet_membership_workers": ("state",),
    "sparknet_membership_transitions_total": ("kind",),
    # two-tier hierarchical averaging (parallel/hierarchy.py,
    # --slices/--cross_slice_every) — tier-split round/byte accounting
    "sparknet_hierarchy_rounds_total": ("tier",),
    "sparknet_hierarchy_bytes_total": ("tier",),
    # fleet shipper (obs/ship.py, --ship_to) — per-host push side
    "sparknet_ship_events_total": (),
    "sparknet_ship_dropped_total": (),
    "sparknet_ship_pushes_total": (),
    "sparknet_ship_push_failures_total": (),
    # serving fleet (serve/fleet.py, cli serve --replicas) — per-replica
    # rotation state + fleet lifecycle counters on the pool's registry
    # (an obs-enabled serve run registers them on the shared training
    # registry so the PR-10 shipper ships them unchanged)
    "sparknet_serve_replica_state": ("replica",),
    "sparknet_serve_replica_inflight": ("replica",),
    "sparknet_serve_replica_requests_total": ("replica",),
    "sparknet_serve_replica_errors_total": ("replica",),
    "sparknet_serve_replica_ejections_total": (),
    "sparknet_serve_replica_respawns_total": (),
    "sparknet_serve_replica_engine_swaps_total": (),
    # train-to-serve delivery (serve/delivery.py, cli serve --watch)
    "sparknet_delivery_phase": (),
    "sparknet_delivery_publishes_seen_total": (),
    "sparknet_delivery_rejected_total": (),
    "sparknet_delivery_canary_mirrors_total": (),
    "sparknet_delivery_promotions_total": (),
    "sparknet_delivery_rollbacks_total": (),
    "sparknet_delivery_divergence": (),
    # run journal + crash recovery (io/journal.py, --journal;
    # runtime/recover.py journaled resume)
    "sparknet_journal_records_total": ("kind",),
    "sparknet_journal_truncated_total": (),
    "sparknet_recover_replayed_rounds_total": (),
    # transformer-LM workload (apps/lm_app.py, --sp sequence
    # parallelism over parallel/ring_attention.py)
    "sparknet_lm_tokens_total": (),
    "sparknet_lm_ring_hop_bytes_total": (),
    # autoregressive generation serving (serve/generate.py KV arena +
    # serve/batcher.py StreamBatcher + serve/fleet.py stream routing)
    "sparknet_kv_blocks_total": (),
    "sparknet_kv_blocks_used": (),
    "sparknet_kv_alloc_total": (),
    "sparknet_kv_free_total": (),
    "sparknet_gen_streams_total": (),
    "sparknet_gen_streams_shed_total": ("cause",),
    "sparknet_gen_stream_errors_total": (),
    "sparknet_gen_tokens_total": (),
    "sparknet_gen_active_streams": (),
    "sparknet_gen_ttft_seconds": (),
    "sparknet_gen_intertoken_seconds": (),
    "sparknet_gen_decode_batch_occupancy": (),
    "sparknet_gen_jit_cache_size": (),
    "sparknet_gen_resumes_total": (),
    # request anatomy (obs/reqtrace.py RequestProfiler) — per-stage
    # latency folds + the window's bound-stage / slow-replica verdicts
    "sparknet_req_stage_seconds": ("stage",),
    "sparknet_req_bound_stage": (),
    "sparknet_req_replica_skew": (),
    "sparknet_req_slow_replica": (),
    "sparknet_req_completed_total": (),
    # bounded-staleness averaging (parallel/stale.py, --stale_bound) —
    # per-worker lag/arrival accounting at each averaging boundary
    "sparknet_staleness": ("worker",),
    "sparknet_stale_arrivals_total": ("worker",),
    "sparknet_stale_skipped_total": ("worker",),
    "sparknet_stale_forced_waits_total": (),
    "sparknet_stale_boundaries_skipped_total": (),
    # fleet collector (obs/fleet.py, --fleet_collector) — the merged
    # cross-host families on the collector's own /metrics
    "sparknet_fleet_hosts": ("state",),
    "sparknet_fleet_round": ("host",),
    "sparknet_fleet_round_skew": (),
    "sparknet_fleet_clock_offset_seconds": ("host",),
    "sparknet_fleet_events_total": ("host",),
    "sparknet_fleet_dropped_events_total": ("host",),
    "sparknet_fleet_lost_events_total": ("host",),
    "sparknet_fleet_pushes_total": ("host",),
    "sparknet_fleet_resets_total": ("host",),
    # time-series plane (obs/tsdb.py) — the embedded rollup store's
    # self-accounting, exported wherever a TSDB is armed (--slo or the
    # fleet collector)
    "sparknet_tsdb_resident_bytes": (),
    "sparknet_tsdb_series": (),
    "sparknet_tsdb_samples_total": (),
    "sparknet_tsdb_dropped_series_total": (),
    # burn-rate SLO plane (obs/slo.py) — objective health + alert
    # counters from the multi-window multi-burn-rate evaluator
    "sparknet_slo_burn_rate": ("slo", "window"),
    "sparknet_slo_error_budget_remaining": ("slo",),
    "sparknet_slo_status": ("slo",),
    "sparknet_slo_alerts_total": ("slo", "severity"),
    # scaling signals (obs/slo.py signals()) — the /signals feed an
    # autoscaler consumes (ROADMAP item 4)
    "sparknet_signal_admission_pressure": (),
    "sparknet_signal_queue_depth_slope": (),
    "sparknet_signal_p99_trend": (),
    "sparknet_signal_round_rate": ("host",),
    "sparknet_signal_error_budget_min": (),
}

# span names by category.  "phase" spans additionally feed the
# sparknet_phase_latency_seconds{phase=...} histogram, so this set IS
# that family's label vocabulary.
CANONICAL_SPANS = {
    "phase": frozenset({
        "assemble", "h2d", "execute", "average",
        "quantize", "allreduce", "dequantize",
        "snapshot", "restore", "verify",
    }),
    "cache": frozenset({"cache_read", "cache_fetch"}),
    # the LM data plane's host-side window sampling (apps/lm_app.py —
    # nests under the producer thread's assemble span in traces)
    "data": frozenset({"sample_text"}),
    # generation serving (serve/generate.py): the two jitted steps of
    # the prefill/decode disaggregation
    "gen": frozenset({"prefill", "decode_step"}),
    # request anatomy (obs/reqtrace.py + serve instrumentation): the
    # per-request lifecycle spans the RequestProfiler folds — a
    # "request" lifetime envelope around queue_wait -> kv_reserve ->
    # (gen) prefill/decode_step -> stream_write per chunk
    "req": frozenset({
        "request", "queue_wait", "kv_reserve", "stream_write",
    }),
}

# the comm-plane span triple tools/trace_report.py folds into its
# compressed-collective section (kept here so the folder and the
# emitters cannot drift apart)
COMM_SPANS = ("quantize", "allreduce", "dequantize")

# doc tokens that look like metric names but aren't (the package
# itself, the native runtime library)
DOC_IGNORED_PREFIXES = ("sparknet_tpu", "sparknet_runtime")
