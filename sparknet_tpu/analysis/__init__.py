"""Framework-aware static analysis: the hot-path invariant linter.

The repo's perf story (PIPELINE's 0.97 overlap, PROFILE's live hidden
fractions, COMM's overlapped collectives, SERVE's zero recompiles)
rests on invariants no runtime test names when they break: no implicit
host<->device syncs in steady-state rounds, no reuse of donated
buffers, disciplined threading across the modules that spawn
producer/comm/watchdog/server threads, and emitter/folder agreement on
every metric and span name.  This package enforces them statically —
each checker is a small AST visitor emitting the shared
:class:`findings.Finding` shape — and ``tools/lint.py --check`` runs
the set against a committed allowlist as a tier-1 guard (the static
sibling of ``tools/perf_gate.py --check``; the dynamic half is
``bench.py --mode=sanitize``).

Checkers
--------
- ``sync_check``     — sync-in-hot-path: ``.item()``, ``float()``/
  ``int()`` on non-shape values, ``np.asarray``/``np.array``,
  ``jax.device_get``, ``block_until_ready`` inside the registered
  round-loop/producer/comm scopes (``hotpaths.HOT_PATHS``) and inside
  any function spawned as a thread target.
- ``donation_check`` — donation discipline: a name used again after
  being passed in a donated position of a ``jax.jit(...,
  donate_argnums=...)`` callable (including across loop iterations,
  the classic reuse bug).
- ``thread_check``   — thread hygiene: anonymous threads, implicit
  daemon policy, un-timeouted ``join()`` outside shutdown paths, bare
  or swallowed ``except`` in thread targets, and a cross-module lock
  acquisition-order graph with cycle detection.
- ``registry_audit`` — trace/metrics registry drift: every emitted
  ``sparknet_*`` metric name and phase-cat ``span(...)`` literal must
  appear in the canonical sets (``analysis.registry``) consumed by
  ``tools/trace_report.py``/``tools/perf_gate.py``/PERF.md, and vice
  versa.

Suppression marker grammar (see ARCHITECTURE.md "Static analysis &
sanitizers"): an inline ``# sparknet: <rule>-ok(<reason>)`` comment on
any line of the flagged statement suppresses that checker's finding
there — ``sync-ok``, ``donation-ok``, ``thread-ok``, ``join-ok``,
``except-ok``, ``lock-ok``.  The reason is mandatory; an empty one is
itself a finding.  Suppressed sites stay enumerable
(``Report.suppressed``) — ``bench.py --mode=sanitize`` lists every
annotated deliberate sync in its artifact.
"""

from sparknet_tpu.analysis.findings import Finding, Report  # noqa: F401
from sparknet_tpu.analysis.runner import scan_package, scan_source  # noqa: F401
