"""The hot-path registry: which scopes the sync checker polices.

"Hot path" means code that runs once per round (or per request) in
steady state, where one implicit device->host sync erases the overlap
the PIPELINE/COMM/PROFILE artifacts measure — the trainer round/step
bodies, the RoundFeed/Prefetcher producer machinery, the comm plane's
dispatch/pace/apply path, the serving forward loop, and the span
fast path.  Setup code (solver construction, checkpoint restore,
dataset staging) deliberately is NOT here: syncing at build time is
free.

Two sources make a scope hot:

1. this explicit registry — ``module-relative path -> qualnames``
   (``Class.method`` or bare function names);
2. any function passed as ``target=`` to ``threading.Thread`` in a
   scanned module (producer/comm/watchdog threads are hot by
   construction — that is where a stray sync silently serializes the
   overlap).

Extending: when a new module grows a per-round loop, add its qualnames
here — the whole-repo ``tools/lint.py --check`` then polices it, and
any deliberate sync it keeps must carry a ``# sparknet:
sync-ok(<reason>)`` marker (ARCHITECTURE.md "Static analysis &
sanitizers").
"""

from __future__ import annotations

from typing import Dict, FrozenSet

HOT_PATHS: Dict[str, FrozenSet[str]] = {
    "solver.py": frozenset({
        "Solver.step",
        "Solver.note_losses",
    }),
    "data/round_feed.py": frozenset({
        "RoundFeed._produce_one",
        "RoundFeed._default_place",
        "RoundFeed.next_round",
        "stack_windows",
    }),
    "data/prefetch.py": frozenset({
        "Prefetcher._run",
        "Prefetcher._put_politely",
        "Prefetcher.__next__",
    }),
    "parallel/trainers.py": frozenset({
        "ParameterAveragingTrainer.round",
        "ParameterAveragingTrainer._place_live",
        "AllReduceTrainer.step",
    }),
    "parallel/comm.py": frozenset({
        "CommPlane.round",
        "CommPlane._dispatch_chunks",
        "CommPlane._pace_chunks",
        "CommPlane._apply_pending_correction",
        "CommPlane._local_call",
        "CommPlane._join_pending",
        "CommPlane.flush_quant_error",
    }),
    "serve/engine.py": frozenset({
        "InferenceEngine.run_padded",
        "InferenceEngine.infer",
    }),
    "serve/batcher.py": frozenset({
        "MicroBatcher._take_batch",
        "MicroBatcher._loop",
        "MicroBatcher.submit",
    }),
    "serve/fleet.py": frozenset({
        "Router.submit",
        "Router._pick",
        "Router._maybe_mirror",
    }),
    "obs/trace.py": frozenset({
        "_Span.__exit__",
        "span",
        "instant",
    }),
    "obs/profile.py": frozenset({
        "RoundProfiler.probe_execute",
        "RoundProfiler.observe_round",
    }),
    "utils/timers.py": frozenset({
        "Timer.stop",
    }),
}


def hot_scopes_for(relpath: str) -> FrozenSet[str]:
    return HOT_PATHS.get(relpath, frozenset())
