"""The shared finding/fixit shape every checker emits, plus the
suppression-marker grammar.

A finding's :meth:`Finding.key` is deliberately line-number-free
(``checker:path:scope:message[#ordinal]``): the committed allowlist
baseline (``tools/lint_allowlist.json``) must survive unrelated edits
shifting line numbers, while still distinguishing two identical
violations in one scope (the ordinal).

Marker grammar — one comment suppresses one checker's rule at one
statement::

    # sparknet: <rule>-ok(<reason>)

where ``<rule>`` is the checker's marker name (``sync``, ``donation``,
``thread``, ``join``, ``except``, ``lock``) and ``<reason>`` is a
mandatory free-text justification.  The marker sits on a line of the
flagged statement (``lineno..end_lineno`` — a black-wrapped call can
carry it on any of its lines) or on the line immediately above it (the
readable placement for statements that fill their line).  Markers with
an empty reason are reported as ``marker`` findings: a suppression
that does not say *why* is a suppression nobody can audit.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# the reason runs to the LAST ')' on the line (anchored), so reasons
# may themselves contain parentheses — "(num_workers,) verdict read"
# must not truncate at its first ')'
MARKER_RE = re.compile(
    r"#\s*sparknet:\s*([a-z]+)-ok\((.*)\)\s*$"
)

# every marker name a checker honors; anything else in a sparknet:
# comment is a typo'd rule and gets flagged (a marker that silently
# suppresses nothing is worse than no marker).  registry-audit
# findings are deliberately NOT site-suppressible — the fix is always
# the canonical registry or the docs, never the emitter.
KNOWN_MARKERS = (
    "sync", "donation", "thread", "join", "except", "lock",
)


@dataclass
class Finding:
    checker: str            # e.g. "sync-in-hot-path"
    path: str               # repo-relative, forward slashes
    line: int               # 1-indexed
    scope: str              # enclosing qualname ("Class.method") or "<module>"
    message: str            # one line: what and why it matters
    fixit: Optional[str] = None   # suggested mechanical fix
    ordinal: int = 0        # disambiguates identical findings in a scope

    @property
    def key(self) -> str:
        base = f"{self.checker}:{self.path}:{self.scope}:{self.message}"
        return base if self.ordinal == 0 else f"{base}#{self.ordinal}"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: [{self.checker}] {self.scope}: {self.message}"
        if self.fixit:
            out += f"\n    fix: {self.fixit}"
        return out


@dataclass
class Suppressed:
    """An annotated (deliberate) site — enumerable, not a failure."""

    checker: str
    path: str
    line: int
    scope: str
    message: str
    reason: str

    def as_dict(self) -> dict:
        return {
            "checker": self.checker, "path": self.path, "line": self.line,
            "scope": self.scope, "message": self.message,
            "reason": self.reason,
        }


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Suppressed] = field(default_factory=list)

    def extend(self, other: "Report") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)

    def finalize(self) -> "Report":
        """Assign ordinals to otherwise-identical findings so baseline
        keys stay unique, and sort for stable output."""
        seen: Dict[str, int] = {}
        for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.checker)
        ):
            base = f"{f.checker}:{f.path}:{f.scope}:{f.message}"
            f.ordinal = seen.get(base, 0)
            seen[base] = f.ordinal + 1
        self.findings.sort(key=lambda f: (f.path, f.line, f.checker))
        self.suppressed.sort(key=lambda s: (s.path, s.line))
        return self


class Markers:
    """Per-file suppression-marker index: ``covers(rule, lo, hi)`` says
    whether any line in [lo - 1, hi] carries ``# sparknet:
    <rule>-ok(...)`` with a non-empty reason.  The scan is over raw
    source lines, so markers inside string literals (e.g. the embedded
    worker sources in ``utils/procs.py``) are indexed too — harmless
    documentation there, and the reason unused markers are NOT
    reported as findings (a string-embedded annotation is deliberate,
    not dead)."""

    def __init__(self, source: str):
        # line -> list of (rule, reason, comment_only)
        self.by_line: Dict[int, List[Tuple[str, str, bool]]] = {}
        self.empty: List[Tuple[int, str]] = []   # (line, rule)
        self.unknown: List[Tuple[int, str]] = []  # (line, rule)
        for i, text in enumerate(source.splitlines(), start=1):
            for m in MARKER_RE.finditer(text):
                rule, reason = m.group(1), m.group(2).strip()
                if rule not in KNOWN_MARKERS:
                    self.unknown.append((i, rule))
                    continue
                if not reason:
                    self.empty.append((i, rule))
                    continue
                comment_only = text.lstrip().startswith("#")
                self.by_line.setdefault(i, []).append(
                    (rule, reason, comment_only)
                )

    def covers(self, rule: str, lo: int, hi: Optional[int]) -> Optional[str]:
        """The reason of the first matching marker in [lo - 1, hi]
        (the statement's lines, or the line immediately above it), else
        None.  ``hi=None`` means single-line.  The line-above lookback
        honors COMMENT-ONLY lines exclusively: a trailing same-line
        marker on the previous statement must not leak onto (and
        silently bless) the next statement's violation."""
        for line in range(max(1, lo - 1), (hi or lo) + 1):
            for r, reason, comment_only in self.by_line.get(line, ()):
                if r == rule and (comment_only or line >= lo):
                    return reason
        return None

    def marker_findings(self, path: str) -> List[Finding]:
        out = []
        for line, rule in self.empty:
            out.append(Finding(
                checker="marker", path=path, line=line, scope="<marker>",
                message=f"{rule}-ok marker with an empty reason",
                fixit="every suppression must say why: "
                f"# sparknet: {rule}-ok(<reason>)",
            ))
        for line, rule in self.unknown:
            out.append(Finding(
                checker="marker", path=path, line=line, scope="<marker>",
                message=f"unknown marker rule {rule!r}",
                fixit="known rules: %s" % ", ".join(KNOWN_MARKERS),
            ))
        return out
