"""Trace/metrics registry audit: emitters and folders must agree.

Collects, across the scanned package:

- every ``registry.counter/gauge/histogram("sparknet_...")`` literal
  (name + ``labels=`` tuple) — the metric emitters;
- every ``span("...")`` / ``obs.span("...")`` literal with its ``cat``
  (default ``"phase"``) — the span emitters;

and cross-checks them against ``analysis.registry``'s canonical sets,
both directions, plus the docs:

- emitted-but-uncanonical: the folding side (``trace_report``,
  ``perf_gate`` fields, dashboards) won't know the name exists;
- canonical-but-never-emitted: the registry documents a ghost;
- label drift: same name, different label tuple;
- docs drift (PERF.md / ARCHITECTURE.md / README.md): every canonical
  metric and phase must appear in the PERF.md telemetry reference, and
  every ``sparknet_*`` token the docs mention must be canonical
  (tokens ending in ``_`` are accepted as explicit prefix mentions).

Dynamic names (f-strings, variables) are skipped — the audit polices
the literal vocabulary, and the framework's instant names are the only
dynamic ones (``fault_{kind}``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from sparknet_tpu.analysis import astutil
from sparknet_tpu.analysis.findings import Finding, Report
from sparknet_tpu.analysis.registry import (
    CANONICAL_METRICS,
    CANONICAL_SPANS,
    DOC_IGNORED_PREFIXES,
)

CHECKER = "registry-audit"

_METRIC_CTORS = ("counter", "gauge", "histogram")
_DOC_TOKEN_RE = re.compile(r"sparknet_[a-z0-9_]+")


class Inventory:
    """What the code actually emits."""

    def __init__(self):
        # name -> [(labels, path, line), ...] — EVERY emitter is kept:
        # two emitters of one name with different label tuples is
        # exactly the drift the audit exists to catch
        self.metrics: Dict[str, List[Tuple[Tuple[str, ...], str, int]]] = {}
        # (cat, name) -> (path, line)
        self.spans: Dict[Tuple[str, str], Tuple[str, int]] = {}


def collect_module(tree: ast.Module, relpath: str, inv: Inventory) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _METRIC_CTORS:
            name = astutil.literal_str(node.args[0]) if node.args else None
            if name and name.startswith("sparknet_"):
                labels: Tuple[str, ...] = ()
                kw = astutil.kwarg(node, "labels")
                if isinstance(kw, (ast.Tuple, ast.List)):
                    labels = tuple(
                        el.value for el in kw.elts
                        if isinstance(el, ast.Constant)
                    )
                inv.metrics.setdefault(name, []).append(
                    (labels, relpath, node.lineno)
                )
        is_span = (
            (isinstance(fn, ast.Name) and fn.id == "span")
            or (isinstance(fn, ast.Attribute) and fn.attr == "span")
        )
        if is_span and node.args:
            name = astutil.literal_str(node.args[0])
            if name is None:
                continue
            cat = astutil.literal_str(astutil.kwarg(node, "cat")) or "phase"
            inv.spans.setdefault((cat, name), (relpath, node.lineno))


def audit(
    inv: Inventory,
    docs: Optional[Dict[str, str]] = None,
) -> Report:
    """Cross-check the inventory against the canonical sets (and the
    docs text when given: ``{filename: content}``)."""
    rep = Report()

    for name, emitters in sorted(inv.metrics.items()):
        for labels, path, line in emitters:
            if name not in CANONICAL_METRICS:
                rep.findings.append(Finding(
                    checker=CHECKER, path=path, line=line,
                    scope="<metrics>",
                    message=f"metric {name!r} emitted but not in the "
                    "canonical registry (analysis/registry.py) — "
                    "folders and dashboards won't know it exists",
                    fixit="add it to CANONICAL_METRICS and the PERF.md "
                    "telemetry reference",
                ))
                break  # one report per name suffices for this class
            if tuple(CANONICAL_METRICS[name]) != tuple(labels):
                # checked per EMITTER: a second module re-registering
                # the name with different labels must not hide behind
                # a canon-conforming first emitter
                rep.findings.append(Finding(
                    checker=CHECKER, path=path, line=line,
                    scope="<metrics>",
                    message=f"metric {name!r} label drift: emits "
                    f"{tuple(labels)!r}, registry says "
                    f"{tuple(CANONICAL_METRICS[name])!r}",
                    fixit="make the emitter and CANONICAL_METRICS agree",
                ))
    for name in sorted(CANONICAL_METRICS):
        if name not in inv.metrics:
            rep.findings.append(Finding(
                checker=CHECKER, path="sparknet_tpu/analysis/registry.py",
                line=1, scope="<metrics>",
                message=f"canonical metric {name!r} is never emitted "
                "(documented ghost)",
                fixit="emit it or drop it from CANONICAL_METRICS",
            ))

    emitted_by_cat: Dict[str, Set[str]] = {}
    for (cat, name), (path, line) in sorted(inv.spans.items()):
        emitted_by_cat.setdefault(cat, set()).add(name)
        canon = CANONICAL_SPANS.get(cat)
        if canon is None or name not in canon:
            rep.findings.append(Finding(
                checker=CHECKER, path=path, line=line, scope="<spans>",
                message=f"span {name!r} (cat={cat!r}) emitted but not "
                "in the canonical span set — trace_report/profile "
                "folding won't attribute it",
                fixit="add it to CANONICAL_SPANS[%r] (and the PERF.md "
                "phase table for phase-cat spans)" % cat,
            ))
    for cat, names in CANONICAL_SPANS.items():
        for name in sorted(names - emitted_by_cat.get(cat, set())):
            rep.findings.append(Finding(
                checker=CHECKER, path="sparknet_tpu/analysis/registry.py",
                line=1, scope="<spans>",
                message=f"canonical span {name!r} (cat={cat!r}) is "
                "never emitted (documented ghost)",
                fixit="emit it or drop it from CANONICAL_SPANS",
            ))

    if docs:
        all_text = "\n".join(docs.values())
        perf = docs.get("PERF.md", "")
        for name in sorted(CANONICAL_METRICS):
            if name not in perf:
                rep.findings.append(Finding(
                    checker=CHECKER, path="PERF.md", line=1,
                    scope="<docs>",
                    message=f"canonical metric {name!r} missing from "
                    "the PERF.md telemetry reference",
                    fixit="add a row to the metrics table",
                ))
        for name in sorted(CANONICAL_SPANS["phase"]):
            if name not in perf:
                rep.findings.append(Finding(
                    checker=CHECKER, path="PERF.md", line=1,
                    scope="<docs>",
                    message=f"canonical phase {name!r} missing from "
                    "the PERF.md telemetry reference",
                    fixit="add it to the phase table",
                ))
        doc_tokens = set(_DOC_TOKEN_RE.findall(all_text))
        for tok in sorted(doc_tokens):
            if any(tok.startswith(p) for p in DOC_IGNORED_PREFIXES):
                continue
            if tok in CANONICAL_METRICS:
                continue
            if tok.endswith("_") and any(
                m.startswith(tok) for m in CANONICAL_METRICS
            ):
                continue  # explicit prefix mention: sparknet_cache_...
            # a doc token may be a stale (renamed/removed) metric
            rep.findings.append(Finding(
                checker=CHECKER, path="<docs>", line=1, scope="<docs>",
                message=f"docs mention {tok!r} which is not a "
                "canonical metric (stale or typo'd name)",
                fixit="fix the docs or register the name",
            ))
    return rep
