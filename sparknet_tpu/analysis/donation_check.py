"""Donation discipline: a name must not be read again after being
passed in a donated position of a ``jax.jit(..., donate_argnums=...)``
callable — XLA deletes (or reuses) the donated buffer, and the next
touch raises ``Array has been deleted`` on real chips (the CPU backend
often silently skips donation, so only the linter and a real-TPU run
catch it).

Two reuse shapes are caught, intraprocedurally:

1. straight-line: ``out = f(state, batch); use(batch)``;
2. loop-carried — the classic one: ``for r in ...: state, _ = f(state,
   batch)`` where ``batch`` is built once OUTSIDE the loop, so
   iteration 2 feeds a donated (deleted) buffer.  (The fix is the
   RoundFeed pattern: place a fresh batch per round, or pass host
   numpy, which the jit re-places per call.)

Donating callables are found two ways: ``X = jax.jit(...,
donate_argnums=(...))`` assignments in the scanned module (``self._x``
or bare names), plus the cross-module registry of the framework's
known donating entry points (``KNOWN_DONATING`` — ``trainer._round``
donates state AND batches since PR 3).

The analysis is a small abstract interpreter over each function body:
``dead`` maps name -> donation line; stores revive, loads of dead
names report.  ``if``/``try`` branches fork the state and merge by
union (possibly-dead is worth reporting); loop bodies run twice so the
second pass models the next iteration.

Suppression: ``# sparknet: donation-ok(<reason>)`` on the reusing
statement (legit when the caller re-places before reuse, or the reuse
is host numpy handed to a donated jit param — numpy args stay valid).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from sparknet_tpu.analysis import astutil
from sparknet_tpu.analysis.findings import Finding, Markers, Report, Suppressed

CHECKER = "donation-discipline"
MARKER = "donation"

# the framework's donating callables, by attribute name: call sites in
# ANY scanned module are held to these positions (trainers.py /
# solver.py construct them; see their donation comments)
KNOWN_DONATING: Dict[str, Tuple[int, ...]] = {
    "_round": (0, 1),      # ParameterAveragingTrainer: state AND batches
    "_jit_round": (0,),    # AllReduceTrainer: state
    "_jit_step": (0,),     # Solver: state
}


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    """donate_argnums of a ``jax.jit(...)`` call, () when absent or
    non-literal."""
    kw = astutil.kwarg(call, "donate_argnums")
    if kw is None:
        return ()
    if isinstance(kw, ast.Constant) and isinstance(kw.value, int):
        return (kw.value,)
    if isinstance(kw, (ast.Tuple, ast.List)):
        out = []
        for el in kw.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


def collect_module_donators(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """``name -> donated positions`` for every ``X = jax.jit(...,
    donate_argnums=...)`` assignment in the module (the last attribute
    segment of the target: ``self._step`` registers ``_step``)."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        name = astutil.dotted(call.func)
        if name not in ("jax.jit", "jit"):
            continue
        pos = _donate_positions(call)
        if not pos:
            continue
        for tgt in node.targets:
            t = astutil.dotted(tgt)
            if t:
                out[t.split(".")[-1]] = pos
    return out


class _Scope:
    """One function's interpretation: dead-name tracking + reporting."""

    def __init__(self, qual: str, relpath: str, markers: Markers,
                 donators: Dict[str, Tuple[int, ...]], rep: Report):
        self.qual = qual
        self.relpath = relpath
        self.markers = markers
        self.donators = donators
        self.rep = rep
        self.reported: Set[Tuple[str, int]] = set()

    # ---- expression walk: loads check deadness, donating calls kill --
    def expr(self, node: ast.AST, dead: Dict[str, int]) -> None:
        if isinstance(node, ast.Call):
            callee = astutil.dotted(node.func)
            leaf = callee.split(".")[-1] if callee else None
            donated = self.donators.get(leaf, ()) if leaf else ()
            self.expr(node.func, dead)
            for a in node.args:
                self.expr(a, dead)
            for kw in node.keywords:
                self.expr(kw.value, dead)
            for p in donated:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    dead[node.args[p].id] = node.lineno
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._check_load(node, dead)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate scope
        for child in ast.iter_child_nodes(node):
            self.expr(child, dead)

    def _check_load(self, node: ast.Name, dead: Dict[str, int]) -> None:
        if node.id not in dead:
            return
        key = (node.id, node.lineno)
        if key in self.reported:
            return
        self.reported.add(key)
        # the donation line stays OUT of the message: Finding.key is
        # the allowlist baseline key and must not shift with the file
        msg = (
            f"'{node.id}' used after being passed in a donated "
            "position (donated buffers are deleted on real chips)"
        )
        reason = self.markers.covers(MARKER, node.lineno, node.lineno)
        if reason is not None:
            self.rep.suppressed.append(Suppressed(
                CHECKER, self.relpath, node.lineno, self.qual, msg, reason,
            ))
        else:
            self.rep.findings.append(Finding(
                checker=CHECKER, path=self.relpath, line=node.lineno,
                scope=self.qual, message=msg,
                fixit="re-place (or rebuild) the buffer before reuse, "
                "pass host numpy instead of a placed array, or annotate "
                "with # sparknet: donation-ok(<why it is still valid>)",
            ))

    # ---- statement walk -------------------------------------------------
    def stores(self, tgt: ast.AST, dead: Dict[str, int]) -> None:
        if isinstance(tgt, ast.Name):
            dead.pop(tgt.id, None)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.stores(el, dead)
        elif isinstance(tgt, ast.Starred):
            self.stores(tgt.value, dead)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            # storing INTO x.attr / x[i] reads x — a load, not a rebind
            self.expr(tgt.value, dead)
            if isinstance(tgt, ast.Subscript):
                self.expr(tgt.slice, dead)

    def block(self, body: List[ast.stmt], dead: Dict[str, int]) -> None:
        for stmt in body:
            self.stmt(stmt, dead)

    def stmt(self, stmt: ast.stmt, dead: Dict[str, int]) -> None:
        if isinstance(stmt, ast.Assign):
            self.expr(stmt.value, dead)
            for t in stmt.targets:
                self.stores(t, dead)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                self._check_load(
                    ast.copy_location(
                        ast.Name(id=stmt.target.id, ctx=ast.Load()),
                        stmt.target,
                    ),
                    dead,
                )
            self.expr(stmt.value, dead)
            self.stores(stmt.target, dead)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.expr(stmt.value, dead)
            self.stores(stmt.target, dead)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.expr(stmt.iter, dead)
            for _pass in range(2):   # second pass = next iteration
                self.stores(stmt.target, dead)
                self.block(stmt.body, dead)
            self.block(stmt.orelse, dead)
        elif isinstance(stmt, ast.While):
            for _pass in range(2):
                self.expr(stmt.test, dead)
                self.block(stmt.body, dead)
            self.block(stmt.orelse, dead)
        elif isinstance(stmt, ast.If):
            self.expr(stmt.test, dead)
            d_then = dict(dead)
            self.block(stmt.body, d_then)
            d_else = dict(dead)
            self.block(stmt.orelse, d_else)
            dead.clear()
            dead.update(d_else)
            dead.update(d_then)   # union: possibly-dead reports
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.expr(item.context_expr, dead)
                if item.optional_vars is not None:
                    self.stores(item.optional_vars, dead)
            self.block(stmt.body, dead)
        elif isinstance(stmt, ast.Try):
            self.block(stmt.body, dead)
            post_body = dict(dead)
            for h in stmt.handlers:
                d_h = dict(post_body)
                self.block(h.body, d_h)
                dead.update(d_h)
            self.block(stmt.orelse, dead)
            self.block(stmt.finalbody, dead)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # separate scope; visited on its own
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                               ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(stmt):
                self.expr(child, dead)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.expr(child, dead)
                elif isinstance(child, ast.stmt):
                    self.stmt(child, dead)


def check_module(tree: ast.Module, relpath: str, markers: Markers) -> Report:
    rep = Report()
    donators = dict(KNOWN_DONATING)
    donators.update(collect_module_donators(tree))
    for qual, fn in astutil.collect_functions(tree).items():
        scope = _Scope(qual, relpath, markers, donators, rep)
        scope.block(fn.body, {})
    return rep
