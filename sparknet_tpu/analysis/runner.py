"""Run every checker over the package (or one source string) and fold
the results into one :class:`findings.Report`, with the committed-
allowlist baseline semantics ``tools/lint.py --check`` enforces:

- a finding whose :attr:`Finding.key` is in the allowlist is *waived*
  (it existed when the baseline was committed, with a written reason);
- any OTHER finding is NEW and fails the check — the gate that keeps
  the next careless ``float(loss)`` out of a round loop;
- allowlist entries that no longer match anything are reported as
  stale (warning, not failure — deleting them is the cleanup).

The allowlist lives at ``tools/lint_allowlist.json``::

    [{"key": "<finding key>", "reason": "<why this one is waived>"}]

and the acceptance bar is that it stays tiny (<= 5 entries): the
preferred fix is always the code fix, the second-best is an inline
``# sparknet: <rule>-ok(<reason>)`` marker at the site (self-
documenting, enumerable), and the allowlist is the last resort for
findings that have no single site to annotate.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Tuple

from sparknet_tpu.analysis import (
    astutil,
    donation_check,
    registry_audit,
    sync_check,
    thread_check,
)
from sparknet_tpu.analysis.findings import Markers, Report
from sparknet_tpu.analysis.hotpaths import hot_scopes_for

DOC_FILES = ("PERF.md", "ARCHITECTURE.md", "README.md")


def scan_source(
    source: str,
    relpath: str = "<fixture>.py",
    hot_scopes: Optional[set] = None,
    audit_registry: bool = False,
) -> Report:
    """Lint one source string — the fixture-test entry point.  Hot
    scopes default to the registry lookup for ``relpath`` (usually
    empty for fixtures, so pass the scopes the fixture exercises)."""
    tree = ast.parse(source)
    markers = Markers(source)
    targets = astutil.thread_target_names(tree)
    rep = Report()
    rep.findings.extend(markers.marker_findings(relpath))
    rep.extend(sync_check.check_module(
        tree, relpath, markers,
        hot_scopes if hot_scopes is not None else hot_scopes_for(relpath),
        targets,
    ))
    rep.extend(donation_check.check_module(tree, relpath, markers))
    t_rep, locks = thread_check.check_module(tree, relpath, markers, targets)
    rep.extend(t_rep)
    rep.extend(thread_check.lock_cycle_findings(
        [(relpath, locks)], {relpath: markers}
    ))
    if audit_registry:
        inv = registry_audit.Inventory()
        registry_audit.collect_module(tree, relpath, inv)
        rep.extend(registry_audit.audit(inv))
    return rep.finalize()


def _iter_py_files(pkg_dir: str):
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [
            d for d in dirnames if d != "__pycache__"
        ]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def scan_package(
    root: str,
    package: str = "sparknet_tpu",
    with_docs: bool = True,
) -> Report:
    """Lint the whole package under ``root`` (the repo checkout)."""
    pkg_dir = os.path.join(root, package)
    rep = Report()
    inv = registry_audit.Inventory()
    all_locks: List[Tuple[str, thread_check._ModuleLocks]] = []
    markers_by_path: Dict[str, Markers] = {}
    for path in _iter_py_files(pkg_dir):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        pkg_rel = os.path.relpath(path, pkg_dir).replace(os.sep, "/")
        with open(path, "r") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            from sparknet_tpu.analysis.findings import Finding

            rep.findings.append(Finding(
                checker="parse", path=relpath, line=e.lineno or 1,
                scope="<module>", message=f"syntax error: {e.msg}",
            ))
            continue
        markers = Markers(source)
        markers_by_path[relpath] = markers
        rep.findings.extend(markers.marker_findings(relpath))
        targets = astutil.thread_target_names(tree)
        rep.extend(sync_check.check_module(
            tree, relpath, markers, hot_scopes_for(pkg_rel), targets,
        ))
        rep.extend(donation_check.check_module(tree, relpath, markers))
        t_rep, locks = thread_check.check_module(
            tree, relpath, markers, targets, module_key=pkg_rel,
        )
        rep.extend(t_rep)
        all_locks.append((relpath, locks))
        registry_audit.collect_module(tree, relpath, inv)
    rep.extend(thread_check.lock_cycle_findings(all_locks, markers_by_path))
    docs = None
    if with_docs:
        docs = {}
        for fname in DOC_FILES:
            p = os.path.join(root, fname)
            if os.path.exists(p):
                with open(p, "r") as f:
                    docs[fname] = f.read()
    rep.extend(registry_audit.audit(inv, docs))
    return rep.finalize()


def load_allowlist(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        entries = json.load(f)
    for e in entries:
        if "key" not in e or not str(e.get("reason", "")).strip():
            raise ValueError(
                "allowlist entries need both 'key' and a non-empty "
                f"'reason': {e!r}"
            )
    return entries


def apply_allowlist(
    rep: Report, entries: List[dict]
) -> Tuple[list, list, list]:
    """Split findings into (new, waived, stale-allowlist-keys)."""
    allowed = {e["key"] for e in entries}
    new = [f for f in rep.findings if f.key not in allowed]
    waived = [f for f in rep.findings if f.key in allowed]
    present = {f.key for f in rep.findings}
    stale = sorted(allowed - present)
    return new, waived, stale
