"""Training-log parser — the ``tools/extra/parse_log.py`` role.

Parses this framework's ``training_log_<ts>*.txt`` format (elapsed
seconds + structured phase messages, ``utils/trainlog.py``) into
train/test row tables and CSV files, so training curves plot without
ad-hoc grepping — the same workflow the reference's parse_log.py +
plot_training_log.py serve for glog output.

Recognized lines:

- ``<sec>: round <r> trained, smoothed_loss <v>``   (app loops)
- ``<sec>: iter <i> smoothed_loss <v>``             (cli train)
- ``<sec>: test output <name> = <v>``               (test phases)
- ``<sec>, i = <r>: <message ...>``                 (round-indexed)
"""

from __future__ import annotations

import csv
import re
from typing import Dict, List, Tuple

_TRAIN_ROUND = re.compile(
    r"^([\d.]+):\s+round\s+(\d+)\s+trained,\s+smoothed_loss\s+([-\d.eE]+)"
)
_TRAIN_ITER = re.compile(
    r"^([\d.]+):\s+iter\s+(\d+)\s+smoothed_loss\s+([-\d.eE]+)"
)
_TEST_OUT = re.compile(
    r"^([\d.]+):\s+test output\s+(\S+)\s+=\s+([-\d.eE]+)"
)
_ROUND_SCORE = re.compile(
    r"^([\d.]+):\s+round\s+(\d+),\s+(\w+)\s+([-\d.eE]+)"
)


def parse_log(path: str) -> Tuple[List[dict], List[dict]]:
    """-> (train_rows, test_rows).

    train rows: {seconds, round_or_iter, smoothed_loss};
    test rows: {seconds, <output name>: value, ...} — consecutive
    ``test output`` lines at one timestamp merge into one row."""
    train: List[dict] = []
    test: List[dict] = []
    pending: Dict[str, float] = {}
    pending_sec = None

    def flush():
        nonlocal pending, pending_sec
        if pending:
            test.append({"seconds": pending_sec, **pending})
        pending, pending_sec = {}, None

    with open(path) as f:
        for line in f:
            line = line.strip()
            m = _TEST_OUT.match(line)
            if m:
                sec = float(m.group(1))
                if pending_sec is not None and sec != pending_sec:
                    flush()
                pending_sec = sec
                pending[m.group(2)] = float(m.group(3))
                continue
            m = _TRAIN_ROUND.match(line) or _TRAIN_ITER.match(line)
            if m:
                flush()
                train.append(
                    {
                        "seconds": float(m.group(1)),
                        "round_or_iter": int(m.group(2)),
                        "smoothed_loss": float(m.group(3)),
                    }
                )
                continue
            m = _ROUND_SCORE.match(line)
            if m:
                # "round R, accuracy A" annotates the pending test row
                if pending_sec is None:
                    pending_sec = float(m.group(1))
                pending.setdefault("round", int(m.group(2)))
                pending[m.group(3)] = float(m.group(4))
                continue
            flush()
    flush()
    return train, test


def write_csvs(train: List[dict], test: List[dict], prefix: str) -> List[str]:
    paths = []
    for rows, kind in ((train, "train"), (test, "test")):
        if not rows:
            continue
        path = f"{prefix}.{kind}.csv"
        keys: List[str] = []
        for row in rows:
            for k in row:
                if k not in keys:
                    keys.append(k)
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            w.writerows(rows)
        paths.append(path)
    return paths
