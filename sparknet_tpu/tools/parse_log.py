"""Training-log parser — the ``tools/extra/parse_log.py`` role.

Parses BOTH experiment-record formats into train/test row tables and
CSV files, so training curves plot without ad-hoc grepping — the same
workflow the reference's parse_log.py + plot_training_log.py serve for
glog output:

- the flat ``training_log_<ts>*.txt`` format (elapsed seconds +
  structured phase messages, ``utils/trainlog.py``), and
- the structured JSONL run log the round-span tracer streams
  (``obs/trace.py``; one JSON object per line) — ``TrainingLog`` lines
  ride in it as ``{"kind": "instant", "name": "log", ...}`` records,
  which are recognized with the SAME line matchers.  Span/other records
  are skipped.

Recognized lines:

- ``<sec>: round <r> trained, smoothed_loss <v>``   (app loops)
- ``<sec>: iter <i> smoothed_loss <v>``             (cli train)
- ``<sec>: test output <name> = <v>``               (test phases)
- ``<sec>, i = <r>: <message ...>``                 (round-indexed)
"""

from __future__ import annotations

import csv
import json
import re
from typing import Dict, Iterable, Iterator, List, Tuple

_TRAIN_ROUND = re.compile(
    r"^([\d.]+):\s+round\s+(\d+)\s+trained,\s+smoothed_loss\s+([-\d.eE]+)"
)
_TRAIN_ITER = re.compile(
    r"^([\d.]+):\s+iter\s+(\d+)\s+smoothed_loss\s+([-\d.eE]+)"
)
_TEST_OUT = re.compile(
    r"^([\d.]+):\s+test output\s+(\S+)\s+=\s+([-\d.eE]+)"
)
_ROUND_SCORE = re.compile(
    r"^([\d.]+):\s+round\s+(\d+),\s+(\w+)\s+([-\d.eE]+)"
)


def is_jsonl_log(path: str) -> bool:
    """Structured-run-log sniff: the first non-blank line is a JSON
    object (the flat format always starts ``<seconds>:``)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                return line.startswith("{")
    return False


def _jsonl_to_lines(f: Iterable[str]) -> Iterator[str]:
    """Reconstruct flat-format lines from JSONL ``log`` records (other
    record kinds — spans, faults, retries — carry no train/test rows
    and are skipped so they cannot split a pending test-row merge)."""
    for raw in f:
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if rec.get("name") != "log":
            continue
        args = rec.get("args") or {}
        msg = args.get("msg", "")
        sec = args.get("elapsed", rec.get("ts_s", 0.0))
        i = args.get("i", -1)
        if isinstance(i, (int, float)) and i >= 0:
            yield f"{sec}, i = {int(i)}: {msg}"
        else:
            yield f"{sec}: {msg}"


def _parse_lines(lines: Iterable[str]) -> Tuple[List[dict], List[dict]]:
    train: List[dict] = []
    test: List[dict] = []
    pending: Dict[str, float] = {}
    pending_sec = None

    def flush():
        nonlocal pending, pending_sec
        if pending:
            test.append({"seconds": pending_sec, **pending})
        pending, pending_sec = {}, None

    for line in lines:
        line = line.strip()
        m = _TEST_OUT.match(line)
        if m:
            sec = float(m.group(1))
            if pending_sec is not None and sec != pending_sec:
                flush()
            pending_sec = sec
            pending[m.group(2)] = float(m.group(3))
            continue
        m = _TRAIN_ROUND.match(line) or _TRAIN_ITER.match(line)
        if m:
            flush()
            train.append(
                {
                    "seconds": float(m.group(1)),
                    "round_or_iter": int(m.group(2)),
                    "smoothed_loss": float(m.group(3)),
                }
            )
            continue
        m = _ROUND_SCORE.match(line)
        if m:
            # "round R, accuracy A" annotates the pending test row
            if pending_sec is None:
                pending_sec = float(m.group(1))
            pending.setdefault("round", int(m.group(2)))
            pending[m.group(3)] = float(m.group(4))
            continue
        flush()
    flush()
    return train, test


def parse_log(path: str) -> Tuple[List[dict], List[dict]]:
    """-> (train_rows, test_rows); auto-detects flat vs JSONL.

    train rows: {seconds, round_or_iter, smoothed_loss};
    test rows: {seconds, <output name>: value, ...} — consecutive
    ``test output`` lines at one timestamp merge into one row."""
    jsonl = is_jsonl_log(path)
    with open(path) as f:
        lines = _jsonl_to_lines(f) if jsonl else f
        return _parse_lines(lines)


def write_csvs(train: List[dict], test: List[dict], prefix: str) -> List[str]:
    paths = []
    for rows, kind in ((train, "train"), (test, "test")):
        if not rows:
            continue
        path = f"{prefix}.{kind}.csv"
        keys: List[str] = []
        for row in rows:
            for k in row:
                if k not in keys:
                    keys.append(k)
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            w.writerows(rows)
        paths.append(path)
    return paths
