"""Net visualization: NetParameter -> graphviz dot text.

Reference role: ``caffe/python/caffe/draw.py:1-213`` (``draw_net_to_file``)
— there it renders through pydot/graphviz; here the dot source is emitted
directly (no third-party dependency; feed the file to ``dot -Tpng`` to
render).  Same visual grammar: one record node per layer colored by type,
octagon nodes per blob, edges labeled with the producing layer's output
size, in-place neuron layers (bottom == top) highlighted green.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sparknet_tpu.config.schema import LayerParameter, NetParameter

# fill colors by layer type (draw.py choose_color_by_layertype)
_COLORS = {
    "Convolution": "#FF5050",
    "Deconvolution": "#FF5050",
    "Pooling": "#FF9900",
    "InnerProduct": "#CC33FF",
    "Attention": "#33CCCC",
}
_DEFAULT_COLOR = "#6495ED"
_NEURON_COLOR = "#90EE90"
_BLOB_STYLE = 'shape=octagon, fillcolor="#E0E0E0", style=filled'


def _first(lst, default):
    return lst[0] if lst else default


def layer_label(layer: LayerParameter, sep: str) -> str:
    """Node label; conv/pool carry kernel/stride/pad like the reference."""
    if layer.type in ("Convolution", "Deconvolution"):
        p = layer.convolution_param
        if p is not None:
            return sep.join([
                layer.name, f"({layer.type})",
                f"kernel size: {_first(p.kernel_size, 1)}",
                f"stride: {_first(p.stride, 1)}",
                f"pad: {_first(p.pad, 0)}",
            ])
    if layer.type == "Pooling" and layer.pooling_param is not None:
        p = layer.pooling_param
        return sep.join([
            layer.name, f"({p.pool} {layer.type})",
            f"kernel size: {p.kernel_size}",
            f"stride: {p.stride}",
            f"pad: {p.pad}",
        ])
    return sep.join([layer.name, f"({layer.type})"])


def edge_label(layer: LayerParameter) -> str:
    """Output-size label on layer->top edges (draw.py get_edge_label)."""
    if layer.type == "Data" and layer.data_param is not None:
        return f"Batch {layer.data_param.batch_size}"
    if (
        layer.type in ("Convolution", "Deconvolution")
        and layer.convolution_param is not None
    ):
        return str(layer.convolution_param.num_output)
    if layer.type == "InnerProduct" and layer.inner_product_param is not None:
        return str(layer.inner_product_param.num_output)
    return ""


def _q(s: str) -> str:
    return '"' + s.replace('"', '\\"') + '"'


def net_to_dot(
    netp: NetParameter,
    rankdir: str = "LR",
    label_edges: bool = True,
    phase: Optional[str] = None,
) -> str:
    """NetParameter -> dot source.  ``phase`` pre-filters with the same
    NetStateRule pass the net compiler uses (``graph.filter_net``)."""
    if phase is not None:
        from sparknet_tpu.config.schema import NetState
        from sparknet_tpu.graph import filter_net

        netp = filter_net(netp, NetState(phase=phase))
    # vertical layouts have free horizontal space -> spaces; horizontal
    # layouts stack the label lines (draw.py get_layer_label)
    sep = " " if rankdir in ("TB", "BT") else "\\n"
    lines: List[str] = [
        f"digraph {_q(netp.name or 'net')} {{",
        f"  rankdir={rankdir};",
        "  node [shape=record];",
    ]
    blob_nodes: Dict[str, None] = {}
    node_lines: List[str] = []
    edge_lines: List[str] = []
    for layer in netp.layer:
        node = f"{layer.name}_{layer.type}"
        in_place = (
            len(layer.bottom) == 1
            and len(layer.top) == 1
            and layer.bottom[0] == layer.top[0]
        )
        color = (
            _NEURON_COLOR if in_place
            else _COLORS.get(layer.type, _DEFAULT_COLOR)
        )
        node_lines.append(
            f"  {_q(node)} [label={_q(layer_label(layer, sep))}, "
            f'fillcolor="{color}", style=filled];'
        )
        for b in layer.bottom:
            blob_nodes.setdefault(b)
            edge_lines.append(f"  {_q(b + '_blob')} -> {_q(node)};")
        for t in layer.top:
            blob_nodes.setdefault(t)
            lbl = edge_label(layer) if label_edges else ""
            attr = f" [label={_q(lbl)}]" if lbl else ""
            edge_lines.append(f"  {_q(node)} -> {_q(t + '_blob')}{attr};")
    for b in blob_nodes:
        node_lines.append(f"  {_q(b + '_blob')} [label={_q(b)}, {_BLOB_STYLE}];")
    lines += node_lines + edge_lines + ["}"]
    return "\n".join(lines) + "\n"


def draw_net_to_file(
    netp: NetParameter,
    filename: str,
    rankdir: str = "LR",
    label_edges: bool = True,
    phase: Optional[str] = None,
) -> None:
    """Write dot source to ``filename`` (draw.py draw_net_to_file's '.raw'
    mode; run graphviz on the result for an image)."""
    with open(filename, "w") as f:
        f.write(net_to_dot(netp, rankdir, label_edges, phase))
