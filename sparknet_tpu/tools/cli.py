"""Command-line interface — the ``caffe`` binary's brew commands.

Reference: ``caffe/tools/caffe.cpp:28-55`` registers train/test/time/
device_query; flag semantics preserved where they make sense on TPU:

    python -m sparknet_tpu.tools.cli train --solver=S [--snapshot=F.solverstate.npz]
        [--weights=F.caffemodel] [--data=DIR] [--sigint_effect=stop|snapshot|none]
    python -m sparknet_tpu.tools.cli test --model=N --weights=F --data=DIR|DB
        [--iterations=50] [--allow_synthetic]
    python -m sparknet_tpu.tools.cli time --model=N [--iterations=50]
    python -m sparknet_tpu.tools.cli device_query
    python -m sparknet_tpu.tools.cli serve --net=N [--weights=F] [--port=P]

``--gpu=...`` becomes ``--devices=N`` (first N local TPU devices as the dp
mesh; the P2PSync role is AllReduceTrainer).  ``test`` scores real data:
``--data`` (CIFAR binary dir or SNDB path) or the net's own Data-layer
``data_param.source``; ``--allow_synthetic`` is a smoke-test-only escape.
``train`` falls back to synthetic batches when ``--data`` is omitted.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, Optional

import numpy as np


def _load_net(path):
    from sparknet_tpu import config

    return config.load_net_prototxt(path)


def _synthetic_batches(net, tau: int, seed: int = 0) -> Dict[str, np.ndarray]:
    from sparknet_tpu.data.source import synthetic_batches

    return synthetic_batches(net, tau, seed)


def _declared_feed_shapes(netp, phase):
    """Declared data-layer shapes for one phase view, straight from the
    config (no net build): the first host-fed layer that can state its
    shapes, or None."""
    from sparknet_tpu.config.schema import NetState
    from sparknet_tpu.graph import filter_net
    from sparknet_tpu.ops import data_layers as dl
    from sparknet_tpu.ops.base import create_layer

    filtered = filter_net(netp, NetState(phase=phase))
    for lp in filtered.layer:
        try:
            layer = create_layer(lp, phase)
        except Exception:
            continue
        if isinstance(layer, dl._HostFed):
            shapes = layer.declared_shapes()
            if shapes:
                return [tuple(s) for s in shapes]
    return None


def _stage_cached_dir(url: str, cache_dir, cache_bytes) -> str:
    """Materialize an object-store root as a local directory view whose
    files are chunk-cache entries (verified, refetch-on-corrupt): list
    the store, pull every ``*.bin`` through the cache, symlink the
    verified chunk paths under ``<cache>/views/<key>/`` — the CIFAR
    loader reads ordinary local files, the network is touched once."""
    import tempfile

    from sparknet_tpu.data import chunk_cache, object_store

    store = object_store.open_store(url)
    cache = chunk_cache.ChunkCache(
        cache_dir or tempfile.mkdtemp(prefix="sparknet_cache_"),
        byte_budget=chunk_cache.parse_bytes(cache_bytes),
    )
    view = os.path.join(
        cache.root, "views", chunk_cache.ChunkCache.key_for(store.url, "")
    )
    os.makedirs(view, exist_ok=True)
    names = [n for n in store.list("") if n.endswith(".bin")]
    if not names:
        raise SystemExit(f"train: no *.bin objects under {url!r}")
    for name in names:
        path = cache.local_path(store, name)
        link = os.path.join(view, name)
        # object names may carry path separators (recursive listings)
        os.makedirs(os.path.dirname(link) or view, exist_ok=True)
        if os.path.islink(link) or os.path.exists(link):
            os.unlink(link)
        os.symlink(path, link)
    return view


def cmd_train(args) -> int:
    # pure argument conflicts fail BEFORE any model/device setup
    if args.resume and (args.snapshot or args.weights):
        print(
            "train: --resume scans the solver's snapshot_prefix and "
            "conflicts with --snapshot/--weights — pass one or the other",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "compress", "none") != "none" or getattr(
        args, "overlap_avg", False
    ):
        # cli train's dp mode is per-step gradient allreduce (the
        # P2PSync analog) — there is no tau-step parameter delta to
        # quantize or overlap.  The comm plane lives on the parameter-
        # averaging drivers.
        print(
            "train: --compress/--overlap_avg apply to tau-round "
            "parameter averaging — use the averaging apps "
            "(sparknet_tpu.apps.cifar_app / cifar_db_app / "
            "imagenet_app / imagenet_run_db_app); cli train's "
            "--devices mode is per-step gradient allreduce",
            file=sys.stderr,
        )
        return 1

    # --publish_to implies the health sentry: a publish carries the
    # sentry's verdict, and an unaudited run has no verdict to attach
    if args.publish_to and not args.health and not args.health_policy:
        args.health = "warn"

    # telemetry first, so restore/snapshot spans and the /metrics
    # sidecar cover the whole run (both flags off -> pure no-op)
    from sparknet_tpu import obs

    run_obs = obs.start_from_args(args)
    try:
        return _cmd_train(args)
    finally:
        run_obs.close()


def _cmd_train(args) -> int:
    import jax

    from sparknet_tpu import config
    from sparknet_tpu.data import CifarLoader, MinibatchSampler
    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import SignalHandler, SolverAction, TrainingLog

    solver_param = config.load_solver_prototxt(args.solver)
    trainer = None
    if args.devices > 1:
        # the `caffe train --gpu=0,1,...` analog (tools/caffe.cpp:213-216
        # spins P2PSync): synchronous gradient allreduce over a dp mesh.
        # Reference semantics: the config's batch_size is per-device, the
        # effective batch is batch * devices (caffe/docs/multigpu.md).
        from sparknet_tpu.config import replace_data_layers
        from sparknet_tpu.parallel import AllReduceTrainer, make_mesh

        n = args.devices
        if len(jax.devices()) < n:
            print(
                f"train: --devices={n} but jax sees "
                f"{len(jax.devices())} device(s)",
                file=sys.stderr,
            )
            return 1
        netp0 = config.resolve_solver_net(solver_param)
        train_shapes = _declared_feed_shapes(netp0, "TRAIN")
        test_shapes = _declared_feed_shapes(netp0, "TEST") or train_shapes
        if train_shapes is None:
            print(
                "train: --devices needs data layers with declared shapes "
                "(HostData/Input/MemoryData)",
                file=sys.stderr,
            )
            return 1
        # reference semantics: training batch scales by device count,
        # the TEST view keeps the config's own batch (caffe's --gpu
        # multiplies the training batch only, docs/multigpu.md)
        scaled = [(s[0] * n,) + tuple(s[1:]) for s in train_shapes]
        netp = replace_data_layers(netp0, scaled, test_shapes)
        solver = Solver(solver_param, net_param=netp)
        mesh = make_mesh({"dp": n}, devices=jax.devices()[:n])
        trainer = AllReduceTrainer(solver, mesh)
        print(f"allreduce data-parallel over {n} devices")
    else:
        solver = Solver(solver_param)
    # one prefix rule for BOTH writing snapshots and --resume's scan
    prefix = solver_param.snapshot_prefix or "snapshot"
    # --journal/--no_journal: the crash-consistency round ledger
    # (io/journal.py) beside the snapshots.  Auto default: a --resume
    # that finds an existing ledger consumes it (journal-guided
    # restore rewinds to the last COMMITTED boundary).
    from sparknet_tpu.io import journal as journal_mod

    jr = journal_mod.journal_from_args(
        args, journal_mod.default_journal_path(prefix),
        resuming=args.resume,
    )
    if jr is not None:
        print(f"run journal: {jr.path} (fsync={jr.fsync})")
    # training-health sentry (--health/--health_policy): flips the
    # solver's in-graph numerics audit on and guards every window;
    # rollback restores the newest verified snapshot under the same
    # prefix the snapshots use (obs/health.py)
    from sparknet_tpu.obs import health as health_mod

    sentry = health_mod.sentry_from_args(args, solver, echo=print)
    if sentry is not None:
        sentry.restore_fn = health_mod.make_restore_fn(
            solver, prefix, trainer=trainer
        )
    if args.resume:
        # fault-tolerant resume: newest CRC-valid snapshot under the
        # solver's snapshot_prefix; corrupt ones are quarantined and the
        # scan falls back (io/checkpoint.restore_newest_valid).  With a
        # run journal the restore is LEDGER-GUIDED: rewind to the last
        # committed round boundary (a snapshot published for a round
        # whose commit never landed is ignored, its round re-executes)
        # and put the journaled driver state (sentry EMA/cooldown) back.
        try:
            if jr is not None and jr.last_committed_round is not None:
                state, used, job_state, jinfo = (
                    checkpoint.restore_newest_valid_journaled(
                        solver, prefix, jr
                    )
                )
                if (
                    job_state
                    and sentry is not None
                    and "sentry" in job_state
                ):
                    sentry.load_state(job_state["sentry"])
                if jinfo["in_flight_round"] is not None:
                    from sparknet_tpu import obs as _obs_mod

                    tm = _obs_mod.training_metrics()
                    if tm is not None:
                        tm.recover_replayed.inc()
                    print(
                        "journal: round %d was in flight at the crash "
                        "— it re-executes (never skipped, never "
                        "double-committed)" % jinfo["in_flight_round"]
                    )
            else:
                state, used = checkpoint.restore_newest_valid(
                    solver, prefix
                )
        except (FileNotFoundError, checkpoint.SnapshotCorrupt) as e:
            print(f"train: --resume: {e}", file=sys.stderr)
            return 1
        if trainer is not None:
            state = trainer.shard_state(state)
        print(f"resumed from {used} at iter {int(state.iter)}")
    elif args.snapshot:
        state = checkpoint.restore(solver, args.snapshot)
        if trainer is not None:
            state = trainer.shard_state(state)
        print(f"resumed from {args.snapshot} at iter {int(state.iter)}")
    else:
        state = (
            trainer.init_state(seed=args.seed)
            if trainer is not None
            else solver.init_state(seed=args.seed)
        )
        if args.weights:
            state = checkpoint.load_weights_into_state(solver, state, args.weights)
            if trainer is not None:
                state = trainer.shard_state(state)
            print(f"warm-started weights from {args.weights}")

    effects = {
        "stop": SolverAction.STOP,
        "snapshot": SolverAction.SNAPSHOT,
        "none": SolverAction.NONE,
    }
    log = TrainingLog(tag="train")

    sampler = None
    if args.data:
        from sparknet_tpu.data import object_store

        data_dir = args.data
        if object_store.is_object_store_url(args.data):
            # stage the CIFAR binaries through the chunk cache: verified
            # local files, CRC-checked on every read, refetched only
            # when missing/evicted/corrupt — a re-run is I/O-free
            data_dir = _stage_cached_dir(
                args.data, args.cache_dir, args.cache_bytes
            )
            print(f"staged {args.data} -> {data_dir} (chunk cache)")
        loader = CifarLoader(data_dir)
        x, y = loader.minibatches(
            solver.net.blob_shapes[solver.net.feed_blobs[0]][0]
        )
        sampler = MinibatchSampler(
            {"data": x, "label": y}, num_sampled_batches=args.tau, seed=args.seed
        )

    max_iter = args.max_iter or solver_param.max_iter or 1000
    snap_every = solver_param.snapshot
    # --async_snapshot: serialization + file writes happen on a worker
    # thread so the train loop keeps stepping (Orbax-style async
    # checkpointing; the snapshot itself still publishes atomically)
    ckpt = checkpoint.AsyncCheckpointer() if args.async_snapshot else None
    # iter tracked host-side: it advances exactly tau per window, and a
    # per-round device_get of state.iter would sync the async dispatch
    # queue (and degrade the put lane on the axon relay — PERF.md)
    it = int(jax.device_get(state.iter))
    # pipelined round feed: the next window is assembled and device_put
    # on a producer thread while the current one trains (--serial_feed
    # restores assemble-then-put on this loop, identical numerics)
    from sparknet_tpu.data import RoundFeed

    # --shuffle_epochs: deterministic epoch passes over the partition,
    # re-permuting the minibatch ORDER each epoch (shuffle-by-assignment
    # over indices — the table moves, the resident arrays do not).
    # Keyed by the ABSOLUTE round (start iter // tau + r): a resumed
    # run continues the same schedule mid-epoch.
    epoch_draw = None
    if sampler is not None and args.shuffle_epochs > 1:
        from sparknet_tpu.data import shuffle as shuffle_mod

        windows_per_epoch = max(1, sampler.total // args.tau)
        base_round = it // args.tau
        perm_memo = {}

        def epoch_draw(r):
            abs_r = base_round + r
            e = abs_r // windows_per_epoch
            if e not in perm_memo:
                perm_memo.clear()  # one epoch's table at a time
                perm_memo[e] = shuffle_mod.permutation(
                    sampler.total, args.seed, e
                )
            pos = (abs_r % windows_per_epoch) * args.tau
            idx = perm_memo[e][pos : pos + args.tau]
            return {k: v[idx] for k, v in sampler.batches.items()}

    def assemble(r, out):
        if epoch_draw is not None:
            return epoch_draw(r)
        return (
            sampler.next_window()
            if sampler
            else _synthetic_batches(solver.net, args.tau)
        )

    feed = RoundFeed(
        assemble,
        sharding=trainer.batch_sharding if trainer is not None else None,
        pipelined=not args.serial_feed,
        num_rounds=max(0, -(-(max_iter - it) // args.tau)),
    )
    r = 0

    def job_extra():
        # the full-job-state companion of a snapshot: driver-side
        # scalars a plain TrainState restore silently resets
        extra = {"cursor": {"iter": it, "round": it // args.tau}}
        if sentry is not None:
            extra["sentry"] = sentry.export_state()
        return extra

    # a journaled async boundary commits once its publish is CONFIRMED
    # (the next save/wait joins the worker): (round, iter) awaiting ref
    async_pending = None

    def commit_async_published():
        nonlocal async_pending
        if jr is None or async_pending is None or ckpt is None:
            return
        paths_done = ckpt.last_paths
        if paths_done:
            pr, pit = async_pending
            async_pending = None
            jr.commit_round(
                pr, iter=pit,
                snapshot=os.path.basename(paths_done[1]),
            )

    # the context manager guarantees the previous handler chain comes
    # back even when a step raises (no leaked handlers on exceptions)
    with SignalHandler(
        sigint_effect=effects[args.sigint_effect],
        sighup_effect=effects[args.sighup_effect],
    ) as handler:
        try:
            while it < max_iter:
                abs_r = it // args.tau
                if jr is not None:
                    # write-ahead intent: restart knows this round was
                    # in flight whatever happens next
                    jr.begin_round(abs_r, iter=it, cursor=abs_r)
                batches = feed.next_round(r)
                stepper = trainer if trainer is not None else solver
                if sentry is not None:
                    state, _ = sentry.guarded_step(
                        stepper, state, batches, round_index=r
                    )
                else:
                    state, _ = stepper.step(state, batches)
                r += 1
                it += args.tau
                # throttled logging (SolverParameter.display semantics,
                # solver.cpp:237): reading smoothed_loss is the device
                # sync point, so it runs once per display interval, not
                # per window
                disp = solver_param.display or args.tau
                if it % disp < args.tau:
                    log.log(
                        f"iter {it} smoothed_loss {solver.smoothed_loss:.4f}"
                    )
                action = handler.get_action()
                if action == SolverAction.SNAPSHOT or (
                    snap_every
                    and it % snap_every < args.tau
                    and it >= snap_every
                ):
                    if ckpt is not None:
                        # publish the PREVIOUS write and commit it
                        # BEFORE the next save spawns: reading
                        # last_paths after save() could race a fast
                        # new write and attach ITS ref to the old
                        # round's commit record
                        ckpt.wait()
                        commit_async_published()
                        ckpt.save(
                            solver, state, prefix,
                            extra_state=job_extra(),
                        )
                        async_pending = (abs_r, it)
                        log.log(f"async snapshot started at iter {it}")
                    else:
                        paths = checkpoint.snapshot(
                            solver, state, prefix,
                            extra_state=job_extra(),
                        )
                        if jr is not None:
                            # the durable boundary: commit rides the
                            # published snapshot ref
                            jr.commit_round(
                                abs_r, iter=it,
                                snapshot=os.path.basename(paths[1]),
                            )
                        log.log(f"snapshotted to {paths[0]}")
                if action == SolverAction.STOP:
                    log.log("stop requested; snapshotting and exiting")
                    if ckpt is not None:
                        ckpt.wait()  # same ordering rule as above
                        commit_async_published()
                        ckpt.save(
                            solver, state, prefix,
                            extra_state=job_extra(),
                        )
                        async_pending = (abs_r, it)
                    else:
                        paths = checkpoint.snapshot(
                            solver, state, prefix,
                            extra_state=job_extra(),
                        )
                        if jr is not None:
                            jr.commit_round(
                                abs_r, iter=it,
                                snapshot=os.path.basename(paths[1]),
                            )
                    break
        except health_mod.SentryHalt as e:
            # deliberately NO snapshot here: the live weights are the
            # poisoned ones the sentry just condemned.  The flight
            # bundle (if armed) was dumped by the sentry; /healthz
            # reads 503 until the process exits.
            log.log(f"training halted by the health sentry: {e}")
            if ckpt is not None:
                ckpt.wait()  # publish any PRE-anomaly async snapshot
                commit_async_published()
                ckpt.close()  # detach the SIGTERM/atexit drain hooks
            if jr is not None:
                jr.close()
            return 1
        finally:
            # a step/snapshot exception must not leak the producer
            # thread (and its in-flight device batches)
            feed.stop()
        if ckpt is not None:
            paths = ckpt.wait()
            commit_async_published()
            ckpt.close()
            if paths:
                log.log(f"final async snapshot: {paths[0]}")
    if jr is not None:
        jr.close()
    if args.publish_to:
        # train-to-serve delivery (serve/publish.py): the final state
        # publishes ONLY with a passing sentry verdict attached to its
        # CRC manifest — the delivery watcher (cli serve --watch)
        # re-verifies both before any canary sees traffic.  A SentryHalt
        # never reaches here: condemned weights are never published.
        from sparknet_tpu.serve import publish as publish_mod

        verdict = publish_mod.verdict_from_sentry(sentry)
        try:
            paths = publish_mod.publish_snapshot(
                solver, state, args.publish_to, verdict
            )
        except publish_mod.PublishRefused as e:
            print(f"train: {e}", file=sys.stderr)
            return 1
        log.log(
            f"published verified snapshot {paths[0]} -> "
            f"{args.publish_to} (verdict: {verdict['reason']})"
        )
    return 0


def cmd_test(args) -> int:
    from sparknet_tpu.config import parse_solver_prototxt
    from sparknet_tpu.data.source import resolve_batches
    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.solver import Solver

    netp = _load_net(args.model)
    solver = Solver(
        parse_solver_prototxt('base_lr: 0.0 lr_policy: "fixed"'), net_param=netp
    )
    state = solver.init_state(0)
    if args.weights:
        state = checkpoint.load_weights_into_state(solver, state, args.weights)
    # real data: --data (CIFAR dir or SNDB path) or the net's own Data
    # layer source; --allow_synthetic is an explicit smoke-test escape
    batches = resolve_batches(
        solver.test_net,
        netp,
        args.data,
        args.iterations,
        phase="TEST",
        allow_synthetic=args.allow_synthetic,
    )
    scores = solver.test_and_store_result(state, batches)
    for name, total in scores.items():
        print(f"{name} = {total / args.iterations:.4f}")
    return 0


def cmd_time(args) -> int:
    import jax

    from sparknet_tpu.config import parse_solver_prototxt
    from sparknet_tpu.net import JaxNet
    from sparknet_tpu.utils.profiler import format_profile, profile_net

    netp = _load_net(args.model)
    net = JaxNet(netp, phase="TRAIN")
    params, stats = net.init(0)
    batch = {k: v[0] for k, v in _synthetic_batches(net, 1).items()}
    result = profile_net(net, params, stats, batch, iterations=args.iterations)
    print(format_profile(result))
    return 0


def cmd_device_query(args) -> int:
    import jax

    for d in jax.devices():
        print(
            f"device {d.id}: platform={d.platform} kind={d.device_kind} "
            f"process={d.process_index}"
        )
    return 0


def cmd_convert_imageset(args) -> int:
    """``convert_imageset [--shuffle] [--resize WxH] [--backend B] ROOT
    LISTFILE DB`` — build a DB of Datum records from an image tree + a
    "<relpath> <label>" listfile (reference:
    ``caffe/tools/convert_imageset.cpp``).  ``--backend sndb`` (default)
    writes the native record format; ``--backend lmdb`` / ``leveldb``
    write the Caffe interchange formats through ``io/lmdb.py`` /
    ``io/leveldb.py``."""
    import os

    from PIL import Image

    entries = []
    with open(args.listfile) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            name, label = line.rsplit(None, 1)
            entries.append((name, int(label)))
    if args.shuffle:  # FLAGS_shuffle
        np.random.RandomState(args.seed).shuffle(entries)

    images, labels = [], []
    for name, label in entries:
        img = Image.open(os.path.join(args.root, name))
        img = img.convert("L" if args.gray else "RGB")
        if args.resize_width and args.resize_height:
            img = img.resize((args.resize_width, args.resize_height))
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        images.append(np.ascontiguousarray(arr.transpose(2, 0, 1)))
        labels.append(label)
    if not images:
        print("convert_imageset: empty listfile", file=sys.stderr)
        return 1
    shapes = {im.shape for im in images}
    if args.check_size and len(shapes) > 1:
        print(f"convert_imageset: sizes differ: {shapes}", file=sys.stderr)
        return 1
    if len(shapes) > 1:
        raise SystemExit(
            "images have differing sizes; pass --resize_width/--resize_height"
        )
    _write_backend_db(args.backend, args.db, np.stack(images), labels)
    print(f"Processed {len(labels)} files.")
    return 0


def _write_backend_db(backend: str, db: str, images, labels) -> None:
    """One Datum-DB writer dispatch for every converter CLI."""
    if backend == "lmdb":
        from sparknet_tpu.io import lmdb

        lmdb.write_datum_lmdb(db, images, labels)
    elif backend == "leveldb":
        from sparknet_tpu.io import leveldb

        leveldb.write_datum_leveldb(db, images, labels)
    else:
        from sparknet_tpu import runtime

        runtime.write_datum_db(db, images, np.asarray(labels))


def cmd_convert_mnist(args) -> int:
    """``convert_mnist IMAGES LABELS DB [--backend B] [--pairs N]`` —
    idx files -> Datum DB (reference: ``examples/mnist/
    convert_mnist_data.cpp``); ``--pairs N`` packs N random 2-channel
    image pairs with same-class labels instead (``examples/siamese/
    convert_mnist_siamese_data.cpp``)."""
    from sparknet_tpu.data import mnist

    images = mnist.read_idx_images(args.images)
    labels = mnist.read_idx_labels(args.labels)
    if len(images) != len(labels):
        print(
            f"convert_mnist: {len(images)} images vs {len(labels)} labels",
            file=sys.stderr,
        )
        return 1
    if args.pairs:
        images, labels = mnist.make_pairs(
            images, labels, args.pairs, seed=args.seed
        )
    _write_backend_db(args.backend, args.db, images, labels)
    print(f"Processed {len(labels)} records.")
    return 0


def cmd_classify(args) -> int:
    """``classify --model D.prototxt --weights W.caffemodel [--mean M]
    [--labels L.txt] [--topk 5] IMAGE...`` — single-image inference with
    top-k class output (reference: ``examples/cpp_classification/
    classification.cpp``).  The deploy net's input size drives the
    resize; mean may be a binaryproto or comma-separated channel
    values."""
    import os

    import jax
    from PIL import Image

    from sparknet_tpu import config, models
    from sparknet_tpu.io import caffemodel
    from sparknet_tpu.net import JaxNet

    netp = (
        config.load_net_prototxt(args.model)
        if args.model.endswith(".prototxt")
        else models.load_model(args.model)
    )
    net = JaxNet(netp, phase="TEST")
    if len(net.feed_blobs) > 1:
        # train/test config: derive the deploy view (Input data, losses
        # -> prob) like the BVLC deploy.prototxts do
        try:
            netp = models.deploy_variant(netp)
        except ValueError as e:
            print(f"classify: {e}", file=sys.stderr)
            return 1
        net = JaxNet(netp, phase="TEST")
        print("classify: derived deploy view from train/test config",
              file=sys.stderr)
    data_blob = net.feed_blobs[0]
    _, c, h, w = net.blob_shapes[data_blob]
    params, stats = net.init(0)
    if args.weights:
        params, stats = caffemodel.apply_blobs(
            net, params, stats, caffemodel.load_weights(args.weights)
        )

    mean = _load_mean_arg(args.mean) if args.mean else None
    if mean is not None:
        if mean.ndim == 1:
            mean = mean.reshape(-1, 1, 1)
        elif mean.shape[1] < h or mean.shape[2] < w:
            print(
                f"classify: mean image {mean.shape[1]}x{mean.shape[2]} "
                f"is smaller than the net input {h}x{w}",
                file=sys.stderr,
            )
            return 1
    labels = None
    if args.labels:
        with open(args.labels) as f:
            labels = [l.strip() for l in f if l.strip()]

    if mean is not None and (mean.shape[1] > h or mean.shape[2] > w):
        # a larger mean image center-crops to the input (the reference
        # resizes; crop keeps exact mean semantics for the standard
        # 256-mean/227-input case); (C,1,1) value means broadcast as-is
        off_h = (mean.shape[1] - h) // 2
        off_w = (mean.shape[2] - w) // 2
        mean = mean[:, off_h:off_h + h, off_w:off_w + w]

    fwd = jax.jit(net.forward)
    for path in args.images:
        img = Image.open(path).convert("L" if c == 1 else "RGB")
        if args.oversample:
            # resize to the oversample source dims, then 10-crop at the
            # net input size and score-average (classifier.py:47-93)
            from sparknet_tpu.data.transformer import oversample_chw

            src = args.resize or max(256, h, w)
            if src < h or src < w:
                print(
                    f"classify: --resize {src} is smaller than the net "
                    f"input {h}x{w}; oversample crops need a larger "
                    "source",
                    file=sys.stderr,
                )
                return 1
            img = img.resize((src, src), Image.BILINEAR)
        else:
            img = img.resize((w, h), Image.BILINEAR)
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        chw = arr.transpose(2, 0, 1)
        if args.oversample:
            crops = oversample_chw(chw, h, w)
            if mean is not None:
                crops = crops - mean[None]
            batch = {data_blob: crops}
        else:
            if mean is not None:
                chw = chw - mean
            batch = {data_blob: chw[None]}
        blobs = fwd(params, stats, batch)
        # "prob" if the deploy net names one (the BVLC convention),
        # else the last layer's top; apply softmax if the scores are
        # not already a distribution (deploy nets often end at fc)
        score_blob = (
            "prob"
            if "prob" in net.blob_shapes
            else net.net_param.layer[-1].top[0]
        )
        out = np.asarray(blobs[score_blob])
        # oversample: average the 10 crops' outputs (classifier.py:81-93)
        scores = out.reshape(out.shape[0], -1).mean(axis=0)
        if scores.min() < 0 or scores.sum() > 1.001:
            e = np.exp(scores - scores.max())
            scores = e / e.sum()
        top = np.argsort(scores)[::-1][: args.topk]
        print(f"---------- Prediction for {path} ----------")
        for i in top:
            name = labels[i] if labels and i < len(labels) else f"class {i}"
            print(f'{scores[i]:.4f} - "{name}"')
    return 0


def cmd_serve(args) -> int:
    """``serve --net D.prototxt|zoo-name [--weights W] [--port P]
    [--buckets 1,4,16,64] [--max_wait_ms 2] [--queue 256]
    [--replicas N] [--watch PUBLISH_DIR] [--canary_frac F]`` — run the
    inference serving front-end (``sparknet_tpu/serve/``): jitted
    forward pre-compiled per batch bucket, dynamic micro-batching,
    ``/predict`` + ``/healthz`` + ``/metrics``, SIGTERM graceful drain.
    ``--replicas N`` serves a fleet (``serve/fleet.py``): N
    shared-nothing replicas behind a load-shedding router;
    ``--watch`` adds the delivery controller (``serve/delivery.py``)
    canarying snapshots that ``cli train --publish_to`` publishes
    there, promoting or rolling back with no restart.

    ``--generate`` serves a TransformerLM checkpoint instead
    (``serve/generate.py``): prefill/decode-disaggregated greedy
    decoding over a paged KV arena with continuous batching, token
    streaming on chunked-NDJSON ``POST /generate``; the fleet and
    delivery flags compose unchanged (streams resume on a sibling
    replica after a replica death, promotes drop zero in-flight
    decodes)."""
    from sparknet_tpu import config, models, obs
    from sparknet_tpu.serve import (
        DeliveryController,
        GenerationEngine,
        InferenceEngine,
        ReplicaPool,
        Router,
        ServeServer,
    )

    if args.generate:
        from sparknet_tpu.models.transformer_lm import TransformerLM

        lm = TransformerLM(
            dim=args.lm_dim, depth=args.lm_depth, heads=args.lm_heads,
            seq_len=args.lm_seq_len,
        )
        gen_buckets = [
            int(b) for b in args.prefill_buckets.split(",") if b.strip()
        ]

        def make_engine(weights=None):
            return GenerationEngine(
                lm,
                weights=weights if weights is not None else args.weights,
                prefill_buckets=gen_buckets,
                max_streams=args.max_streams,
                kv_blocks=args.kv_blocks,
                kv_block_size=args.kv_block_size,
            )

    else:
        if not args.net:
            print("serve: --net is required without --generate",
                  file=sys.stderr)
            return 2
        netp = (
            config.load_net_prototxt(args.net)
            if args.net.endswith(".prototxt")
            else models.load_model(args.net)
        )
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]

        def make_engine(weights=None):
            return InferenceEngine(
                netp,
                weights=weights if weights is not None else args.weights,
                buckets=buckets,
                output_blob=args.output_blob,
                compute_dtype=args.dtype or None,
            )

    # telemetry (--obs/--ship_to/...): the fleet registers its series on
    # the shared training registry so the PR-10 shipper ships the
    # per-replica/fleet autoscaling signals unchanged
    run_obs = obs.start_from_args(args)
    delivery = None
    try:
        if args.replicas > 1 or args.watch:
            tm = obs.training_metrics()
            pool = ReplicaPool(
                make_engine,
                replicas=args.replicas,
                max_queue=args.queue,
                max_wait_ms=args.max_wait_ms,
                registry=tm.registry if tm is not None else None,
                stream=args.generate,
            )
            router = Router(
                pool, max_inflight=args.queue,
                canary_frac=args.canary_frac,
            )
            if args.generate:
                print(
                    "serve: generation fleet of %d replica(s) warmed "
                    "(prefill buckets %s, %d decode slots, %d x %d "
                    "KV blocks each) — POST /generate streams NDJSON"
                    % (
                        len(pool.replicas), gen_buckets,
                        args.max_streams, args.kv_blocks,
                        args.kv_block_size,
                    )
                )
            else:
                print(
                    "serve: fleet of %d replica(s) warmed (%d bucket "
                    "programs each: %s), input %s"
                    % (
                        len(pool.replicas), len(buckets), buckets,
                        pool.item_shape,
                    )
                )
            if args.watch:
                delivery = DeliveryController(
                    pool, router, args.watch,
                    cache_dir=args.cache_dir,
                    decision_requests=args.decision_requests,
                    divergence_max=args.divergence_max,
                    echo=print,
                ).start()
                print(f"serve: delivery watcher on {args.watch}")
            server = ServeServer(
                router=router,
                delivery=delivery,
                host=args.host,
                port=args.port,
                verbose=args.verbose,
            )
        else:
            engine = make_engine()
            n = engine.warmup()
            if args.generate:
                print(
                    "serve: warmed %d programs (prefill buckets %s + "
                    "decode + score), %d decode slots, %d x %d KV "
                    "blocks — POST /generate streams NDJSON"
                    % (
                        n, engine.buckets, args.max_streams,
                        args.kv_blocks, args.kv_block_size,
                    )
                )
            else:
                print(
                    f"serve: warmed {n} bucket programs "
                    f"{engine.buckets} for input {engine.item_shape}, "
                    f"output blob {engine.output_blob!r}"
                )
            server = ServeServer(
                engine,
                host=args.host,
                port=args.port,
                max_queue=args.queue,
                max_wait_ms=args.max_wait_ms,
                verbose=args.verbose,
            )
        return server.run()
    finally:
        run_obs.close()


def cmd_parse_log(args) -> int:
    """``parse_log LOG [--out PREFIX]`` — training log -> train/test
    CSVs (the ``tools/extra/parse_log.py`` role, for this framework's
    ``training_log_<ts>.txt`` format)."""
    from sparknet_tpu.tools import parse_log as pl

    train, test = pl.parse_log(args.log)
    import os

    prefix = args.out or os.path.splitext(args.log)[0]
    paths = pl.write_csvs(train, test, prefix)
    print(
        f"parsed {len(train)} train rows, {len(test)} test rows -> "
        + ", ".join(paths)
    )
    return 0


def cmd_upgrade_net_proto_text(args) -> int:
    """``upgrade_net_proto_text IN OUT`` — rewrite a legacy (V0/V1)
    net prototxt in the modern format (reference:
    ``caffe/tools/upgrade_net_proto_text.cpp``; the upgrade passes
    themselves live in ``config/prototext.py``)."""
    from sparknet_tpu import config
    from sparknet_tpu.config import prototext

    netp = config.load_net_prototxt(args.input)  # upgrades on load
    with open(args.output, "w") as f:
        f.write(prototext.dumps(netp))
    print(f"Wrote upgraded net to {args.output}")
    return 0


def cmd_upgrade_net_proto_binary(args) -> int:
    """``upgrade_net_proto_binary IN OUT`` — rewrite a legacy (V0/V1)
    *binary* NetParameter in the modern binary format (reference:
    ``caffe/tools/upgrade_net_proto_binary.cpp``; codec:
    ``io/protobin.py``).  Weight-carrying nets upgrade in place — layer
    blobs ride through like upgrade_proto.cpp:21-80 copies them."""
    from sparknet_tpu.io import protobin

    netp = protobin.load_net_binary(args.input)  # upgrades on load
    protobin.save_net_binary(netp, args.output)
    print(f"Wrote upgraded binary net to {args.output}")
    return 0


def cmd_upgrade_solver_proto_text(args) -> int:
    """``upgrade_solver_proto_text IN OUT`` — rewrite a legacy solver
    prototxt (enum ``solver_type`` -> string ``type``) in the modern
    format (reference: ``caffe/tools/upgrade_solver_proto_text.cpp``)."""
    from sparknet_tpu import config
    from sparknet_tpu.config import prototext
    from sparknet_tpu.config.schema import solver_method

    sp = config.load_solver_prototxt(args.input)
    if sp.solver_type is not None:
        sp.type = solver_method(sp)
        sp.solver_type = None
    with open(args.output, "w") as f:
        f.write(prototext.dumps(sp))
    print(f"Wrote upgraded solver to {args.output}")
    return 0


def _load_mean_arg(arg: str):
    """``--mean`` value -> array: a mean.binaryproto path gives the
    (C, H, W) mean image; comma-separated values give per-channel (C,)
    means.  Shared by ``classify`` and ``detect``."""
    import os

    import numpy as np

    from sparknet_tpu.io import caffemodel

    if os.path.isfile(arg):
        mean = np.asarray(caffemodel.load_mean_image(arg))
        return mean[0] if mean.ndim == 4 else mean
    return np.asarray([float(v) for v in arg.split(",")], np.float32)


def cmd_detect(args) -> int:
    """``detect --model M [--weights W] --window_file F`` — R-CNN-style
    windowed detection: score every proposal window listed in an R-CNN
    window file (reference: ``python/caffe/detector.py`` driven over
    ``window_data_layer``-format files).  Prints one line per window:
    ``<image> <x1> <y1> <x2> <y2> <top-class> <score>``."""
    import numpy as np

    from sparknet_tpu import config, models
    from sparknet_tpu.data.windows import parse_window_file
    from sparknet_tpu.tools.detector import Detector

    netp = (
        config.load_net_prototxt(args.model)
        if args.model.endswith(".prototxt")
        else models.load_model(args.model)
    )
    mean = _load_mean_arg(args.mean) if args.mean else None
    # Detector validates a too-small mean image itself
    det = Detector(
        netp,
        weights=args.weights,
        mean=mean,
        context_pad=args.context_pad,
        crop_mode=args.crop_mode,
        batch=args.batch,
    )
    images = parse_window_file(args.window_file, args.root_folder)
    jobs = []
    for im in images:
        # window-file rows are (class, overlap, x1, y1, x2, y2),
        # inclusive; Detector takes (ymin, xmin, ymax, xmax) max-exclusive
        wins = [
            (int(y1), int(x1), int(y2) + 1, int(x2) + 1)
            for (_cls, _ov, x1, y1, x2, y2) in im.windows
        ]
        if wins:
            jobs.append((im.path, wins))
    dets = det.detect_windows(jobs)
    for d in dets:
        ymin, xmin, ymax, xmax = [int(v) for v in d["window"]]
        top = int(np.argmax(d["prediction"]))
        print(
            f"{d['filename']} {xmin} {ymin} {xmax - 1} {ymax - 1} "
            f"{top} {float(d['prediction'][top]):.4f}"
        )
    print(f"scored {len(dets)} windows over {len(jobs)} images",
          file=sys.stderr)
    return 0


def cmd_draw_net(args) -> int:
    """``draw_net NET OUT.dot`` — emit a graphviz visualization of a net
    definition (reference: ``caffe/python/caffe/draw.py`` via
    ``python/draw_net.py``; here dot source is written directly, render
    with ``dot -Tpng OUT.dot -o OUT.png``)."""
    from sparknet_tpu import config
    from sparknet_tpu.tools import draw

    netp = config.load_net_prototxt(args.input)
    draw.draw_net_to_file(
        netp, args.output, rankdir=args.rankdir,
        label_edges=not args.no_edge_labels, phase=args.phase,
    )
    print(f"Drawing net to {args.output}")
    return 0


def cmd_compute_image_mean(args) -> int:
    """``compute_image_mean DB [OUTPUT]`` — streaming mean image of a
    Datum DB, written as mean.binaryproto (reference:
    ``caffe/tools/compute_image_mean.cpp``)."""
    import os

    from sparknet_tpu.io import caffemodel, lmdb

    total = None
    count = 0
    if lmdb.is_lmdb(args.db):
        it = (img for img, _ in lmdb.read_datum_lmdb(args.db))
    elif os.path.isdir(args.db):
        from sparknet_tpu.io import leveldb

        if not leveldb.is_leveldb(args.db):
            print(
                f"compute_image_mean: {args.db} is neither an LMDB, a "
                "LevelDB, nor a record DB",
                file=sys.stderr,
            )
            return 1
        it = (img for img, _ in leveldb.read_datum_leveldb(args.db))
    else:
        from sparknet_tpu import runtime
        from sparknet_tpu.data.source import _record_shape

        c, h, w = _record_shape(args.db, args.channels, 0, 0)

        def _iter_sndb():
            with runtime.RecordDB(args.db) as db:
                for i in range(len(db)):
                    _, value = db.read(i)
                    lw = len(value) - c * h * w  # 1- or 2-byte label
                    yield np.frombuffer(value[lw:], np.uint8).reshape(c, h, w)

        it = _iter_sndb()
    for img in it:
        s = img.astype(np.int64)
        total = s if total is None else total + s
        count += 1
    if total is None:
        print("compute_image_mean: empty db", file=sys.stderr)
        return 1
    mean = (total.astype(np.float64) / count).astype(np.float32)
    caffemodel.save_mean_image(mean, args.output)
    print(f"Number of items: {count}")
    for ch in range(mean.shape[0]):
        print(f"mean_value channel [{ch}]: {mean[ch].mean():.6g}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv[:1] == ["train"] and "--lm" in argv:
        # the transformer-LM workload: ``train --lm`` hands the rest of
        # the line to apps/lm_app.py, whose parser carries the LM's
        # full surface — --sp (sequence-parallel ring width, dp x sp
        # mesh), --corpus/--cache_dir, --seq_len/--dim/--depth/--heads,
        # plus the same --obs/--health/--journal/--elastic/--compress
        # groups every averaging app exposes.  A prototxt --solver does
        # not apply (the LM is builder-backed, models/transformer_lm).
        from sparknet_tpu.apps import lm_app

        return lm_app.main([a for a in argv[1:] if a != "--lm"])
    parser = argparse.ArgumentParser(prog="sparknet_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("train")
    p.add_argument(
        "--lm", action="store_true",
        help="train the transformer LM workload instead of a prototxt "
        "solver: the rest of the line goes to apps/lm_app.py "
        "(--sp RING_WIDTH for sequence parallelism over a dp x sp "
        "mesh, --corpus URL/dir, --seq_len/--dim/--depth/--heads, "
        "full --obs/--health/--journal/--elastic surface; --solver "
        "does not apply)",
    )
    p.add_argument("--solver", required=True)
    p.add_argument("--snapshot", default=None)
    p.add_argument("--resume", action="store_true",
                   help="continue from the newest CRC-valid snapshot "
                   "under the solver's snapshot_prefix (corrupt ones "
                   "are quarantined and skipped)")
    p.add_argument("--weights", default=None)
    p.add_argument("--data", default=None,
                   help="CIFAR binary dir, or a gs://|s3://|http(s)://|"
                   "file:// url staged through the chunk cache")
    p.add_argument("--cache_dir", default=None,
                   help="chunk-cache root for an object-store --data "
                   "(data/chunk_cache.py; default: a temp dir)")
    p.add_argument("--cache_bytes", default="0",
                   help="chunk-cache LRU byte budget, e.g. 512M / 8G "
                   "(0 = unbounded)")
    p.add_argument("--shuffle_epochs", type=int, default=0,
                   help="with a value >= 2, draw training windows as "
                   "deterministic epoch passes whose minibatch ORDER "
                   "re-permutes each epoch (seeded shuffle-by-"
                   "assignment, data/shuffle.py); resume-aware via the "
                   "absolute iteration.  0/1 = the legacy random "
                   "windows (matching the averaging apps' 0/1 = off). "
                   "Unlike the apps, the value does not split the run: "
                   "an epoch here is one data pass (total/tau windows)")
    p.add_argument("--tau", type=int, default=10)
    p.add_argument("--max_iter", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--async_snapshot", action="store_true",
                   help="write snapshots on a background thread")
    p.add_argument("--serial_feed", action="store_true",
                   help="disable the pipelined round feed (assemble+H2D "
                   "on the training loop) — for relay-degraded links "
                   "where overlapped transfers collapse (PERF.md)")
    p.add_argument("--devices", type=int, default=1,
                   help="N>1: synchronous allreduce DP over the first N "
                   "local devices (the caffe train --gpu=0,..,N-1 analog; "
                   "batch_size is per-device)")
    p.add_argument(
        "--sigint_effect", choices=["stop", "snapshot", "none"], default="stop"
    )
    p.add_argument(
        "--sighup_effect", choices=["stop", "snapshot", "none"], default="snapshot"
    )
    p.add_argument(
        "--publish_to", default=None, metavar="DIR",
        help="publish the final state here for a serving fleet "
        "(serve/publish.py): a CRC-manifested snapshot with the health "
        "sentry's PASSING verdict attached — a diverged run publishes "
        "nothing.  Implies --health warn.  Serve side: "
        "cli serve --watch DIR canaries + promotes it with no restart",
    )
    from sparknet_tpu import obs as _obs
    from sparknet_tpu.io import journal as _journal
    from sparknet_tpu.parallel import comm as _comm

    _obs.add_cli_args(p)  # --obs / --obs_port / --trace_out
    _comm.add_cli_args(p)  # --compress / --overlap_avg
    _journal.add_cli_args(p)  # --journal / --no_journal / --journal_path
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("test")
    p.add_argument("--model", required=True)
    p.add_argument("--weights", default=None)
    p.add_argument("--data", default=None, help="CIFAR binary dir or SNDB path")
    p.add_argument("--allow_synthetic", action="store_true",
                   help="smoke-test only: score random batches")
    p.add_argument("--iterations", type=int, default=50)
    p.set_defaults(fn=cmd_test)

    p = sub.add_parser("time")
    p.add_argument("--model", required=True)
    p.add_argument("--iterations", type=int, default=10)
    p.set_defaults(fn=cmd_time)

    p = sub.add_parser("device_query")
    p.set_defaults(fn=cmd_device_query)

    p = sub.add_parser("convert_imageset")
    p.add_argument("root", help="image tree root")
    p.add_argument("listfile", help='"<relpath> <label>" lines')
    p.add_argument("db", help="output DB path")
    p.add_argument("--gray", action="store_true")
    p.add_argument("--shuffle", action="store_true")
    p.add_argument(
        "--backend", choices=["sndb", "lmdb", "leveldb"], default="sndb"
    )
    p.add_argument("--resize_width", type=int, default=0)
    p.add_argument("--resize_height", type=int, default=0)
    p.add_argument("--check_size", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_convert_imageset)

    p = sub.add_parser("convert_mnist")
    p.add_argument("images", help="idx3 image file (.gz ok)")
    p.add_argument("labels", help="idx1 label file (.gz ok)")
    p.add_argument("db", help="output DB path")
    p.add_argument(
        "--backend", choices=["sndb", "lmdb", "leveldb"], default="sndb"
    )
    p.add_argument("--pairs", type=int, default=0,
                   help="write N siamese 2-channel pairs instead")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_convert_mnist)

    p = sub.add_parser("serve")
    p.add_argument("--net", default=None,
                   help="deploy prototxt or zoo model name (required "
                   "unless --generate)")
    p.add_argument("--weights", default=None,
                   help=".caffemodel / .caffemodel.h5 (snapshot output ok)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8361)
    p.add_argument("--buckets", default="1,4,16,64",
                   help="comma-separated batch-size buckets to pre-compile")
    p.add_argument("--max_wait_ms", type=float, default=2.0,
                   help="micro-batch coalescing deadline")
    p.add_argument("--queue", type=int, default=256,
                   help="admission queue bound (overflow -> 429)")
    p.add_argument("--output_blob", default=None,
                   help="blob to serve (default: prob, else last top)")
    p.add_argument("--dtype", default=None,
                   help="compute dtype, e.g. bfloat16 (default f32)")
    p.add_argument("--verbose", action="store_true",
                   help="log each HTTP request")
    p.add_argument("--replicas", type=int, default=1,
                   help="N>1: a serving FLEET (serve/fleet.py) — N "
                   "shared-nothing engine replicas behind a router "
                   "that load-balances by in-flight depth and sheds "
                   "(429) at a fleet-wide admission bound (--queue)")
    p.add_argument("--watch", default=None, metavar="PUBLISH_DIR",
                   help="watch this publish location (local dir or "
                   "object-store url) for cli train --publish_to "
                   "snapshots: CRC+verdict verify, warm a standby "
                   "off-path, canary live traffic, promote or roll "
                   "back with no restart (serve/delivery.py)")
    p.add_argument("--canary_frac", type=float, default=0.125,
                   help="fraction of live traffic mirrored to a canary "
                   "during a delivery decision window")
    p.add_argument("--decision_requests", type=int, default=32,
                   help="mirrored requests per canary decision window")
    p.add_argument("--divergence_max", type=float, default=0.25,
                   help="max |canary - incumbent| output divergence "
                   "before the canary rolls back")
    p.add_argument("--cache_dir", default=None,
                   help="chunk-cache root for the delivery watcher's "
                   "verified snapshot staging (default: a temp dir)")
    p.add_argument("--generate", action="store_true",
                   help="serve a TransformerLM checkpoint for token "
                   "streaming (serve/generate.py): chunked-NDJSON "
                   "POST /generate, continuous batching over a paged "
                   "KV arena; composes with --replicas/--watch")
    p.add_argument("--lm_dim", type=int, default=256,
                   help="--generate: TransformerLM embedding dim")
    p.add_argument("--lm_depth", type=int, default=4,
                   help="--generate: TransformerLM layers")
    p.add_argument("--lm_heads", type=int, default=4,
                   help="--generate: TransformerLM attention heads")
    p.add_argument("--lm_seq_len", type=int, default=256,
                   help="--generate: model context length")
    p.add_argument("--prefill_buckets", default="16,32,64,128",
                   help="--generate: prompt-length buckets to "
                   "pre-compile (longer prompts -> 400)")
    p.add_argument("--max_streams", type=int, default=8,
                   help="--generate: decode slots (the fixed decode "
                   "batch width)")
    p.add_argument("--kv_blocks", type=int, default=64,
                   help="--generate: paged KV arena blocks (worst-case "
                   "reservation at admission; overflow -> 429)")
    p.add_argument("--kv_block_size", type=int, default=16,
                   help="--generate: positions per KV block")
    _obs.add_cli_args(p)  # --obs/--ship_to/...: fleet series ride the shipper
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("parse_log")
    p.add_argument("log")
    p.add_argument("--out", default=None, help="CSV prefix")
    p.set_defaults(fn=cmd_parse_log)

    p = sub.add_parser("classify")
    p.add_argument("images", nargs="+")
    p.add_argument("--model", required=True)
    p.add_argument("--weights", default=None)
    p.add_argument("--mean", default=None,
                   help="mean.binaryproto path or comma-separated values")
    p.add_argument("--labels", default=None, help="one class name per line")
    p.add_argument("--topk", type=int, default=5)
    p.add_argument(
        "--oversample", action="store_true",
        help="10-crop (corners+center and mirrors) score averaging "
        "(classifier.py predict(oversample=True))",
    )
    p.add_argument(
        "--resize", type=int, default=0,
        help="oversample source size (default max(256, input))",
    )
    p.set_defaults(fn=cmd_classify)

    for name, fn in (
        ("upgrade_net_proto_text", cmd_upgrade_net_proto_text),
        ("upgrade_net_proto_binary", cmd_upgrade_net_proto_binary),
        ("upgrade_solver_proto_text", cmd_upgrade_solver_proto_text),
    ):
        p = sub.add_parser(name)
        p.add_argument("input")
        p.add_argument("output")
        p.set_defaults(fn=fn)

    p = sub.add_parser("detect")
    p.add_argument("--model", required=True,
                   help="deploy prototxt or zoo model name")
    p.add_argument("--weights", default=None)
    p.add_argument("--window_file", required=True,
                   help="R-CNN window_data file of proposal windows")
    p.add_argument("--root_folder", default="")
    p.add_argument("--mean", default=None,
                   help="mean.binaryproto path or comma-separated values")
    p.add_argument("--context_pad", type=int, default=0)
    p.add_argument("--crop_mode", default="warp", choices=["warp", "square"])
    p.add_argument("--batch", type=int, default=32)
    p.set_defaults(fn=cmd_detect)

    p = sub.add_parser("draw_net")
    p.add_argument("input")
    p.add_argument("output")
    p.add_argument("--rankdir", default="LR", choices=["LR", "TB", "BT", "RL"])
    p.add_argument("--phase", default=None, choices=["TRAIN", "TEST"])
    p.add_argument("--no_edge_labels", action="store_true")
    p.set_defaults(fn=cmd_draw_net)

    p = sub.add_parser("compute_image_mean")
    p.add_argument("db")
    p.add_argument("output", nargs="?", default="mean.binaryproto")
    p.add_argument("--channels", type=int, default=3,
                   help="record channels for raw DBs (1 for --gray sets; "
                   "LMDB Datums carry their own shape)")
    p.set_defaults(fn=cmd_compute_image_mean)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
