"""Windowed detection driver — R-CNN-style per-window scoring.

Reference role: ``caffe/python/caffe/detector.py:1-216`` (``Detector``):
crop each proposal window (with optional surrounding context), warp to the
net input size, and score every window with the classifier.  Differences
from the reference, by design:

- crops go through ``data/windows.crop_window`` — the same routine the
  WindowData *training* layer uses — so train and inference see identical
  context-padding/warp geometry (the reference maintains two copies:
  ``window_data_layer.cpp`` and ``detector.py crop``);
- windows are scored in fixed-size jitted batches (one compile, MXU-sized
  work) instead of one variable-length ``forward_all`` dispatch;
- the selective-search MATLAB bridge is out of scope (proposals come from
  the caller), as is channel_swap (images load as RGB planes here, not
  OpenCV BGR).

Window coordinates follow the reference convention ``(ymin, xmin, ymax,
xmax)`` with max-exclusive bounds (the numpy slice semantics of
``detector.py crop``: ``im[ymin:ymax, xmin:xmax]``).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from sparknet_tpu.config.schema import NetParameter


class Detector:
    """Score proposal windows with a deploy net.

    Parameters
    ----------
    netp : NetParameter (a deploy/Input-fed config, or anything
        ``models.deploy_variant`` can reduce)
    weights : optional .caffemodel path
    mean : per-channel mean values (C,) or mean image (C, H, W)
    input_scale : multiplier applied after mean subtraction
        (``Transformer.set_input_scale`` analog)
    context_pad : context border in input-image pixels (R-CNN uses 16)
    crop_mode : "warp" or "square" (``det_crop_mode`` semantics)
    batch : windows scored per jitted dispatch
    """

    def __init__(
        self,
        netp: NetParameter,
        weights: Optional[str] = None,
        mean: Optional[np.ndarray] = None,
        input_scale: Optional[float] = None,
        context_pad: int = 0,
        crop_mode: str = "warp",
        batch: int = 32,
    ):
        import jax

        from sparknet_tpu import models
        from sparknet_tpu.io import caffemodel
        from sparknet_tpu.net import JaxNet

        net = JaxNet(netp, phase="TEST")
        if len(net.feed_blobs) > 1:
            netp = models.deploy_variant(netp, batch=batch)
            net = JaxNet(netp, phase="TEST")
        self.net = net
        self.data_blob = net.feed_blobs[0]
        _, self.channels, self.crop_h, self.crop_w = net.blob_shapes[
            self.data_blob
        ]
        if self.crop_h != self.crop_w:
            raise ValueError(
                "windowed detection needs a square input "
                f"(net takes {self.crop_h}x{self.crop_w})"
            )
        self.params, self.stats = net.init(0)
        if weights:
            self.params, self.stats = caffemodel.apply_blobs(
                net, self.params, self.stats, caffemodel.load_weights(weights)
            )
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        if self.mean is not None and self.mean.ndim == 3 and (
            self.mean.shape[1] < self.crop_h
            or self.mean.shape[2] < self.crop_w
        ):
            raise ValueError(
                f"mean image {self.mean.shape[1]}x{self.mean.shape[2]} is "
                f"smaller than the net input {self.crop_h}x{self.crop_w}"
            )
        self.input_scale = input_scale
        self.context_pad = int(context_pad)
        self.crop_mode = crop_mode
        self.batch = int(batch)
        # "prob" if the deploy net names one (the BVLC convention), else
        # the last layer's top — same rule as `cli.py classify`
        self.out_blob = (
            "prob" if "prob" in net.blob_shapes
            else net.net_param.layer[-1].top[0]
        )

        def fwd(params, stats, data):
            blobs = net.forward(params, stats, {self.data_blob: data})
            return blobs[self.out_blob]

        self._fwd = jax.jit(fwd)

    # -- preprocessing ----------------------------------------------------

    def _preprocess(self, window_hwc: np.ndarray, content=None) -> np.ndarray:
        """Mean-subtract + scale one crop.  ``content`` is the
        (pad_h, pad_w, (warped_h, warped_w)) geometry from crop_window:
        the zero-padded border outside it is masked back to zero AFTER
        mean subtraction, so the net sees zero-signal padding exactly
        like WindowSampler training batches (the reference detector pads
        with the mean so the net likewise sees 0 post-subtraction,
        detector.py:96-108)."""
        chw = window_hwc.transpose(2, 0, 1).astype(np.float32)
        if self.mean is not None:
            if self.mean.ndim == 1:
                chw = chw - self.mean[:, None, None]
            else:
                off_h = (self.mean.shape[1] - self.crop_h) // 2
                off_w = (self.mean.shape[2] - self.crop_w) // 2
                chw = chw - self.mean[
                    :, off_h:off_h + self.crop_h, off_w:off_w + self.crop_w
                ]
            if content is not None:
                pad_h, pad_w, (wh, ww) = content
                mask = np.zeros(chw.shape[1:], bool)
                mask[pad_h:pad_h + wh, pad_w:pad_w + ww] = True
                chw = np.where(mask[None], chw, 0.0)
        if self.input_scale is not None:
            chw = chw * self.input_scale
        return chw

    def crop(self, im: np.ndarray, window: Sequence[float]):
        """Crop one (ymin, xmin, ymax, xmax) window (context-padded) —
        ``Detector.crop`` analog.  Returns ``(out_hwc, content)`` where
        ``content`` is the (pad_h, pad_w, warped_shape) geometry that
        _preprocess uses to keep padding at zero signal."""
        from sparknet_tpu.data.windows import crop_window

        ymin, xmin, ymax, xmax = [float(v) for v in window]
        out, pad_h, pad_w, warped = crop_window(
            im, xmin, ymin, xmax - 1, ymax - 1, self.crop_h,
            context_pad=self.context_pad,
            square=self.crop_mode == "square",
        )
        return out, (pad_h, pad_w, warped)

    # -- scoring ----------------------------------------------------------

    def _score(self, inputs: List[np.ndarray]) -> np.ndarray:
        preds = []
        for i in range(0, len(inputs), self.batch):
            chunk = inputs[i:i + self.batch]
            n = len(chunk)
            buf = np.zeros(
                (self.batch, self.channels, self.crop_h, self.crop_w),
                np.float32,
            )
            buf[:n] = np.stack(chunk)
            out = np.asarray(self._fwd(self.params, self.stats, buf))
            preds.append(out.reshape(self.batch, -1)[:n])
        return np.concatenate(preds) if preds else np.zeros((0, 0))

    def detect_windows(
        self,
        images_windows: Iterable[
            Tuple[Union[str, np.ndarray], Sequence[Sequence[float]]]
        ],
    ) -> List[Dict]:
        """Score every (image, window-list) pair; returns dicts of
        ``{filename, window, prediction}`` in input order
        (``Detector.detect_windows`` contract)."""
        from sparknet_tpu.data.windows import _load_image

        images_windows = list(images_windows)
        inputs, meta = [], []
        for src, windows in images_windows:
            if isinstance(src, str):
                im = _load_image(src, self.channels)
                name = src
            else:
                im = np.asarray(src)
                name = None
                if im.dtype != np.uint8:
                    # accept caffe.io.load_image-style float [0,1] images;
                    # anything else is ambiguous for the uint8 warp path
                    if np.issubdtype(im.dtype, np.floating) and (
                        im.min() >= 0.0 and im.max() <= 1.0
                    ):
                        im = (im * 255.0).round().astype(np.uint8)
                    else:
                        raise TypeError(
                            "detect_windows takes uint8 images or float "
                            f"images in [0, 1]; got {im.dtype} with range "
                            f"[{im.min()}, {im.max()}]"
                        )
            for window in windows:
                out, content = self.crop(im, window)
                inputs.append(self._preprocess(out, content))
                meta.append((name, np.asarray(window)))
        preds = self._score(inputs)
        return [
            {"filename": name, "window": win, "prediction": preds[i]}
            for i, (name, win) in enumerate(meta)
        ]
