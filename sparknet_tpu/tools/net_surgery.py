"""Net surgery: fully-convolutional conversion of InnerProduct layers.

The reference's ``examples/net_surgery.ipynb`` workflow: cast a trained
classifier's fc layers to convolutions (fc6 -> 6x6 conv, fc7/fc8 -> 1x1)
so the net slides over larger images and emits a dense score map instead
of one vector — weights are *the same numbers reshaped*, because an
InnerProduct over a flattened (C, H, W) bottom computes exactly a VALID
convolution with an (out, C, H, W) kernel at the single aligned
position.

``fc_to_conv`` does the whole operation on (NetParameter, params):
returns a rewritten net and the reshaped params, ready to build a
JaxNet at any input size.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparknet_tpu.config.schema import (
    ConvolutionParameter,
    LayerParameter,
    NetParameter,
)


def fc_to_conv(
    netp: NetParameter,
    blob_shapes: Dict[str, Tuple[int, ...]],
    params: Dict[str, List],
    layer_names: Sequence[str],
    rename: Optional[Dict[str, str]] = None,
) -> Tuple[NetParameter, Dict[str, List[np.ndarray]]]:
    """Convert the named InnerProduct layers to Convolution layers.

    ``blob_shapes`` is the source net's blob-shape map (it supplies each
    fc bottom's (C, H, W), which becomes the kernel); ``rename``
    optionally maps old -> new layer names (the reference renames
    fc6 -> fc6-conv so ``CopyTrainedLayersFrom`` cannot mis-match
    shapes).  Returns (new NetParameter, new params dict); untouched
    layers keep their parameter arrays by reference."""
    rename = rename or {}
    targets = set(layer_names)
    by_name = {l.name: l for l in netp.layer}
    for name in targets:
        if name not in by_name:
            raise KeyError(f"no layer named {name!r}")
        if by_name[name].type != "InnerProduct":
            raise ValueError(
                f"layer {name!r} is {by_name[name].type}, not InnerProduct"
            )

    new_net = netp.copy()
    new_params: Dict[str, List[np.ndarray]] = {}
    for name, blobs in params.items():
        if name not in targets:
            new_params[rename.get(name, name)] = list(blobs)

    # renamed layers also rename their top blob when it shares the layer
    # name (the universal Caffe convention and what the reference's
    # surgery prototxt does), so every later bottom/top reference follows
    blob_rename = {
        old: new
        for old, new in rename.items()
        if any(l.name == old and old in l.top for l in netp.layer)
    }
    converted_tops = set()
    for lp in new_net.layer:
        if lp.name in rename:
            lp.name = rename[lp.name]
        lp.bottom = [blob_rename.get(b, b) for b in lp.bottom]
        lp.top = [blob_rename.get(t, t) for t in lp.top]
        if lp.name not in {rename.get(n, n) for n in targets}:
            continue
        old_name = next(
            n for n in targets if rename.get(n, n) == lp.name
        )
        bottom = lp.bottom[0]
        # blob_shapes is keyed by SOURCE names; map a renamed bottom back
        src_bottom = {v: k for k, v in blob_rename.items()}.get(
            bottom, bottom
        )
        bshape = blob_shapes[src_bottom]
        if len(bshape) == 4:
            _, c, kh, kw = bshape
        elif bottom in converted_tops or len(bshape) == 2:
            # bottom was itself converted (or is already flat): 1x1
            c, kh, kw = bshape[1], 1, 1
        else:
            raise ValueError(
                f"cannot infer kernel for {old_name!r} from bottom "
                f"shape {bshape}"
            )
        ip = lp.inner_product_param
        w, *rest = params[old_name]
        w = np.asarray(w)
        if w.shape != (ip.num_output, c * kh * kw):
            raise ValueError(
                f"{old_name!r}: weight {w.shape} does not match "
                f"({ip.num_output}, {c}*{kh}*{kw})"
            )
        lp.type = "Convolution"
        lp.inner_product_param = None
        lp.convolution_param = ConvolutionParameter(
            num_output=ip.num_output,
            kernel_size=[kh] if kh == kw else [],
            kernel_h=0 if kh == kw else kh,
            kernel_w=0 if kh == kw else kw,
            bias_term=ip.bias_term,
        )
        new_params[lp.name] = [
            w.reshape(ip.num_output, c, kh, kw)
        ] + [np.asarray(b) for b in rest]
        converted_tops.add(lp.top[0])
    return new_net, new_params
