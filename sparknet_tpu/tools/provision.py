"""Cluster provisioning — the create/describe/destroy half of L8.

Reference: ``ec2/spark_ec2.py`` (1,528 LoC; ``launch_cluster`` at ``:481``,
action dispatch in ``real_main`` at ``:1256-1518``) provisions EC2
instances, waits for SSH, deploys files, and tears clusters down.  The
TPU-native analog provisions a Cloud TPU pod slice (every host of the slice
is one worker VM) through ``gcloud compute tpus tpu-vm``:

    provision  ->  create slice, wait READY, deploy the repo to every
                   worker, install nothing (jax ships on the TPU image)
    describe   ->  slice state + worker endpoints      (get_existing_cluster)
    run        ->  submit an app on every worker       (spark-submit analog)
    ssh        ->  interactive shell on one worker     (login action)
    teardown   ->  delete the slice                    (destroy action)

Every action resolves to an exact ``gcloud`` command sequence from
``command_plan`` — a pure function so tests (and ``--dry-run``) can assert
the sequence without a cloud project.  ``--dry-run`` prints one
shell-quoted command per line and executes nothing, making SETUP.md's
walkthrough an executable artifact::

    python -m sparknet_tpu.tools.launch provision --dry-run \
        --name=sparknet-v5e --zone=us-west4-8a --accelerator=v5litepod-8

Unlike ``spark_ec2.py`` there are no security groups, AMI resolution, or
SSH-readiness polling loops to hand-roll: the TPU runtime image carries the
ML stack, ``gcloud ... ssh`` brokers IAP/keys, and slice state is a single
``describe`` field — so the whole layer stays small without losing the
reference's capability.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from typing import List, Optional

ACTIONS = ("provision", "describe", "run", "ssh", "teardown")

# Default runtime image for current-generation slices; override with
# --version (the analog of spark_ec2's --spark-version/AMI resolution).
DEFAULT_VERSION = "tpu-ubuntu2204-base"
DEFAULT_REMOTE_DIR = "~/sparknet_tpu"


def _gcloud_tpu(opts) -> List[str]:
    cmd = ["gcloud"]
    if opts.project:
        cmd += ["--project", opts.project]
    cmd += ["compute", "tpus", "tpu-vm"]
    return cmd


def command_plan(
    action: str, opts, app_argv: Optional[List[str]] = None
) -> List[List[str]]:
    """The exact gcloud command sequence for one action (pure; no I/O)."""
    base = _gcloud_tpu(opts)
    zone = ["--zone", opts.zone]
    if action == "provision":
        create = base + [
            "create", opts.name, *zone,
            "--accelerator-type", opts.accelerator,
            "--version", opts.version,
        ]
        if opts.spot:
            create += ["--spot"]
        if opts.network:
            create += ["--network", opts.network]
        plan = [create]
        # wait-for-READY: gcloud create blocks until the slice exists, but
        # state is re-checked explicitly the way spark_ec2 waits for
        # 'ssh-ready' (spark_ec2.py:905) — one describe, judged by caller
        plan.append(
            base + ["describe", opts.name, *zone, "--format=value(state)"]
        )
        # deploy the framework to every worker (deploy_files analog,
        # spark_ec2.py:1035).  scp -r into an EXISTING dir would nest the
        # copy one level down (stale code on redeploy), so clear first —
        # the role rsync played in spark_ec2's deploy
        plan.append(
            base + [
                "ssh", opts.name, *zone, "--worker=all",
                "--command", f"rm -rf {opts.remote_dir}",
            ]
        )
        plan.append(
            base + [
                "scp", "--recurse", opts.repo,
                f"{opts.name}:{opts.remote_dir}",
                *zone, "--worker=all",
            ]
        )
        return plan
    if action == "describe":
        return [
            base + ["describe", opts.name, *zone],
        ]
    if action == "run":
        # spark-submit analog: the same launch line on every worker;
        # jax.distributed discovers slice topology from metadata, so no
        # coordinator flags are needed (tools/launch.py docstring)
        app_line = " ".join(
            ["cd", opts.remote_dir, "&&", "python", "-m",
             "sparknet_tpu.tools.launch"]
            + [shlex.quote(a) for a in (app_argv or [])]
        )
        return [
            base + [
                "ssh", opts.name, *zone, "--worker=all",
                "--command", app_line,
            ]
        ]
    if action == "ssh":
        return [
            base + ["ssh", opts.name, *zone, f"--worker={opts.worker}"],
        ]
    if action == "teardown":
        return [
            base + ["delete", opts.name, *zone, "--quiet"],
        ]
    raise ValueError(f"unknown action {action!r}")


def format_plan(plan: List[List[str]]) -> str:
    return "\n".join(shlex.join(cmd) for cmd in plan)


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="launch provision|describe|run|ssh|teardown",
        description=__doc__.split("\n", 1)[0],
    )
    p.add_argument("--name", default="sparknet", help="slice name")
    p.add_argument("--zone", default="us-central2-b")
    p.add_argument("--project", default=None)
    p.add_argument(
        "--accelerator", default="v5litepod-8",
        help="accelerator type, e.g. v5litepod-8 / v4-32",
    )
    p.add_argument("--version", default=DEFAULT_VERSION,
                   help="TPU runtime image")
    p.add_argument("--spot", action="store_true",
                   help="preemptible capacity (spark_ec2 --spot-price analog)")
    p.add_argument("--network", default=None)
    p.add_argument("--repo", default=".", help="local repo dir to deploy")
    p.add_argument("--remote_dir", default=DEFAULT_REMOTE_DIR)
    p.add_argument("--worker", default="0", help="worker index for ssh")
    p.add_argument("--dry-run", dest="dry_run", action="store_true",
                   help="print the exact command sequence; execute nothing")
    return p


def main(action: str, argv: List[str]) -> int:
    # `run` forwards everything after `--` to the app launch line
    argv = list(argv)
    app_argv: List[str] = []
    if "--" in argv:
        cut = argv.index("--")
        argv, app_argv = argv[:cut], argv[cut + 1:]
    opts = make_parser().parse_args(argv)
    plan = command_plan(action, opts, app_argv)
    if opts.dry_run:
        print(format_plan(plan))
        return 0
    for cmd in plan:
        print("+ " + shlex.join(cmd), file=sys.stderr)
        if "--format=value(state)" in cmd:
            # the wait-for-READY step: judge the state, poll until READY
            # (spark_ec2.py wait_for_cluster_state analog, :905)
            rc = _wait_ready(cmd)
        else:
            rc = subprocess.call(cmd)
        if rc != 0:
            return rc
    return 0


def _wait_ready(cmd, tries: int = 90, sleep_s: int = 10) -> int:
    import time

    for i in range(tries):
        r = subprocess.run(cmd, capture_output=True, text=True)
        state = r.stdout.strip()
        if r.returncode == 0 and state == "READY":
            print("slice state: READY", file=sys.stderr)
            return 0
        print(
            f"slice state: {state or r.stderr.strip()!r} "
            f"(waiting, {i + 1}/{tries})",
            file=sys.stderr,
        )
        time.sleep(sleep_s)
    print("timed out waiting for READY", file=sys.stderr)
    return 1
