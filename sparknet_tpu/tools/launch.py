"""Multi-host launcher — the cluster bring-up layer (L8).

Reference: ``ec2/spark_ec2.py`` (provision EC2, wire master/workers, submit
apps) + ``SETUP.md``.  On TPU there is nothing to *provision* from inside
the job — the pod slice exists and every host runs the same program — so
the L8 role reduces to: start one process per host, join them through
``jax.distributed`` (``parallel/mesh.py initialize_distributed``), shard
the data per host, and run the app.  This tool does all three:

Local simulation (N processes on this machine, CPU devices standing in
for per-host chips — the development / CI path)::

    python -m sparknet_tpu.tools.launch --nprocs=2 --devices_per_host=2 \
        cifar --rounds=3 --tau=2

One process per real host (run the same line on EVERY host of the slice;
on Cloud TPU use ``gcloud ... ssh --worker=all --command=...``)::

    python -m sparknet_tpu.tools.launch \
        --coordinator=10.0.0.2:8476 --num_processes=4 --process_id=$WORKER_ID \
        imagenet --data=/mnt/imagenet --rounds=100

On a Cloud TPU VM the three flags can all be omitted —
``jax.distributed.initialize()`` discovers the slice topology from the
metadata server — so ``launch imagenet ...`` alone is a full bring-up.

Apps see the joined runtime: ``jax.process_count() > 1`` switches them to
global-mesh mode, loading only their own workers' partitions (see
``parallel.local_worker_slice``).  SETUP.md walks the full path from
"N TPU VMs" to a running multi-host ImageNetApp.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

APPS = {
    "cifar": "sparknet_tpu.apps.cifar_app",
    "imagenet": "sparknet_tpu.apps.imagenet_app",
    "cifar_db": "sparknet_tpu.apps.cifar_db_app",
    "imagenet_create_db": "sparknet_tpu.apps.imagenet_create_db_app",
    "imagenet_run_db": "sparknet_tpu.apps.imagenet_run_db_app",
    "featurizer": "sparknet_tpu.apps.featurizer_app",
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_app(app: str, app_argv, coordinator, num_processes, process_id) -> int:
    """Join the distributed runtime, then hand off to the app's main()."""
    import importlib

    from sparknet_tpu.parallel.mesh import initialize_distributed

    if coordinator or num_processes is not None:
        initialize_distributed(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        # Cloud TPU VM: topology comes from the metadata server
        initialize_distributed()
    mod = importlib.import_module(APPS[app])
    return int(mod.main(list(app_argv)) or 0)


def spawn_local(args, app_argv) -> int:
    """The CI/dev path: N OS processes on this machine, each given
    ``devices_per_host`` virtual CPU devices — process boundaries stand in
    for host boundaries exactly as in tests/test_multihost.py.

    With ``--fleet_collector`` the launcher starts the fleet collector
    (obs/fleet.py) and points every simulated host's shipper at it
    (``--ship_to`` appended to each app argv), so the whole run has ONE
    merged /fleet + /metrics view and the end-of-run summary names any
    late/dead host."""
    collector = None
    if args.fleet_collector:
        from sparknet_tpu.obs.fleet import FleetCollector, parse_hostport

        chost, cport = parse_hostport(args.fleet_collector)
        collector = FleetCollector(host=chost, port=cport).start()
        print(f"launch: fleet collector on {collector.url}/fleet")
        app_argv = list(app_argv) + [f"--ship_to={collector.url}"]
    try:
        return _spawn_local_procs(args, app_argv, collector)
    finally:
        # the listener thread + bound port must not outlive a failed
        # spawn/wait (Ctrl-C, bad app argv, a worker that never exits)
        if collector is not None:
            collector.close()


def proc_slice_members(nprocs: int, slices: int):
    """Contiguous process->slice grouping (the simulated-pod topology
    rule, shared with ``parallel/hierarchy.py``)."""
    from sparknet_tpu.parallel.hierarchy import slice_members

    return slice_members(nprocs, max(1, slices))


def _spawn_local_procs(args, app_argv, collector) -> int:
    import signal as _signal
    import threading
    import time

    port = free_port()
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env_base = {
        **os.environ,
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "PALLAS_AXON_POOL_IPS": "",  # never route the sim through a TPU tunnel
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            f"--xla_force_host_platform_device_count={args.devices_per_host} "
            + os.environ.get("SPARKNET_EXTRA_XLA_FLAGS", "")
        ).strip(),
    }
    # simulated-slice topology: contiguous process blocks; every child
    # learns its slice through SPARKNET_SLICE_ID (the membership
    # controller's SIGTERM hook marks THAT slice leaving)
    slices = proc_slice_members(args.nprocs, getattr(args, "slices", 1))
    slice_of = {
        pid: i for i, members in enumerate(slices) for pid in members
    }
    # flag validation BEFORE any child spawns: a bad --preempt_slice
    # must not leave nprocs orphaned training processes behind an
    # early return
    if getattr(args, "preempt_slice", None) is not None and not (
        0 <= args.preempt_slice < len(slices)
    ):
        print(
            f"launch: --preempt_slice={args.preempt_slice} out of "
            f"range (have {len(slices)} slice(s))",
            file=sys.stderr,
        )
        return 2

    procs = []
    outputs = []
    readers = []
    preempt_killed = set()

    def spawn(pid: int, relaunched: bool = False):
        cmd = [
            sys.executable,
            "-m",
            "sparknet_tpu.tools.launch",
            f"--coordinator=127.0.0.1:{port}",
            f"--num_processes={args.nprocs}",
            f"--process_id={pid}",
            args.app,
            *app_argv,
        ]
        env = {**env_base, "SPARKNET_SLICE_ID": str(slice_of[pid])}
        if collector is not None:
            # each simulated host gets a stable fleet identity —
            # STABLE across a relaunch, so the collector sees the same
            # host come back with a new boot_id (restart detection)
            env["SPARKNET_HOST_ID"] = f"host{pid}"
        if relaunched:
            env["SPARKNET_RELAUNCHED"] = "1"
        p = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        procs.append(p)
        buf = []
        outputs.append((pid, p, buf))
        # drain every child's pipe CONCURRENTLY — a sequential
        # communicate() deadlocks once any later child fills its 64KB
        # pipe while an earlier one waits on it in a collective
        t = threading.Thread(
            target=lambda p=p, buf=buf: buf.extend(p.stdout),
            name=f"launch-drain-p{pid}",
            daemon=True,
        )
        t.start()
        readers.append(t)
        return p

    for pid in range(args.nprocs):
        spawn(pid)

    # slice-granular lifecycle: --preempt_slice kills a WHOLE simulated
    # slice (SIGTERM — the orchestrator's preemption notice) at
    # --preempt_at seconds and relaunches the same processes
    # --relaunch_after seconds later, same argv + SPARKNET_RELAUNCHED=1
    # — the launcher-level half of "train through a preempted slice"
    preempt_thread = None
    t_end = time.time() + args.timeout
    if getattr(args, "preempt_slice", None) is not None:
        members = slices[args.preempt_slice]

        def do_preempt():
            time.sleep(args.preempt_at)
            victims = [
                (pid, p) for pid, p, _ in list(outputs)
                if pid in members and p.poll() is None
            ]
            if not victims:
                # the run finished (or died) before the scheduled
                # preemption: there is nothing to preempt, and
                # relaunching would re-run the whole app from scratch
                # into a completed run's accounting
                print(
                    "launch: slice %d preemption skipped (no live "
                    "process in the slice)" % args.preempt_slice
                )
                return
            for pid, p in victims:
                preempt_killed.add(p.pid)
                p.send_signal(_signal.SIGTERM)
            print(
                "launch: slice %d preempted (SIGTERM to host(s) %s)"
                % (args.preempt_slice, sorted(pid for pid, _ in victims))
            )
            time.sleep(args.relaunch_after)
            if time.time() >= t_end:
                # the global deadline passed while we slept: the main
                # loop has killed everything and moved on — spawning
                # now would orphan fresh children behind its back
                print(
                    "launch: slice %d relaunch skipped (run deadline "
                    "passed)" % args.preempt_slice
                )
                return
            # orchestrator escalation: a victim that treated the
            # SIGTERM as a notice and kept running (--elastic children
            # do) is hard-killed and REAPED before its replacement
            # takes the same --process_id/coordinator identity — two
            # live children with one identity would wedge the join
            for pid, p in victims:
                if p.poll() is None:
                    p.kill()
            for pid, p in victims:
                try:
                    p.wait(timeout=30)
                # sparknet: except-ok(best-effort reap of a just-killed victim; the main wait loop owns final reaping and rc accounting)
                except Exception:  # noqa: BLE001
                    pass
            for pid in members:
                spawn(pid, relaunched=True)
            print(
                "launch: slice %d relaunched (host(s) %s)"
                % (args.preempt_slice, sorted(members))
            )

        preempt_thread = threading.Thread(
            target=do_preempt, name="launch-preempt", daemon=True
        )
        preempt_thread.start()

    rc = 0
    waited = 0
    while True:
        # procs may GROW (a relaunched slice): keep waiting until every
        # spawned process — original and relaunched — has exited
        current = list(procs)
        for p in current[waited:]:
            try:
                p.wait(timeout=max(1, t_end - time.time()))
            except subprocess.TimeoutExpired:
                for q in list(procs):
                    if q.poll() is None:
                        q.kill()
                rc = 1
        waited = len(current)
        if preempt_thread is not None and preempt_thread.is_alive():
            preempt_thread.join(timeout=max(1, t_end - time.time()))
        if time.time() >= t_end:
            # global deadline: nothing further may spawn — reap and go
            for q in list(procs):
                if q.poll() is None:
                    q.kill()
                    rc = rc or 1
            break
        if len(procs) == waited and (
            preempt_thread is None or not preempt_thread.is_alive()
        ):
            break
    for t in readers:
        t.join(timeout=30)
    for pid, p, buf in outputs:
        prefix = f"[host {pid}] "
        sys.stdout.write(
            "".join(prefix + line.rstrip("\n") + "\n" for line in buf)
        )
        if p.returncode != 0 and p.pid not in preempt_killed:
            # a deliberately-preempted incarnation's kill rc is the
            # fault we injected, not a failure
            rc = rc or p.returncode or 1
    if collector is not None:
        view = collector.fleet_view()
        f = view["fleet"]
        print(
            "launch: fleet summary — %d host(s): %d live, %d late, "
            "%d dead; round skew %s"
            % (
                f["hosts_total"], f["hosts_live"], f["hosts_late"],
                f["hosts_dead"], f["round_skew"],
            )
        )
        for h, st in sorted(view["hosts"].items()):
            if st["state"] != "live":
                print(
                    "launch:   %s is %s (round %s, last push %.1fs ago)"
                    % (h, st["state"], st["round"], st["last_push_age_s"])
                )
            else:
                print(
                    "launch:   %s is live (round %s, last push %.1fs ago)"
                    % (h, st["round"], st["last_push_age_s"])
                )
    return rc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # cluster lifecycle actions (spark_ec2.py real_main action dispatch
    # analog) live in tools/provision.py: `launch provision --dry-run ...`
    from sparknet_tpu.tools import provision

    if argv and argv[0] in provision.ACTIONS:
        return provision.main(argv[0], argv[1:])

    parser = argparse.ArgumentParser(
        prog="launch", description=__doc__.split("\n", 1)[0],
        epilog="cluster lifecycle actions (dispatched before app "
        "launch): launch provision|describe|run|ssh|teardown "
        "[--dry-run] ... — see `launch provision --help` and SETUP.md §1",
    )
    parser.add_argument(
        "--nprocs", type=int, default=0,
        help="spawn N local processes (simulation mode); 0 = this process "
        "IS one host of a real cluster",
    )
    parser.add_argument(
        "--devices_per_host", type=int, default=2,
        help="virtual CPU devices per simulated host (simulation mode)",
    )
    parser.add_argument(
        "--fleet_collector", nargs="?", default=None,
        const="127.0.0.1:0", metavar="HOST:PORT",
        help="simulation mode: start the fleet collector (obs/fleet.py) "
        "in the launcher and ship every simulated host's telemetry to "
        "it (appends --ship_to to each app argv); prints the merged "
        "live/late/dead summary at the end.  Real clusters pass the "
        "apps' own --fleet_collector/--ship_to flags instead",
    )
    parser.add_argument(
        "--slices", type=int, default=1,
        help="simulation mode: group the --nprocs processes into N "
        "contiguous simulated TPU slices (each child learns its slice "
        "via SPARKNET_SLICE_ID; pairs with the apps' --slices/"
        "--cross_slice_every two-tier averaging flags)",
    )
    parser.add_argument(
        "--preempt_slice", type=int, default=None, metavar="IDX",
        help="simulation mode: SIGTERM every process of slice IDX at "
        "--preempt_at seconds (the orchestrator's preemption notice) "
        "and relaunch them --relaunch_after seconds later with "
        "SPARKNET_RELAUNCHED=1 — kill and relaunch a whole simulated "
        "slice mid-run",
    )
    parser.add_argument(
        "--preempt_at", type=float, default=5.0,
        help="seconds into the run at which --preempt_slice fires",
    )
    parser.add_argument(
        "--relaunch_after", type=float, default=5.0,
        help="seconds after the preemption at which the slice's "
        "processes are relaunched",
    )
    parser.add_argument(
        "--coordinator", default=None, help="host:port of process 0"
    )
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    parser.add_argument("--timeout", type=int, default=1200)
    # lifecycle actions appear in choices purely for help/typo messages;
    # real action argv is dispatched above before argparse runs
    parser.add_argument(
        "app", choices=sorted(APPS) + list(provision.ACTIONS)
    )
    parser.add_argument("app_argv", nargs=argparse.REMAINDER,
                        help="arguments passed through to the app")
    args = parser.parse_args(argv)
    app_argv = [a for a in args.app_argv if a != "--"]

    if args.app in provision.ACTIONS:
        # lifecycle action given after launcher flags: the flags don't
        # apply to provisioning — require the action-first form instead
        # of falling into the app path (which would KeyError on APPS)
        print(
            f"launch: lifecycle action {args.app!r} must come first: "
            f"`launch {args.app} ...` (launcher flags like --nprocs do "
            "not apply to provisioning)",
            file=sys.stderr,
        )
        return 2

    if args.nprocs:
        return spawn_local(args, app_argv)
    return run_app(
        args.app, app_argv, args.coordinator, args.num_processes,
        args.process_id,
    )


if __name__ == "__main__":
    raise SystemExit(main())
