"""Multi-host launcher — the cluster bring-up layer (L8).

Reference: ``ec2/spark_ec2.py`` (provision EC2, wire master/workers, submit
apps) + ``SETUP.md``.  On TPU there is nothing to *provision* from inside
the job — the pod slice exists and every host runs the same program — so
the L8 role reduces to: start one process per host, join them through
``jax.distributed`` (``parallel/mesh.py initialize_distributed``), shard
the data per host, and run the app.  This tool does all three:

Local simulation (N processes on this machine, CPU devices standing in
for per-host chips — the development / CI path)::

    python -m sparknet_tpu.tools.launch --nprocs=2 --devices_per_host=2 \
        cifar --rounds=3 --tau=2

One process per real host (run the same line on EVERY host of the slice;
on Cloud TPU use ``gcloud ... ssh --worker=all --command=...``)::

    python -m sparknet_tpu.tools.launch \
        --coordinator=10.0.0.2:8476 --num_processes=4 --process_id=$WORKER_ID \
        imagenet --data=/mnt/imagenet --rounds=100

On a Cloud TPU VM the three flags can all be omitted —
``jax.distributed.initialize()`` discovers the slice topology from the
metadata server — so ``launch imagenet ...`` alone is a full bring-up.

Apps see the joined runtime: ``jax.process_count() > 1`` switches them to
global-mesh mode, loading only their own workers' partitions (see
``parallel.local_worker_slice``).  SETUP.md walks the full path from
"N TPU VMs" to a running multi-host ImageNetApp.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

APPS = {
    "cifar": "sparknet_tpu.apps.cifar_app",
    "imagenet": "sparknet_tpu.apps.imagenet_app",
    "cifar_db": "sparknet_tpu.apps.cifar_db_app",
    "imagenet_create_db": "sparknet_tpu.apps.imagenet_create_db_app",
    "imagenet_run_db": "sparknet_tpu.apps.imagenet_run_db_app",
    "featurizer": "sparknet_tpu.apps.featurizer_app",
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_app(app: str, app_argv, coordinator, num_processes, process_id) -> int:
    """Join the distributed runtime, then hand off to the app's main()."""
    import importlib

    from sparknet_tpu.parallel.mesh import initialize_distributed

    if coordinator or num_processes is not None:
        initialize_distributed(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        # Cloud TPU VM: topology comes from the metadata server
        initialize_distributed()
    mod = importlib.import_module(APPS[app])
    return int(mod.main(list(app_argv)) or 0)


def spawn_local(args, app_argv) -> int:
    """The CI/dev path: N OS processes on this machine, each given
    ``devices_per_host`` virtual CPU devices — process boundaries stand in
    for host boundaries exactly as in tests/test_multihost.py.

    With ``--fleet_collector`` the launcher starts the fleet collector
    (obs/fleet.py) and points every simulated host's shipper at it
    (``--ship_to`` appended to each app argv), so the whole run has ONE
    merged /fleet + /metrics view and the end-of-run summary names any
    late/dead host."""
    collector = None
    if args.fleet_collector:
        from sparknet_tpu.obs.fleet import FleetCollector, parse_hostport

        chost, cport = parse_hostport(args.fleet_collector)
        collector = FleetCollector(host=chost, port=cport).start()
        print(f"launch: fleet collector on {collector.url}/fleet")
        app_argv = list(app_argv) + [f"--ship_to={collector.url}"]
    try:
        return _spawn_local_procs(args, app_argv, collector)
    finally:
        # the listener thread + bound port must not outlive a failed
        # spawn/wait (Ctrl-C, bad app argv, a worker that never exits)
        if collector is not None:
            collector.close()


def _spawn_local_procs(args, app_argv, collector) -> int:
    port = free_port()
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env_base = {
        **os.environ,
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "PALLAS_AXON_POOL_IPS": "",  # never route the sim through a TPU tunnel
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            f"--xla_force_host_platform_device_count={args.devices_per_host} "
            + os.environ.get("SPARKNET_EXTRA_XLA_FLAGS", "")
        ).strip(),
    }
    import threading

    procs = []
    outputs = []
    readers = []
    for pid in range(args.nprocs):
        cmd = [
            sys.executable,
            "-m",
            "sparknet_tpu.tools.launch",
            f"--coordinator=127.0.0.1:{port}",
            f"--num_processes={args.nprocs}",
            f"--process_id={pid}",
            args.app,
            *app_argv,
        ]
        env = env_base
        if collector is not None:
            # each simulated host gets a stable fleet identity
            env = {**env_base, "SPARKNET_HOST_ID": f"host{pid}"}
        p = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        procs.append(p)
        outputs.append([])
        # drain every child's pipe CONCURRENTLY — a sequential
        # communicate() deadlocks once any later child fills its 64KB
        # pipe while an earlier one waits on it in a collective
        t = threading.Thread(
            target=lambda p=p, buf=outputs[-1]: buf.extend(p.stdout),
            name=f"launch-drain-p{pid}",
            daemon=True,
        )
        t.start()
        readers.append(t)

    rc = 0
    deadline = args.timeout
    for pid, p in enumerate(procs):
        try:
            p.wait(timeout=deadline)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            rc = 1
    for t in readers:
        t.join(timeout=30)
    for pid, (p, buf) in enumerate(zip(procs, outputs)):
        prefix = f"[host {pid}] "
        sys.stdout.write(
            "".join(prefix + line.rstrip("\n") + "\n" for line in buf)
        )
        if p.returncode != 0:
            rc = rc or p.returncode or 1
    if collector is not None:
        view = collector.fleet_view()
        f = view["fleet"]
        print(
            "launch: fleet summary — %d host(s): %d live, %d late, "
            "%d dead; round skew %s"
            % (
                f["hosts_total"], f["hosts_live"], f["hosts_late"],
                f["hosts_dead"], f["round_skew"],
            )
        )
        for h, st in sorted(view["hosts"].items()):
            if st["state"] != "live":
                print(
                    "launch:   %s is %s (round %s, last seen %.1fs ago)"
                    % (h, st["state"], st["round"], st["age_s"])
                )
    return rc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # cluster lifecycle actions (spark_ec2.py real_main action dispatch
    # analog) live in tools/provision.py: `launch provision --dry-run ...`
    from sparknet_tpu.tools import provision

    if argv and argv[0] in provision.ACTIONS:
        return provision.main(argv[0], argv[1:])

    parser = argparse.ArgumentParser(
        prog="launch", description=__doc__.split("\n", 1)[0],
        epilog="cluster lifecycle actions (dispatched before app "
        "launch): launch provision|describe|run|ssh|teardown "
        "[--dry-run] ... — see `launch provision --help` and SETUP.md §1",
    )
    parser.add_argument(
        "--nprocs", type=int, default=0,
        help="spawn N local processes (simulation mode); 0 = this process "
        "IS one host of a real cluster",
    )
    parser.add_argument(
        "--devices_per_host", type=int, default=2,
        help="virtual CPU devices per simulated host (simulation mode)",
    )
    parser.add_argument(
        "--fleet_collector", nargs="?", default=None,
        const="127.0.0.1:0", metavar="HOST:PORT",
        help="simulation mode: start the fleet collector (obs/fleet.py) "
        "in the launcher and ship every simulated host's telemetry to "
        "it (appends --ship_to to each app argv); prints the merged "
        "live/late/dead summary at the end.  Real clusters pass the "
        "apps' own --fleet_collector/--ship_to flags instead",
    )
    parser.add_argument(
        "--coordinator", default=None, help="host:port of process 0"
    )
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    parser.add_argument("--timeout", type=int, default=1200)
    # lifecycle actions appear in choices purely for help/typo messages;
    # real action argv is dispatched above before argparse runs
    parser.add_argument(
        "app", choices=sorted(APPS) + list(provision.ACTIONS)
    )
    parser.add_argument("app_argv", nargs=argparse.REMAINDER,
                        help="arguments passed through to the app")
    args = parser.parse_args(argv)
    app_argv = [a for a in args.app_argv if a != "--"]

    if args.app in provision.ACTIONS:
        # lifecycle action given after launcher flags: the flags don't
        # apply to provisioning — require the action-first form instead
        # of falling into the app path (which would KeyError on APPS)
        print(
            f"launch: lifecycle action {args.app!r} must come first: "
            f"`launch {args.app} ...` (launcher flags like --nprocs do "
            "not apply to provisioning)",
            file=sys.stderr,
        )
        return 2

    if args.nprocs:
        return spawn_local(args, app_argv)
    return run_app(
        args.app, app_argv, args.coordinator, args.num_processes,
        args.process_id,
    )


if __name__ == "__main__":
    raise SystemExit(main())
