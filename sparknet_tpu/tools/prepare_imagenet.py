"""Dataset preparation — the ``put_imagenet_on_s3.py`` role.

Reference: ``scripts/put_imagenet_on_s3.py:1-116`` — split the label
file into shuffled chunks, resize every JPEG, re-tar the chunks as
``train.XXXXX.tar`` / ``val.XXX.tar``, upload together with
``train.txt``/``val.txt``.  This tool produces exactly the layout the
read side consumes (``data/object_store.py ImageNetLoader`` +
SETUP.md §3): shards + label files + an ``index.txt`` manifest (the
listing used by plain-HTTP roots), written locally and optionally
synced to a bucket with ``gsutil``/``aws`` (``--dry-run`` prints the
exact command instead).

Inputs, either form per split:

- ``--train_dir DIR``: a ``<class>/<image>`` tree (labels derived from
  sorted class-folder order, or supplied via ``--train_labels``);
- ``--train_tar FILE``: the ILSVRC-style nested tar (a tar of per-class
  sub-tars), as the reference consumed.

Chunking matches the reference: shuffle the label lines once (seeded),
deal them round-robin into N chunks, one output shard per chunk.
"""

from __future__ import annotations

import argparse
import io
import os
import random
import shlex
import subprocess
import sys
import tarfile
from typing import Callable, Dict, Iterable, List, Optional, Tuple


def split_label_lines(
    pairs: List[Tuple[str, int]], num_chunks: int, seed: int = 0
) -> List[List[Tuple[str, int]]]:
    """Shuffle once, deal round-robin (put_imagenet_on_s3.py
    split_label_file)."""
    pairs = list(pairs)
    random.Random(seed).shuffle(pairs)
    chunks: List[List[Tuple[str, int]]] = [[] for _ in range(num_chunks)]
    for i, p in enumerate(pairs):
        chunks[i % num_chunks].append(p)
    return [c for c in chunks if c]


def resize_jpeg(data: bytes, size: Optional[Tuple[int, int]]) -> bytes:
    """Decode/resize/re-encode one image (ANTIALIAS resize + JPEG
    re-save, like resize_and_add_image).  ``size=None`` passes the
    original bytes through untouched — no decode cost and no
    re-encode generation loss for a byte-identity operation."""
    if size is None:
        return data
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    img = img.resize(size, Image.LANCZOS)
    out = io.BytesIO()
    img.save(out, format="JPEG")
    return out.getvalue()


def labels_from_dir(root: str) -> List[Tuple[str, int]]:
    """``<class>/<image>`` tree -> (relative name, label) with labels
    assigned by sorted class-folder order (the caffe_ilsvrc12 synset
    ordering convention)."""
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d))
    )
    pairs = []
    for label, cls in enumerate(classes):
        for name in sorted(os.listdir(os.path.join(root, cls))):
            pairs.append((f"{cls}/{name}", label))
    return pairs


def read_label_file(path: str) -> List[Tuple[str, int]]:
    pairs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                name, label = line.rsplit(None, 1)
                pairs.append((name, int(label)))
    return pairs


def dir_image_reader(root: str) -> Callable[[str], bytes]:
    def read(name: str) -> bytes:
        with open(os.path.join(root, name), "rb") as f:
            return f.read()

    return read


def build_tar_index(path: str) -> Dict[str, Tuple[int, int]]:
    """Index an ILSVRC-style tar-of-subtars: ``<subtar-stem>/<image>`` ->
    (absolute byte offset of the member data in the OUTER file, size).
    Both tars are uncompressed, so a member's bytes live at
    ``outer_member.offset_data + inner_member.offset_data`` and can be
    served by plain seek+read on one file handle.  The index is ints
    only — picklable and compact — so the parent builds it ONCE and
    ships it to pool workers; having every worker re-run getmembers()
    would re-read the whole (138 GB) train tar and hold a TarInfo per
    image per process (ADVICE r4)."""
    index: Dict[str, Tuple[int, int]] = {}
    with open(path, "rb") as probe:
        magic = probe.read(2)
    if magic in (b"\x1f\x8b", b"BZ", b"\xfd7"):  # gz / bz2 / xz
        raise ValueError(
            f"{path}: compressed tars are not seekable by raw offset — "
            "decompress the outer tar first (ILSVRC ships uncompressed)"
        )
    with tarfile.open(path) as outer:
        for member in outer.getmembers():
            # real tars carry directory entries / stray non-tar files
            # next to the class sub-tars; only regular .tar members are
            # sub-tars
            if not member.isfile() or not member.name.endswith(".tar"):
                continue
            stem = os.path.splitext(os.path.basename(member.name))[0]
            base = member.offset_data
            with tarfile.open(fileobj=outer.extractfile(member)) as sub:
                for m in sub.getmembers():
                    if not m.isfile():
                        continue
                    index[f"{stem}/{m.name}"] = (base + m.offset_data, m.size)
    return index


def nested_tar_reader(
    path: str, index: Optional[Dict[str, Tuple[int, int]]] = None
) -> Callable[[str], bytes]:
    """Fetch members of a tar-of-subtars by ``<subtar-stem>/<image>``
    via the offset index (built here if not supplied); bytes are read
    on demand through one kept-open descriptor, so memory stays flat.

    Reads use ``os.pread`` on a stored fd: the offset rides in the call
    (no shared seek cursor), so one reader is safe to share across
    threads — a seek+read pair on a shared handle interleaves under
    concurrency and returns bytes from the wrong member.  The fd is
    closed by a finalizer on the returned callable (no leak when the
    reader is dropped)."""
    import weakref

    if index is None:
        index = build_tar_index(path)
    by_basename = {os.path.basename(k): k for k in index}
    fd = os.open(path, os.O_RDONLY)

    def read(name: str) -> bytes:
        entry = index.get(name)
        if entry is None:
            # reference train.txt keys are sometimes bare file names
            key = by_basename.get(os.path.basename(name))
            if key is None:
                raise KeyError(name)
            entry = index[key]
        off, size = entry
        buf = os.pread(fd, size, off)  # atomic at-offset read
        if len(buf) != size:
            raise IOError(
                f"{path}: short read for {name!r} "
                f"({len(buf)}/{size} bytes at {off})"
            )
        return buf

    weakref.finalize(read, os.close, fd)
    return read


# reader spec -> reader, rebuilt once per worker process (open handles
# are not picklable; the tar OFFSET INDEX is, and rides in the spec so
# workers skip the full-tar re-index)
ReaderSpec = tuple  # ("dir", path) | ("tar", path, offset_index)
_WORKER_READER: Optional[Callable[[str], bytes]] = None


def _make_reader(spec: ReaderSpec) -> Callable[[str], bytes]:
    if spec[0] == "dir":
        return dir_image_reader(spec[1])
    return nested_tar_reader(spec[1], spec[2] if len(spec) > 2 else None)


def _init_worker(spec: ReaderSpec) -> None:
    global _WORKER_READER
    _WORKER_READER = _make_reader(spec)


def _write_one_shard(job) -> str:
    out_path, chunk, size = job
    with tarfile.open(out_path, "w") as tf:
        for name, _label in chunk:
            data = resize_jpeg(_WORKER_READER(name), size)
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return os.path.basename(out_path)


def write_shards(
    out_dir: str,
    prefix: str,
    chunks: List[List[Tuple[str, int]]],
    reader_spec: ReaderSpec,
    size: Optional[Tuple[int, int]],
    zfill: int,
    workers: int = 1,
) -> List[str]:
    """One output shard per chunk; with ``workers > 1`` chunks are
    written by a process pool (they are independent — the decode/resize/
    re-encode of the full 1.28M-image ImageNet is CPU-bound; each worker
    re-opens the source via ``reader_spec``)."""
    jobs = [
        (
            os.path.join(out_dir, f"{prefix}.{str(i).zfill(zfill)}.tar"),
            chunk,
            size,
        )
        for i, chunk in enumerate(chunks)
    ]
    if workers <= 1:
        _init_worker(reader_spec)
        return [_write_one_shard(j) for j in jobs]
    import multiprocessing as mp

    with mp.Pool(workers, initializer=_init_worker,
                 initargs=(reader_spec,)) as pool:
        return list(pool.map(_write_one_shard, jobs))


def upload_command(out_dir: str, dest: str) -> List[str]:
    """The sync command for a bucket destination (the upload_file role;
    gsutil for gs://, aws for s3://)."""
    if dest.startswith("gs://"):
        return ["gsutil", "-m", "rsync", "-r", out_dir, dest]
    if dest.startswith("s3://"):
        return ["aws", "s3", "sync", out_dir, dest]
    raise ValueError(f"unsupported destination {dest!r} (gs:// or s3://)")


def _prepare_split(
    split: str, src_dir, src_tar, labels_path, out_dir, num_chunks,
    size, seed, zfill, workers=1,
) -> List[str]:
    if src_dir:
        pairs = (
            read_label_file(labels_path) if labels_path
            else labels_from_dir(src_dir)
        )
        reader_spec: ReaderSpec = ("dir", src_dir)
    else:
        if not labels_path:
            raise SystemExit(
                f"--{split}_labels is required with --{split}_tar "
                "(nested tars carry no label information)"
            )
        pairs = read_label_file(labels_path)
        # index once in the parent; workers get the picklable offsets
        reader_spec = ("tar", src_tar, build_tar_index(src_tar))
    # the read side keys labels by BASENAME (ImageNetLoader.scala:41-54
    # semantics) — colliding basenames would silently corrupt labels, so
    # the producer refuses them
    seen: Dict[str, str] = {}
    for name, _ in pairs:
        base = os.path.basename(name)
        if base in seen and seen[base] != name:
            raise SystemExit(
                f"{split}: duplicate image basename {base!r} "
                f"({seen[base]!r} vs {name!r}) — the reader keys labels "
                "by basename, so names must be globally unique "
                "(rename, e.g. prefix the class)"
            )
        seen[base] = name
    with open(os.path.join(out_dir, f"{split}.txt"), "w") as f:
        for name, label in pairs:
            f.write(f"{name} {label}\n")
    chunks = split_label_lines(pairs, num_chunks, seed)
    shards = write_shards(
        out_dir, split, chunks, reader_spec, size, zfill, workers=workers
    )
    print(f"{split}: {len(pairs)} images -> {len(shards)} shards")
    return shards + [f"{split}.txt"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("out_dir")
    p.add_argument("--train_dir")
    p.add_argument("--train_tar")
    p.add_argument("--train_labels")
    p.add_argument("--val_dir")
    p.add_argument("--val_tar")
    p.add_argument("--val_labels")
    p.add_argument("--num_train_chunks", type=int, default=1000)
    p.add_argument("--num_val_chunks", type=int, default=50)
    p.add_argument("--resize", type=int, nargs=2, metavar=("W", "H"),
                   default=None, help="resize every image to WxH (the "
                   "reference default workflow uses 256 256)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workers", type=int, default=1,
                   help="process pool size for the decode/resize/re-tar "
                   "stage (chunks are independent)")
    p.add_argument("--upload", default=None,
                   help="gs://bucket/path or s3://bucket/path")
    p.add_argument("--dry-run", dest="dry_run", action="store_true",
                   help="with --upload: print the sync command only")
    args = p.parse_args(argv)

    os.makedirs(args.out_dir, exist_ok=True)
    size = tuple(args.resize) if args.resize else None
    files: List[str] = []
    if args.train_dir or args.train_tar:
        files += _prepare_split(
            "train", args.train_dir, args.train_tar, args.train_labels,
            args.out_dir, args.num_train_chunks, size, args.seed, 5,
            workers=args.workers,
        )
    if args.val_dir or args.val_tar:
        files += _prepare_split(
            "val", args.val_dir, args.val_tar, args.val_labels,
            args.out_dir, args.num_val_chunks, size, args.seed + 1, 3,
            workers=args.workers,
        )
    if not files:
        print("nothing to do: give --train_dir/--train_tar and/or "
              "--val_dir/--val_tar", file=sys.stderr)
        return 2
    # manifest for plain-HTTP roots (object_store.py lists index.txt)
    with open(os.path.join(args.out_dir, "index.txt"), "w") as f:
        for name in sorted(files):
            f.write(name + "\n")

    if args.upload:
        cmd = upload_command(args.out_dir, args.upload)
        if args.dry_run:
            print(shlex.join(cmd))
            return 0
        print("+ " + shlex.join(cmd), file=sys.stderr)
        return subprocess.call(cmd)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
