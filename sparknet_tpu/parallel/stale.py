"""Bounded-staleness parameter averaging: τ as a spectrum, not a gate.

The synchronous round (``ParameterAveragingTrainer``) is gated on the
slowest worker: one straggling slice taxes the whole fleet every
boundary.  This module implements the stale-synchronous-parallel relief
valve (Ho et al., SSP; FedBuff's buffered async aggregation): workers
run ahead up to a **staleness bound B** (``--stale_bound``), and the
averaging boundary takes **whoever has arrived** —

- each boundary ``b`` folds in the workers whose local τ-window has
  finished; the arrival set becomes a weight mask over the averaging
  collective, with per-worker **staleness-discounted weights**
  ``discount ** lag`` where ``lag = b - worker_rounds[w]``,
- a worker whose window is still in flight keeps ALL its local state
  (params, BN stats, momentum, iter) untouched — its contribution folds
  in at a later boundary instead of stalling this one,
- the bound is hard: a live worker at ``lag >= B`` is *forced* into the
  boundary — the harness blocks for it, which is exactly the (bounded)
  synchronous cost SSP pays to keep convergence guarantees,
- ``B = 0`` forces every live worker every round, and ``round()``
  delegates verbatim to the synchronous trainer — **bit-identical** to
  today's averaging (pinned by ``tests/test_stale.py``).

The averaging math changes with fractional weights.  The synchronous
``wmean`` is a *masked mean*: contributions enter at full value and the
denominator counts heads — correct for 0/1 masks, wrong for discounts
(a half-weight worker would be over-counted).  The stale programs use a
true weighted mean ``psum(w·θ) / psum(w)``, ``where``-guarded on both
sides so an absent worker's (possibly junk) replica can never leak
through ``0 * NaN`` into the sum.  Arrived workers adopt the mean;
absent workers keep their own replica — per-worker params now *diverge
between boundaries by design*, which is why stale jobstate snapshots
carry full per-worker replicas (``export_worker_replicas``) instead of
the consensus-plus-history layout of the sync driver.

Hierarchy goes **asymmetric** (the real-pod-elasticity leg): intra-slice
boundaries stay fast synchronous-style averaging *within each arrived
slice* every round, while the cross-slice tier is lazy and
stale-tolerant — a late or preempted slice is simply a maximally-stale
one, readmitted by the same discounted fold-in as any straggler.
Arrivals are coarsened to slices (a slice moves together, so its
members share one round clock).

Interplay contracts:

- **journal** (``io/journal.py``): the driver versions the full
  ``worker_rounds`` vector into every intent/commit record; a
  kill-anywhere resume replays ≤ B rounds bit-identically
  (``runtime/recover.py``, kill point ``stale_boundary``).
- **membership** (``runtime/membership.py``): the epoch clock orders
  roster views; a dead worker is excluded from forcing (it cannot
  arrive) and rejoins as maximally stale.
- **sentry** (``obs/health.py``): losses/audit stats of non-arrived
  workers are zeroed in-graph; ``HealthSentry.observe`` takes the
  arrival mask + ``worker_rounds`` so a lagging worker's loss is judged
  at its OWN round index and never trips a false anomaly.

Honesty note: on the virtual CPU mesh "running ahead" is *modeled* —
the harness decides arrival sets (seeded straggler schedules, sleeps
for wall-clock) and the trainer executes one fused program per
boundary in which non-arrived workers' speculative windows are
discarded in-graph.  The arrival/weight/ledger semantics, the journal
versioning, and the recovery contract are the real ones; only the
overlap of straggler compute with the boundary is simulated
(``bench.py --mode=stale`` measures the wall-clock consequences with
real sleeps).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from sparknet_tpu import obs
from sparknet_tpu.parallel.hierarchy import HierarchySpec
from sparknet_tpu.parallel.trainers import (
    ParameterAveragingTrainer,
    leading_sharding,
    shard_leading,
)
from sparknet_tpu.solver import Solver, TrainState
from sparknet_tpu.utils.rngs import default_train_key

tree_map = jax.tree_util.tree_map

# division guard for the weighted-mean denominator: an all-absent
# boundary never divides (the host skips dispatch), but an
# all-masked-by-audit one reaches the program with psum(w) == 0
_DENOM_EPS = 1e-8


def stale_window(window_fn, worker_rounds) -> Dict[str, np.ndarray]:
    """Assemble the mixed-round batch for one stale boundary: worker
    ``w``'s rows come from ``window_fn(worker_rounds[w])`` — each worker
    consumes the window of its OWN next round, not the boundary's.
    ``window_fn(r)`` is the usual absolute-round feed (leaves
    ``(num_workers, tau, ...)``); the result keeps that layout.  Rounds
    are deduplicated so a mostly-synchronous fleet costs ~1 feed call."""
    rounds = [int(r) for r in np.asarray(worker_rounds).reshape(-1)]
    per_round = {r: window_fn(r) for r in sorted(set(rounds))}
    out: Dict[str, np.ndarray] = {}
    first = per_round[rounds[0]]
    for key in first:
        base = np.array(np.asarray(first[key]), copy=True)
        for w, r in enumerate(rounds):
            base[w] = np.asarray(per_round[r][key])[w]
        out[key] = base
    return out


def export_worker_replicas(host_state) -> Dict:
    """Full per-worker TrainState stacks as a jobstate fragment (the
    ``stale`` key's ``replicas`` block).  Stale averaging makes worker
    replicas diverge between boundaries *by design* — absent workers
    keep their own params — so the sync driver's consensus-plus-history
    snapshot under-determines the fleet; resume needs every slot."""
    return {
        str(i): np.asarray(l)
        for i, l in enumerate(jax.tree_util.tree_leaves(host_state))
    }


def restore_worker_replicas(state, replicas: Dict, mesh: Mesh,
                            axis: str = "dp"):
    """Inverse of ``export_worker_replicas``: put journaled per-worker
    stacks back onto a placed state of the same geometry.  Shape
    mismatches fail loudly — the jobstate belongs to a different
    trainer geometry."""
    cur, treedef = jax.tree_util.tree_flatten(state)
    leaves = [np.asarray(replicas[str(i)]) for i in range(len(cur))]
    if any(
        tuple(l.shape) != tuple(np.asarray(c).shape)
        for l, c in zip(leaves, cur)
    ):
        raise ValueError(
            "jobstate worker replicas do not match this trainer's shapes"
        )
    host = jax.tree_util.tree_unflatten(treedef, leaves)
    return shard_leading(host, mesh, axis)


class BoundedStalenessTrainer:
    """τ-step local SGD + bounded-staleness weighted averaging.

    Wraps a synchronous ``ParameterAveragingTrainer`` (the classic
    fused round — the comm plane's compressed/overlapped collectives
    assume a synchronous boundary and are rejected for ``B > 0``) and
    adds the staleness machinery:

    - ``worker_rounds`` — the host-side round ledger, one entry per
      worker: how many τ-windows that worker has folded into a
      boundary.  ``lag = boundary - worker_rounds[w]``; journaled by
      the driver every intent/commit (``export_stale_state``).
    - ``round(state, batches, arrived=...)`` — one boundary.  With
      ``stale_bound == 0`` this is a verbatim delegation to the sync
      trainer (bit-identity).  Otherwise the arrival set (host bools,
      coarsened to slices under a two-tier hierarchy, forced at
      ``lag >= B``, masked by ``live_mask``) picks the jitted stale
      program: global weighted mean on flat/cross boundaries,
      per-slice weighted mean on intra boundaries.
    - ``last_boundary`` — the boundary's host-side readout (lags,
      arrival/forced/skipped masks, weights): the telemetry source and
      what drivers journal beside ``worker_rounds``.

    ``batches`` at a stale boundary must be mixed-round (each worker's
    rows from ITS own next round — ``stale_window``); non-arrived
    workers' rows are computed speculatively and discarded in-graph, so
    their content only matters for arrived workers.
    """

    def __init__(
        self,
        solver: Solver,
        mesh: Mesh,
        axis: str = "dp",
        *,
        stale_bound: int = 0,
        discount: float = 0.5,
        average_stats: bool = True,
        average_params: bool = True,
        mask_nonfinite: bool = True,
        compress: str = "none",
        overlap_avg: bool = False,
        hierarchy: Optional[HierarchySpec] = None,
        batch_spec=None,
    ):
        if stale_bound < 0:
            raise ValueError(f"stale_bound={stale_bound}: must be >= 0")
        if not (0.0 < discount <= 1.0):
            raise ValueError(
                f"discount={discount}: must be in (0, 1]"
            )
        if stale_bound > 0 and (compress != "none" or overlap_avg):
            # the comm plane's delta-quantized/overlapped collectives
            # carry error-feedback residuals anchored on a synchronous
            # consensus; a partial-arrival boundary breaks the anchor.
            raise ValueError(
                "stale_bound > 0 does not compose with "
                "compress/overlap_avg (the comm plane assumes "
                "synchronous boundaries); run compress='none'"
            )
        self.base = ParameterAveragingTrainer(
            solver, mesh, axis,
            average_stats=average_stats,
            average_params=average_params,
            mask_nonfinite=mask_nonfinite,
            compress=compress,
            overlap_avg=overlap_avg,
            hierarchy=hierarchy,
            batch_spec=batch_spec,
        )
        self.solver = solver
        self.mesh = mesh
        self.axis = axis
        self.num_workers = self.base.num_workers
        self.audit = self.base.audit
        self.hierarchy = hierarchy
        self.stale_bound = int(stale_bound)
        self.discount = float(discount)
        # the staleness ledger: worker w has folded worker_rounds[w]
        # τ-windows into some boundary; boundary counter rides beside
        # it for drivers that don't pass absolute round indices
        self.worker_rounds = np.zeros((self.num_workers,), np.int64)
        self._boundary = 0
        # last boundary's host readout (None until the first round)
        self.last_boundary: Optional[Dict] = None

        if self.stale_bound == 0:
            # pure delegation — no stale programs to build
            self._stale_round = None
            self._stale_slice_round = None
            return

        audit = self.audit
        mask_nf = self.base.mask_nonfinite
        two_tier = self.base._two_tier

        def fold(st, bt, rng, weights, stepm):
            """Shared per-worker body: speculative τ-window + in-graph
            discard for non-arrived workers.  Returns the post-select
            state pieces and this worker's (weight, stepped, bad)."""
            widx = jax.lax.axis_index(axis)
            lrng = jax.random.fold_in(rng, widx)
            stepped, out = solver._step_tau(st, bt, lrng)
            if audit:
                losses, astats = out
            else:
                losses, astats = out, None
            step = stepm[0]
            w = weights[0]
            keep = step > 0
            # a non-arrived worker's window is still in flight: the
            # speculative step is discarded wholesale — params, BN
            # stats, momentum, iter, losses, audit stats — so its
            # replica is bit-untouched until its own fold-in boundary
            sel = lambda a, b: jnp.where(keep, a, b)
            params = tree_map(sel, stepped.params, st.params)
            stats = tree_map(sel, stepped.stats, st.stats)
            history = tree_map(sel, stepped.history, st.history)
            it = jnp.where(keep, stepped.iter, st.iter)
            losses = jnp.where(keep, losses, jnp.zeros_like(losses))
            bad = None
            if audit:
                astats = tree_map(
                    lambda a: jnp.where(keep, a, jnp.zeros_like(a)),
                    astats,
                )
            if mask_nf:
                # in-graph sentry mask composes: an ARRIVED worker
                # whose own window produced non-finite grads/params
                # contributes weight 0 (its astats are zeroed above
                # when absent, so absent never reads as bad)
                bad = (
                    jnp.sum(astats["nonfinite_grads"])
                    + jnp.sum(astats["nonfinite_params"])
                ) > 0
                ok = jnp.where(bad, 0.0, 1.0)
                w = w * ok
                astats = dict(astats, masked=(1.0 - ok) * step)
            return params, stats, history, it, losses, astats, w, keep, bad

        def finish(params, stats, history, it, losses, astats,
                   keep, bad, swmean, any_arr):
            avg_params = (
                tree_map(swmean, params) if average_params else params
            )
            avg_stats = (
                tree_map(swmean, stats)
                if average_stats and average_params
                else stats
            )
            if mask_nf and average_params:
                # an audit-masked arrival adopts the survivor mean but
                # its momentum still holds the poisoned window — zero
                # it (the sync round's rejoin contract); absent workers
                # never match (bad is zeroed with their astats)
                rejoined = jnp.logical_and(
                    bad, jnp.logical_and(keep, any_arr)
                )
                history = tree_map(
                    lambda h: jnp.where(rejoined, jnp.zeros_like(h), h),
                    history,
                )
            st = TrainState(avg_params, avg_stats, history, it)
            if audit:
                return (
                    tree_map(lambda x: x[None], st),
                    losses[None],
                    tree_map(lambda x: x[None], astats),
                )
            return tree_map(lambda x: x[None], st), losses[None]

        def stale_body(state, batches, rng, weights, stepm):
            st = tree_map(lambda x: x[0], state)
            bt = tree_map(lambda x: x[0], batches)
            (params, stats, history, it, losses, astats,
             w, keep, bad) = fold(st, bt, rng, weights, stepm)
            # true weighted mean psum(w·θ)/psum(w): discounted weights
            # are fractional, so the head-count denominator of the sync
            # wmean would over-weight stale arrivals.  where()-guarded
            # on both sides: an absent worker's replica never enters
            # the sum, and only arrived workers adopt the mean.
            denomw0 = jax.lax.psum(w, axis)
            denomw = jnp.maximum(denomw0, _DENOM_EPS)
            any_arr = denomw0 > 0

            def swmean(x):
                contrib = jnp.where(
                    w > 0, x * w.astype(x.dtype), jnp.zeros_like(x)
                )
                m = jax.lax.psum(contrib, axis) / denomw.astype(x.dtype)
                # arrived adopt the mean (an audit-masked arrival
                # rejoins healthy, like the sync round); absent keep
                # their own replica; if NO arrival is finite everyone
                # keeps own so the host sentry sees the damage
                return jnp.where(
                    jnp.logical_and(keep, any_arr), m, x
                )

            return finish(params, stats, history, it, losses, astats,
                          keep, bad, swmean, any_arr)

        out_specs = (
            (P(axis), P(axis), P(axis)) if audit else (P(axis), P(axis))
        )
        batch_in_spec = (
            P(axis) if batch_spec is None else batch_spec
        )
        shmap_kw = {}
        if batch_spec is not None:
            from sparknet_tpu.parallel.ring_attention import (
                seq_shmap_kwargs,
            )

            shmap_kw = seq_shmap_kwargs()
        self._stale_round = jax.jit(
            shard_map(
                stale_body,
                mesh=mesh,
                in_specs=(
                    P(axis), batch_in_spec, P(), P(axis), P(axis)
                ),
                out_specs=out_specs,
                **shmap_kw,
            ),
            donate_argnums=(0, 1),
        )
        obs.track_jit(self._stale_round)

        # asymmetric hierarchy: intra-slice boundaries average the
        # arrived workers WITHIN each slice (stacked per-slice psum —
        # same lowering workaround as the sync slice program); the
        # cross tier reuses the global stale program above
        self._stale_slice_round = None
        if two_tier:
            slice_ids = jnp.asarray(hierarchy.slice_ids(), jnp.int32)
            num_slices = hierarchy.num_slices

            def stale_slice_body(state, batches, rng, weights, stepm):
                st = tree_map(lambda x: x[0], state)
                bt = tree_map(lambda x: x[0], batches)
                (params, stats, history, it, losses, astats,
                 w, keep, bad) = fold(st, bt, rng, weights, stepm)
                widx = jax.lax.axis_index(axis)
                sid = slice_ids[widx]
                onehot = (
                    jnp.arange(num_slices, dtype=jnp.int32) == sid
                ).astype(jnp.float32)
                denomw_all = jax.lax.psum(onehot * w, axis)
                denomw0 = jnp.take(denomw_all, sid)
                denomw = jnp.maximum(denomw0, _DENOM_EPS)
                any_arr = denomw0 > 0

                def sswmean(x):
                    contrib = jnp.where(
                        w > 0, x * w.astype(x.dtype), jnp.zeros_like(x)
                    )
                    stacked = (
                        onehot.reshape((num_slices,) + (1,) * x.ndim)
                        * contrib[None]
                    )
                    sums = jax.lax.psum(stacked, axis)
                    m = jnp.take(sums, sid, axis=0) / denomw.astype(
                        x.dtype
                    )
                    return jnp.where(
                        jnp.logical_and(keep, any_arr), m, x
                    )

                return finish(params, stats, history, it, losses,
                              astats, keep, bad, sswmean, any_arr)

            self._stale_slice_round = jax.jit(
                shard_map(
                    stale_slice_body,
                    mesh=mesh,
                    in_specs=(
                        P(axis), batch_in_spec, P(), P(axis), P(axis)
                    ),
                    out_specs=out_specs,
                    **shmap_kw,
                ),
                donate_argnums=(0, 1),
            )
            obs.track_jit(self._stale_slice_round)

    # ------------------------------------------------------------------
    # delegation: placement / eval / jobstate surfaces are the base's
    def init_state(self, seed: int = 0) -> TrainState:
        return self.base.init_state(seed)

    def broadcast_state(self, st: TrainState) -> TrainState:
        return self.base.broadcast_state(st)

    def test_and_store_result(self, *a, **kw):
        return self.base.test_and_store_result(*a, **kw)

    def finalize(self, state: TrainState) -> TrainState:
        return self.base.finalize(state)

    def export_comm_state(self):
        return self.base.export_comm_state()

    def restore_comm_state(self, exported) -> None:
        self.base.restore_comm_state(exported)

    def reset_comm_state(self) -> None:
        self.base.reset_comm_state()

    # ------------------------------------------------------------------
    # the staleness ledger (journaled every intent/commit)
    def export_stale_state(self) -> Dict:
        """The ledger as a jobstate/journal fragment: the bound, the
        discount, the boundary counter, and the full per-worker round
        vector — what a kill-anywhere resume replays from."""
        return {
            "stale_bound": np.asarray(self.stale_bound, np.int64),
            "discount": np.asarray(self.discount, np.float64),
            "boundary": np.asarray(self._boundary, np.int64),
            "worker_rounds": np.asarray(self.worker_rounds, np.int64),
        }

    def reset_stale_state(self) -> None:
        """Zero the ledger (fresh-run entry for a reused trainer: the
        in-process chaos/recover harnesses run control/crash/resume
        legs off one compiled context)."""
        self.worker_rounds[:] = 0
        self._boundary = 0
        self.last_boundary = None

    def load_stale_state(self, frag: Dict) -> None:
        wr = np.asarray(frag["worker_rounds"], np.int64).reshape(-1)
        if wr.shape[0] != self.num_workers:
            raise ValueError(
                f"stale jobstate covers {wr.shape[0]} workers, mesh "
                f"has {self.num_workers}"
            )
        self.worker_rounds = wr.copy()
        self._boundary = int(np.asarray(frag["boundary"]))

    def lags(self, boundary: Optional[int] = None) -> np.ndarray:
        """Per-worker staleness at ``boundary`` (default: the next
        one): ``boundary - worker_rounds``, floored at 0."""
        b = self._boundary if boundary is None else int(boundary)
        return np.maximum(b - self.worker_rounds, 0)

    # ------------------------------------------------------------------
    def _arrival_sets(self, b: int, arrived, live: np.ndarray):
        """Resolve one boundary's arrival semantics on the host:
        returns ``(eff, forced, lag)`` — the effective arrival mask
        (bools), which of those were forced by the bound, and the
        per-worker lag.  Dead workers never arrive and never force (a
        preempted slice just goes maximally stale); under a two-tier
        hierarchy arrivals coarsen to whole slices."""
        lag = np.maximum(b - self.worker_rounds, 0)
        if arrived is None:
            arr = live > 0
        else:
            arr = np.asarray(arrived, bool).reshape(-1)
            if arr.shape[0] != self.num_workers:
                raise ValueError(
                    f"arrived has {arr.shape[0]} entries, mesh has "
                    f"{self.num_workers} workers"
                )
            arr = arr & (live > 0)
        # the hard bound: a LIVE worker at lag >= B is forced into the
        # boundary (the harness blocks for it — SSP's bounded sync
        # cost).  Dead workers are exempt: they cannot arrive at all.
        forced = (lag >= self.stale_bound) & (live > 0) & ~arr
        eff = arr | forced
        if self.base._two_tier:
            # slices move together: a slice arrives iff every live
            # member did (dead members don't hold it back), so members
            # share one round clock
            eff2 = eff.copy()
            for members in self.hierarchy.slices:
                m = np.asarray(members, np.int64)
                lv = live[m] > 0
                ok = bool(np.all(eff[m] | ~lv)) and bool(np.any(lv))
                eff2[m] = ok & lv
            forced = forced & eff2
            eff = eff2
        return eff, forced, lag

    def round(
        self,
        state: TrainState,
        batches: Dict[str, jax.Array],
        rng=None,
        arrived=None,
        live_mask=None,
        round_index: Optional[int] = None,
    ):
        """One averaging boundary.

        ``arrived`` (num_workers,) bools: whose τ-window has finished
        by this boundary (None = everyone live — the synchronous
        degenerate case).  The trainer forces live workers at
        ``lag >= stale_bound`` into the set and coarsens to slices
        under a two-tier hierarchy; the resolved masks land in
        ``self.last_boundary``.

        With ``stale_bound == 0`` this delegates verbatim to the
        synchronous ``ParameterAveragingTrainer.round`` (bit-identity
        pinned by the degenerate-path regression test).  A boundary
        with NO arrivals (possible only for ``B > 0``) skips dispatch
        entirely: returns the state untouched with zero losses (and
        ``None`` audit stats) — drivers consult ``last_boundary`` and
        skip the sentry for skipped boundaries."""
        b = self._boundary if round_index is None else int(round_index)
        if live_mask is None:
            live = np.ones((self.num_workers,), np.float32)
        else:
            live = np.asarray(live_mask, np.float32).reshape(-1)
        if self.stale_bound == 0:
            out = self.base.round(
                state, batches, rng=rng, live_mask=live_mask,
                round_index=round_index,
            )
            self._boundary = b + 1
            # ledger stays coherent for telemetry/journal symmetry:
            # every live worker folded its window this boundary
            self.worker_rounds[live > 0] += 1
            self.last_boundary = {
                "boundary": b,
                "lag": [0] * self.num_workers,
                "arrived": [bool(v > 0) for v in live],
                "forced": [False] * self.num_workers,
                "weights": [float(v > 0) for v in live],
                "skipped": False,
                "tier": "sync",
            }
            self._emit_metrics()
            return out

        eff, forced, lag = self._arrival_sets(b, arrived, live)
        weights = np.where(
            eff, np.power(self.discount, lag.astype(np.float64)), 0.0
        ).astype(np.float32)
        intra = (
            self.base._two_tier
            and not self.hierarchy.is_cross_round(b)
        )
        tier = "intra" if intra else "cross"
        self.last_boundary = {
            "boundary": b,
            "lag": [int(v) for v in lag],
            "arrived": [bool(v) for v in eff],
            "forced": [bool(v) for v in forced],
            "weights": [float(v) for v in weights],
            "skipped": not bool(eff.any()),
            "tier": tier,
        }
        self._boundary = b + 1
        if not eff.any():
            # nobody reached this boundary (all in flight, none at the
            # bound): the boundary itself is skipped — no program, no
            # state change, no ledger advance
            self._emit_metrics()
            tau = int(
                next(iter(jax.tree_util.tree_leaves(batches))).shape[1]
            )
            losses = np.zeros((self.num_workers, tau), np.float32)
            if self.audit:
                return state, losses, None
            return state, losses
        self.worker_rounds[eff] += 1

        rng = rng if rng is not None else default_train_key(0)
        sharding = leading_sharding(self.mesh, self.axis)
        w_dev = jax.device_put(weights, sharding)
        step_dev = jax.device_put(
            eff.astype(np.float32), sharding
        )
        astats = None
        with obs.span("average"):
            prog = (
                self._stale_slice_round if intra else self._stale_round
            )
            with obs.span("execute"):
                if self.audit:
                    state, losses, astats = prog(
                        state, batches, rng, w_dev, step_dev
                    )
                else:
                    state, losses = prog(
                        state, batches, rng, w_dev, step_dev
                    )
            self.solver.note_losses(losses)
        tm = obs.training_metrics()
        if tm is not None:
            tm.rounds.inc()
            tm.iters.inc(losses.shape[-1])
            if self.hierarchy is not None and self.base.average_params:
                tm.hierarchy_rounds.labels(tier).inc()
                tm.hierarchy_bytes.labels(tier).inc(
                    self.base._payload_bytes(state)
                )
        self._emit_metrics()
        obs.report_healthy()
        if self.audit:
            return state, losses, astats
        return state, losses

    def _emit_metrics(self) -> None:
        """Publish the boundary readout on the shared registry:
        per-worker staleness gauge, arrival/skip counters, forced-wait
        counter (the bound's synchronous cost, the quantity the stale
        bench wants ≈ 0 for a straggler within the bound)."""
        tm = obs.training_metrics()
        lb = self.last_boundary
        if tm is None or lb is None:
            return
        for w in range(self.num_workers):
            tm.staleness.labels(str(w)).set(float(lb["lag"][w]))
            if lb["arrived"][w]:
                tm.stale_arrivals.labels(str(w)).inc()
            else:
                tm.stale_skipped.labels(str(w)).inc()
        nforced = sum(1 for v in lb["forced"] if v)
        if nforced:
            tm.stale_forced_waits.inc(nforced)
        if lb["skipped"]:
            tm.stale_boundaries_skipped.inc()
