"""Two-tier hierarchical averaging topology: the ``HierarchySpec``.

The reference paper's own tau-vs-workers tradeoff (SparkNet §4) applied
across the slice boundary: communication inside a TPU slice rides the
ICI fabric (cheap, every round), communication *between* slices rides
the DCN (expensive, amortized).  One declarative spec carries both
decisions:

- the **slice grouping** — which dp workers share a slice (on a real
  pod: which workers share an ICI domain; on the virtual CPU mesh: a
  declared partition of the dp axis), and
- **K = cross_slice_every** — intra-slice parameter averaging runs
  every round, the cross-slice (DCN) average every K-th round.

``ParameterAveragingTrainer(hierarchy=spec)`` consumes the spec: rounds
where ``(r + 1) % K != 0`` average within each slice only (a per-slice
masked weighted mean, same survivor/sentry semantics as the global
round), every K-th round runs the ordinary GLOBAL round — which is
exactly today's single-tier program, so compression and overlap
(``parallel/comm.py``) compose unchanged on the cross-slice tier.

**Flat specs are bit-identical to today's round by construction**: a
single-slice grouping or ``K == 1`` produces the single-tier schedule
(every round global), and global rounds run the SAME jitted program as
a hierarchy-less trainer — pinned like the PR-3/PR-5 identity tests.

Virtual-mesh honesty (the PERF.md modeled-bytes convention): this jax
build's shard_map does not lower ``psum(axis_index_groups=...)``, so
the intra-slice tier is expressed as a stacked per-slice psum (each
worker selects its own slice's row) — on the CPU simulation collectives
are shared-memory copies either way, and the tier-split byte accounting
(``sparknet_hierarchy_bytes_total{tier}``) models what the ICI vs DCN
fabrics would actually carry.  On a real pod the same spec maps to a
``(slice, worker)`` mesh factorization.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class HierarchySpec:
    """A partition of the dp workers into slices plus the cross-slice
    averaging cadence K.  Immutable and validated at construction."""

    num_workers: int
    slices: Tuple[Tuple[int, ...], ...]
    cross_slice_every: int = 1

    def __post_init__(self):
        if self.num_workers < 1:
            raise ValueError(f"num_workers={self.num_workers} < 1")
        if self.cross_slice_every < 1:
            raise ValueError(
                f"cross_slice_every={self.cross_slice_every} < 1"
            )
        seen = [w for s in self.slices for w in s]
        if sorted(seen) != list(range(self.num_workers)):
            raise ValueError(
                "slices must partition workers 0..%d exactly (got %r)"
                % (self.num_workers - 1, self.slices)
            )
        if any(len(s) == 0 for s in self.slices):
            raise ValueError("empty slice in %r" % (self.slices,))

    # ------------------------------------------------------------------
    @classmethod
    def flat(cls, num_workers: int) -> "HierarchySpec":
        """The single-tier topology: one slice holding every worker.
        A trainer given this spec is bit-identical to one given none."""
        return cls(num_workers, (tuple(range(num_workers)),), 1)

    @classmethod
    def grouped(
        cls, num_workers: int, num_slices: int, cross_slice_every: int = 1
    ) -> "HierarchySpec":
        """Contiguous near-equal grouping (the launcher's process->slice
        rule): workers [0..n) split into ``num_slices`` blocks."""
        num_slices = max(1, min(int(num_slices), num_workers))
        bounds = [
            round(i * num_workers / num_slices)
            for i in range(num_slices + 1)
        ]
        slices = tuple(
            tuple(range(bounds[i], bounds[i + 1]))
            for i in range(num_slices)
        )
        return cls(num_workers, slices, cross_slice_every)

    # ------------------------------------------------------------------
    @property
    def num_slices(self) -> int:
        return len(self.slices)

    def is_flat(self) -> bool:
        """True when the schedule degenerates to single-tier: one slice,
        or a cross-slice average every round.  The trainer then runs
        the ordinary global program every round (bit-identity)."""
        return self.num_slices <= 1 or self.cross_slice_every <= 1

    def is_cross_round(self, r: int) -> bool:
        """Whether absolute round ``r`` runs the cross-slice (global)
        average.  Flat specs are always cross (= today's round)."""
        return self.is_flat() or ((r + 1) % self.cross_slice_every) == 0

    def slice_of(self, worker: int) -> int:
        for i, s in enumerate(self.slices):
            if worker in s:
                return i
        raise ValueError(f"worker {worker} not in any slice")

    def slice_ids(self) -> Tuple[int, ...]:
        """Per-worker slice index, worker-ordered — the static array the
        trainer's intra-slice program closes over."""
        out = [0] * self.num_workers
        for i, s in enumerate(self.slices):
            for w in s:
                out[w] = i
        return tuple(out)


# ----------------------------------------------------------------------
# CLI surface (the averaging apps share it, like parallel/comm.py's)


def add_cli_args(parser) -> None:
    """``--slices`` / ``--cross_slice_every`` / ``--elastic`` — the
    two-tier topology + elastic-membership surface of the parameter-
    averaging apps."""
    parser.add_argument(
        "--slices", type=int, default=1,
        help="group the dp workers into N contiguous slices for two-"
        "tier averaging: every-round psum inside a slice, cross-slice "
        "(DCN) averaging every --cross_slice_every rounds.  1 = flat "
        "(today's single-tier round, bit-identical)",
    )
    parser.add_argument(
        "--cross_slice_every", type=int, default=1,
        help="K: run the cross-slice (global) average every K-th round; "
        "intra-slice rounds in between.  1 = every round global "
        "(bit-identical to the flat schedule)",
    )
    parser.add_argument(
        "--rejoin_after", type=int, default=2,
        help="--elastic: request a departed slice's rejoin N round "
        "boundaries after its leave completes (the single-process "
        "stand-in for the orchestrator's relaunch notice; 0 = rejoin "
        "only on external events — fleet views / note_join)",
    )
    parser.add_argument(
        "--stale_bound", type=int, default=0,
        help="bounded-staleness averaging (parallel/stale.py): let "
        "workers run ahead up to B rounds; each boundary averages "
        "whoever has arrived with staleness-discounted weights and a "
        "live worker at lag B is forced in.  0 = today's synchronous "
        "round, bit-identical (the degenerate-path pin).  With "
        "--slices the hierarchy goes asymmetric: intra-slice sync "
        "every round, lazy stale-tolerant cross-slice",
    )
    parser.add_argument(
        "--stale_discount", type=float, default=0.5,
        help="per-round staleness weight decay for --stale_bound > 0: "
        "a lag-L arrival enters the boundary's weighted mean at "
        "discount**L (1.0 = no discount; default 0.5)",
    )
    parser.add_argument(
        "--elastic", action="store_true",
        help="arm the elastic membership controller "
        "(runtime/membership.py): epoch-numbered views of the worker "
        "roster drive the round's live_mask, a SIGTERM preemption "
        "notice marks its slice leaving at the next round boundary, "
        "and a departed slice rejoins at a later view epoch via "
        "broadcast_state (membership metrics + /healthz block ride "
        "--obs)",
    )


def spec_from_args(args, num_workers: int) -> Optional["HierarchySpec"]:
    """Build the spec the CLI flags describe, or None for the flat
    default (no spec at all — the trainer keeps its classic path)."""
    slices = int(getattr(args, "slices", 1) or 1)
    every = int(getattr(args, "cross_slice_every", 1) or 1)
    if slices <= 1 and every <= 1 and not getattr(args, "elastic", False):
        return None
    return HierarchySpec.grouped(num_workers, max(1, slices), max(1, every))


def trainer_kwargs_from_args(args, num_workers: int) -> dict:
    """Trainer kwargs for the hierarchy from parsed CLI args (the
    ``comm.comm_kwargs_from_args`` pattern)."""
    return {"hierarchy": spec_from_args(args, num_workers)}


def stale_kwargs_from_args(args) -> dict:
    """``BoundedStalenessTrainer`` kwargs from parsed CLI args, or an
    empty dict when ``--stale_bound`` stays at the synchronous default
    (the apps then construct the plain averaging trainer)."""
    bound = int(getattr(args, "stale_bound", 0) or 0)
    if bound <= 0:
        return {}
    return {
        "stale_bound": bound,
        "discount": float(getattr(args, "stale_discount", 0.5) or 0.5),
    }


def averaging_trainer_from_args(args, solver, mesh, num_workers, **extra):
    """The round-averaging trainer the CLI flags describe: the plain
    ``ParameterAveragingTrainer``, or — with ``--stale_bound > 0`` —
    the ``BoundedStalenessTrainer`` wrapping it (same round surface;
    the stale trainer itself rejects compress/overlap combinations).
    Comm kwargs and the hierarchy spec are folded in from ``args``;
    ``extra`` overrides (pass ``hierarchy=spec`` when the app already
    built the spec for the membership controller)."""
    from sparknet_tpu.parallel import comm as comm_mod
    from sparknet_tpu.parallel.trainers import ParameterAveragingTrainer

    kw = dict(comm_mod.comm_kwargs_from_args(args))
    kw.update(extra)
    kw.setdefault("hierarchy", spec_from_args(args, num_workers))
    stale = stale_kwargs_from_args(args)
    if stale:
        from sparknet_tpu.parallel.stale import BoundedStalenessTrainer

        return BoundedStalenessTrainer(solver, mesh, **kw, **stale)
    return ParameterAveragingTrainer(solver, mesh, **kw)


def slice_members(nprocs: int, num_slices: int) -> Tuple[Tuple[int, ...], ...]:
    """Contiguous process->slice grouping for the launcher's simulated
    slice lifecycle (process indices, not worker indices)."""
    return HierarchySpec.grouped(nprocs, num_slices).slices
