"""Ring attention: sequence parallelism over a mesh axis.

Long sequences shard along time across the ``sp`` mesh axis; each device
holds (B, T/N, H, D) of Q, K, V.  KV shards rotate around the ring with
``lax.ppermute`` (one ICI hop per step, overlapping compute with the next
transfer) while each device accumulates its queries' attention with the
online-softmax (flash) recurrence — so attention over a sequence N times
longer than one chip could hold costs N ring steps and O(T/N) memory per
chip.  This is the blockwise/ring-attention construction from the public
literature (Liu et al., "Ring Attention with Blockwise Transformers"),
expressed with XLA collectives.

Use inside ``shard_map`` (see ``ring_self_attention`` for the wrapped
form).  Exactness: matches single-device attention up to float
associativity — pinned by tests on the CPU mesh.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

try:
    _pcast = lax.pcast  # jax >= 0.7: the varying-type system
    _SHMAP_KW = {}
except AttributeError:  # pragma: no cover - version-dependent
    def _pcast(x, axis_name, to="varying"):
        # pre-varying jax has no replication typing to satisfy; the
        # loop-carry semantics are identical without the annotation
        return x

    # pre-varying shard_map mis-types the ppermute loop carries under
    # autodiff (replication checker, not semantics) — disable the check
    _SHMAP_KW = {"check_rep": False}


def seq_shmap_kwargs() -> dict:
    """Extra ``shard_map`` kwargs any program needs when its body
    carries ring collectives (ppermute loop carries / sp psums) under
    autodiff on this jax build — the check_rep backport, shared with
    the trainers so their sequence-parallel rounds lower on the same
    jax versions this module does.  Empty on varying-typed jax
    (>= 0.7), ``{"check_rep": False}`` before it."""
    return dict(_SHMAP_KW)


def _merge_partials(o1, lse1, o2, lse2):
    """Online-softmax combine of two partial attentions over disjoint
    key sets: ``(o, lse)`` each normalized within its own keys, lse the
    row logsumexp ( -inf == no visible keys).  Differentiable — every
    -inf/0 leg is guarded so no NaN survives into either the value or
    the cotangent path."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m == -jnp.inf, 0.0, m)
    w1 = jnp.exp(lse1 - m_safe)  # exp(-inf) = 0: absent side drops out
    w2 = jnp.exp(lse2 - m_safe)
    den = w1 + w2
    den_safe = jnp.maximum(den, 1e-30)
    o = (w1[..., None] * o1 + w2[..., None] * o2) / den_safe[..., None]
    lse = jnp.where(den > 0, m_safe + jnp.log(den_safe), -jnp.inf)
    return o, lse


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   use_flash=None):
    """Attention over ring-sharded KV. Call under shard_map; q/k/v are the
    local shards (B, T_local, H, D); returns the local output shard.

    ``use_flash``: the per-shard local attention of each ring step runs
    through the Pallas flash kernel (``ops.pallas_attention.
    flash_attention_step`` — absolute-position causal mask, (o, lse)
    merged with the online-softmax combine, exact gradients via the
    kernel's custom_vjp).  ``None`` takes the kernel wherever it lowers
    natively (``pallas_attention.lowerable()``); ``True`` forces it
    (interpreter mode off-TPU — the test/bench pin), ``False`` keeps
    the einsum path (``--dense_attention``)."""
    from sparknet_tpu.ops import pallas_attention

    if use_flash is None:
        use_flash = pallas_attention.lowerable()
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_flash:
        def flash_step(i, o_acc, lse_acc, k_cur, v_cur):
            src = (idx - i) % n  # whose KV shard we hold at ring step i
            o_s, lse_s = pallas_attention.flash_attention_step(
                q, k_cur, v_cur,
                q_offset=idx * tq, k_offset=src * tk, causal=causal,
            )
            return _merge_partials(
                o_acc, lse_acc, o_s.astype(o_acc.dtype), lse_s
            )

        def flash_body(i, carry):
            o_acc, lse_acc, k_cur, v_cur = carry
            o_acc, lse_acc = flash_step(i, o_acc, lse_acc, k_cur, v_cur)
            k_next = lax.ppermute(k_cur, axis_name, perm)
            v_next = lax.ppermute(v_cur, axis_name, perm)
            return o_acc, lse_acc, k_next, v_next

        o_acc = _pcast(
            jnp.zeros((b, h, tq, d), jnp.float32), axis_name, to="varying"
        )
        lse_acc = _pcast(
            jnp.full((b, h, tq), -jnp.inf, jnp.float32),
            axis_name, to="varying",
        )
        o_acc, lse_acc, k_last, v_last = lax.fori_loop(
            0, n - 1, flash_body, (o_acc, lse_acc, k, v)
        )
        o_acc, _ = flash_step(n - 1, o_acc, lse_acc, k_last, v_last)
        return jnp.transpose(o_acc, (0, 2, 1, 3)).astype(q.dtype)

    q_pos = idx * tq + jnp.arange(tq)  # global query positions

    def accumulate(i, acc, m, l, k_cur, v_cur):
        src = (idx - i) % n  # whose KV shard we hold at ring step i
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * tk + jnp.arange(tk)
            mask = k_pos[None, :] <= q_pos[:, None]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(jnp.where(m == -jnp.inf, 0.0, m - m_new))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur
        )
        return acc_new, m_new, l_new

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = accumulate(i, acc, m, l, k_cur, v_cur)
        # rotate KV one hop around the ring (ICI neighbor exchange)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_next, v_next

    # carries must be typed as varying over the ring axis from the start
    # (the loop body makes them so) — pcast marks the replicated zeros
    acc = _pcast(jnp.zeros((b, h, tq, d), q.dtype), axis_name, to="varying")
    m = _pcast(jnp.full((b, h, tq), -jnp.inf, q.dtype), axis_name, to="varying")
    l = _pcast(jnp.zeros((b, h, tq), q.dtype), axis_name, to="varying")
    # n-1 rotate-and-accumulate steps, then the last shard accumulates
    # without the (discarded) final exchange
    acc, m, l, k_last, v_last = lax.fori_loop(
        0, n - 1, body, (acc, m, l, k, v)
    )
    acc, m, l = accumulate(n - 1, acc, m, l, k_last, v_last)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3))


def ring_self_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = False, use_flash=None
):
    """Returns a fn (q, k, v) -> out with q/k/v (B, T, H, D) sharded
    along T over ``axis``; the driver-facing wrapper.  T must divide
    evenly by the axis size (the ring rotates equal shards) — a ragged
    T is rejected up front with the fix spelled out, instead of the
    shard_map partitioner's generic shape error."""
    spec = P(None, axis, None, None)
    n = mesh.shape[axis]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_SHMAP_KW,
    )
    def inner(q, k, v):
        return ring_attention(q, k, v, axis, causal=causal,
                              use_flash=use_flash)

    def fn(q, k, v):
        for name, arr in (("q", q), ("k", k), ("v", v)):
            if arr.ndim != 4:
                raise ValueError(
                    f"ring_self_attention: {name} must be (B, T, H, D), "
                    f"got shape {tuple(arr.shape)}"
                )
            if arr.shape[1] % n:
                raise ValueError(
                    f"ring_self_attention: {name} has T={arr.shape[1]} "
                    f"which does not divide over the {n}-way {axis!r} "
                    "ring — pad the sequence or pick T a multiple of "
                    f"{n}"
                )
        return inner(q, k, v)

    return fn
