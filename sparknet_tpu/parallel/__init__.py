"""Distributed execution: device meshes + the two reference data-parallel
modes, lowered to XLA collectives.

Reference comm planes (SURVEY §2.4) and their TPU-native replacements:

- inter-node Spark broadcast/reduce parameter averaging
  (``CifarApp.scala:95-136``)  ->  ``ParameterAveragingTrainer``:
  tau jitted local steps per worker, then ``pmean(params)`` over the ``dp``
  mesh axis riding ICI/DCN — the driver<->executor round trip and the
  2x|theta|xN floats through the driver disappear entirely.
- in-node P2PSync GPU tree allreduce (``caffe/src/caffe/parallel.cpp``)  ->
  ``AllReduceTrainer``: per-step gradient ``psum`` — one mechanism covers
  both of the reference's topologies.

Multi-host: the same code runs under ``jax.distributed.initialize`` — the
mesh just spans hosts, and XLA routes collectives over ICI within a slice
and DCN across slices.
"""

from sparknet_tpu.parallel import comm  # noqa: F401
from sparknet_tpu.parallel import hierarchy  # noqa: F401
from sparknet_tpu.parallel.hierarchy import HierarchySpec  # noqa: F401
from sparknet_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    local_device_count,
    initialize_distributed,
)
from sparknet_tpu.parallel.trainers import (  # noqa: F401
    AllReduceTrainer,
    ParameterAveragingTrainer,
    export_worker_history,
    first_worker,
    leading_sharding,
    restore_worker_history,
    local_worker_slice,
    replicate,
    replicate_global,
    replicated_sharding,
    shard_leading,
    shard_leading_global,
)
from sparknet_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
from sparknet_tpu.parallel.stale import (  # noqa: F401
    BoundedStalenessTrainer,
    export_worker_replicas,
    restore_worker_replicas,
    stale_window,
)
