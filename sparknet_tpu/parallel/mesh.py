"""Device-mesh construction.

The reference discovers comm topology by probing GPU boards and P2P
reachability (``parallel.cpp:115-197 DevicePair::compute``); on TPU the
topology is the pod slice itself — we just lay axes over
``jax.devices()``: ``dp`` (data/worker axis, the Spark-executor analog),
``mp`` (model/tensor axis), with room for ``sp``/``pp``/``ep`` as models
need them.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


def local_device_count() -> int:
    return jax.local_device_count()


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh from an {axis: size} dict; a -1 size absorbs the
    remaining devices (e.g. {"dp": -1, "mp": 2})."""
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"dp": len(devices)})
    sizes = list(axes.values())
    n_fixed = int(np.prod([s for s in sizes if s > 0])) or 1
    if any(s == -1 for s in sizes):
        if len(devices) % n_fixed:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {axes}"
            )
        sizes = [s if s > 0 else len(devices) // n_fixed for s in sizes]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up (the Spark-cluster analog): each host process
    calls this, then ``jax.devices()`` spans the whole slice and every
    mesh/collective below works unchanged across hosts."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
