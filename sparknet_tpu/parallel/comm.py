"""Communication-efficient parameter averaging: the comm plane.

SCALING_r05 measured the regime SparkNet's tau exists to amortize: on
the 2-proc mesh the averaging collective costs 25.4 ms against 7.4 ms
of local compute per round — the round is bandwidth-bound.  This module
attacks the wire directly, three ways:

1. **Delta quantization.**  Workers average bf16/int8-quantized
   *deltas from the round-start broadcast params* (``theta_end -
   theta_0``), never raw weights: deltas are small and centered, so a
   bf16/int8 grid loses far less than quantizing the weights
   themselves, and the round-start params are already known on every
   worker (the previous round's average) — only the delta has to cross
   the wire.  A per-worker **error-feedback residual** carries the
   quantization error into the next round's delta so the bias never
   accumulates (the EF-SGD contract).

2. **Chunked collectives.**  The param pytree is flattened and split
   into ``chunks`` byte-balanced groups; the collective dispatches per
   chunk, so it can interleave with compute instead of being one
   monolithic barrier, and peak payload memory is bounded by the chunk
   size, not the model size.

3. **Overlap with the next round's compute.**  With ``overlap=True``
   round r's chunk collectives run on a comm thread while the main
   thread runs the first ``overlap_steps`` local steps of round r+1;
   when they land, every worker applies the *correction*
   ``mean(delta) - dequant(own delta)`` to both its params and its
   anchor — the RoundFeed (PR 3) overlap trick, applied to the network
   instead of H2D.  Wall-clock per round approaches
   ``max(collective, local)`` instead of their sum.  The first
   ``overlap_steps`` of a round therefore run one average *stale*
   (delayed averaging — disclosed in PERF.md); the ``compress=none,
   overlap off`` default path never enters this module and stays
   bit-identical to the fused round.

Masking composes: the survivor/sentry mask (``live_mask`` x in-graph
finite audit) applies **per chunk** through ``where()`` — a dead or
poisoned worker's delta contributes exactly zero to every chunk, its
slot receives the survivor consensus ``anchor + mean``, and its
error-feedback residual resets on rejoin (mirroring the momentum-
zeroing rejoin contract of the fused round).  When any worker is
masked in an overlapped round, that round degrades to the barriered
apply — overlap is a healthy-path optimization; the fault path keeps
the strict semantics.

Bytes accounting (``sparknet_collective_bytes_total``): a ring
all-reduce moves ~2x the payload per worker, so the counter charges
``2 x payload_nbytes`` per round, where the payload is the compressed
representation (int8 = 1 B/elem + one f32 max-abs scale per tensor,
bf16 = 2 B/elem, fp32 = 4 B/elem).  On the virtual CPU mesh
collectives are shared-memory copies — the counter models what a
bandwidth-bound interconnect would carry, which is exactly the
quantity compression changes; ``bench.py --mode=scaling`` A/Bs the
wall-clock against a configurable interconnect cost model
(``SPARKNET_COMM_COST_MS_PER_MB``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from sparknet_tpu import obs

tree_map = jax.tree_util.tree_map

# CLI-facing compression modes; "fp32" is additionally accepted by the
# trainer for benchmarks/tests that want the comm-plane *structure*
# (chunked delta averaging) with an uncompressed payload.
CLI_COMPRESS_MODES = ("none", "bf16", "int8")
COMPRESS_MODES = ("none", "fp32", "bf16", "int8")

DEFAULT_CHUNKS = 4
DEFAULT_OVERLAP_STEPS = 1

# The pinned bit-accuracy band (PR-5 audit style): over the reference
# A/B protocol (same seed, same data, cifar10_quick-class model, tens
# of rounds), the final smoothed loss of a bf16/int8 delta-averaged
# run must land within this absolute band of the fp32 collective's.
# Pinned here, proven by ``bench.py --mode=scaling`` (COMM_r11.json:
# loss_band_ok) and by the tier-1 smoke in tests/test_comm.py.
LOSS_BAND = 0.08

_ELEM_NBYTES = {"fp32": 4, "none": 4, "bf16": 2, "int8": 1}
# ring all-reduce moves ~2x(N-1)/N x payload per worker; charge 2x
_RING_FACTOR = 2


def add_cli_args(parser) -> None:
    """``--compress {none,bf16,int8}`` / ``--overlap_avg`` — the comm
    plane's CLI surface, shared by the parameter-averaging apps."""
    parser.add_argument(
        "--compress", choices=CLI_COMPRESS_MODES, default="none",
        help="delta-quantized parameter averaging: workers average "
        "bf16/int8 deltas from the round-start params (error-feedback "
        "residual carried per worker); 'none' keeps the fp32 fused "
        "collective, bit-identical to the classic round",
    )
    parser.add_argument(
        "--overlap_avg", action="store_true",
        help="overlap the averaging collective with the next round's "
        "first local steps (chunked comm on a background thread; the "
        "overlapped steps run one average stale — PERF.md "
        "'Communication-efficient averaging')",
    )


def comm_kwargs_from_args(args) -> Dict[str, object]:
    """Trainer kwargs for the comm plane from parsed CLI args."""
    return {
        "compress": getattr(args, "compress", "none"),
        "overlap_avg": bool(getattr(args, "overlap_avg", False)),
    }


def _cost_ms_per_mb_default() -> float:
    try:
        return float(os.environ.get("SPARKNET_COMM_COST_MS_PER_MB", "0"))
    except ValueError:
        return 0.0


def _per_worker_nbytes(leaf, mode: str) -> int:
    """Modeled payload bytes ONE worker contributes for ``leaf`` (leaf
    is worker-stacked: shape (num_workers, ...)): compressed elements
    plus the per-tensor f32 scale int8 carries."""
    per_worker_elems = int(np.prod(leaf.shape[1:], dtype=np.int64))
    nb = per_worker_elems * _ELEM_NBYTES[mode]
    if mode == "int8":
        nb += 4  # one f32 max-abs scale per tensor per worker
    return nb


def fused_round_payload_bytes(state, average_stats: bool = True) -> int:
    """Modeled per-round collective bytes of the classic fused fp32
    round (params + averaged BN stats, ring factor applied) — what
    ``sparknet_collective_bytes_total{compress="none"}`` charges when
    the comm plane is off.  ``state`` is the worker-stacked TrainState."""
    leaves = jax.tree_util.tree_leaves(state.params)
    if average_stats:
        leaves = leaves + jax.tree_util.tree_leaves(state.stats)
    return _RING_FACTOR * sum(_per_worker_nbytes(x, "fp32") for x in leaves)


class CommPlane:
    """The chunked, delta-quantized, optionally-overlapped averaging
    engine behind ``ParameterAveragingTrainer``.  Built once per
    trainer when ``compress != 'none'`` or ``overlap_avg`` is set."""

    def __init__(
        self,
        solver,
        mesh: Mesh,
        axis: str,
        compress: str = "fp32",
        overlap: bool = False,
        chunks: int = DEFAULT_CHUNKS,
        overlap_steps: int = DEFAULT_OVERLAP_STEPS,
        cost_ms_per_mb: Optional[float] = None,
        average_stats: bool = True,
        mask_nonfinite: bool = True,
        batch_spec=None,
        fused: Optional[bool] = None,
    ):
        if compress not in COMPRESS_MODES:
            raise ValueError(
                f"compress={compress!r}: expected one of {COMPRESS_MODES}"
            )
        if overlap and jax.process_count() > 1:
            # two threads enqueueing programs race the cross-process
            # program order multi-controller jax requires — a deadlock,
            # not a slowdown.  Barriered compression is still fine.
            raise ValueError(
                "overlap_avg needs a single-process runtime (multi-host "
                "program order must be deterministic); use barriered "
                "compression instead"
            )
        self.solver = solver
        self.mesh = mesh
        self.axis = axis
        self.num_workers = mesh.shape[axis]
        # "none" reaching the plane means overlap-only: fp32 payload
        self.compress = "fp32" if compress == "none" else compress
        self.overlap = bool(overlap)
        self.chunks = max(1, int(chunks))
        self.overlap_steps = max(1, int(overlap_steps))
        self.cost_ms_per_mb = (
            _cost_ms_per_mb_default()
            if cost_ms_per_mb is None
            else float(cost_ms_per_mb)
        )
        self.average_stats = bool(average_stats)
        self.audit = bool(getattr(solver, "audit", False))
        self.mask_nonfinite = bool(mask_nonfinite) and self.audit
        # fused Pallas epilogue (ops/pallas_comm.py): delta-encode +
        # quantize + EF-residual in one kernel per chunk, and the
        # apply/correction likewise.  None routes on the shared
        # lowerable() gate (TPU native); True forces the kernels
        # (interpreter mode off-TPU — the test/bench pin); False keeps
        # the unfused jitted closures.  Both paths are bit-identical
        # by construction (same per-element op order).
        from sparknet_tpu.ops.pallas_attention import lowerable

        self.fused = lowerable() if fused is None else bool(fused)

        # ---- per-round carried state (device, worker-stacked) ----
        # anchor: what deltas are measured against — the round-start
        # broadcast params (barriered: re-seeded from the round entry
        # each round; overlap: persisted and corrected in lockstep
        # with the params, consistent across workers up to the
        # error-feedback residual drift)
        self._anchor: Optional[list] = None
        self._resid: Optional[list] = None  # error-feedback residuals
        self._treedefs = None  # (params_treedef, stats_treedef, nparams)
        self._chunk_slices: Optional[List[slice]] = None
        self._modes: Optional[List[str]] = None  # per comm leaf
        self._modes_static: Tuple[str, ...] = ()
        self._payload_bytes_per_round = 0  # modeled, set at _setup
        self._pending = None  # in-flight overlapped round
        self._pending_err = None  # dispatched quant-error readout
        # journaled residuals restored before the first round (consumed
        # by _setup in place of the zero init — the resume path)
        self._resid_restore: Optional[list] = None

        audit = self.audit
        mask_nf = self.mask_nonfinite
        solver_ref = solver

        def local_body(state, batches, rng, live):
            # per-worker local steps (tau or an overlap segment) — the
            # fused round_body minus the averaging epilogue; alive/bad
            # ride out so the chunked collective can mask per chunk.
            st = tree_map(lambda x: x[0], state)
            bt = tree_map(lambda x: x[0], batches)
            widx = jax.lax.axis_index(axis)
            lrng = jax.random.fold_in(rng, widx)
            st, out = solver_ref._step_tau(st, bt, lrng)
            if audit:
                losses, astats = out
            else:
                losses = out
            alive = live[0]
            bad = jnp.zeros(())
            if mask_nf:
                bad_flag = (
                    jnp.sum(astats["nonfinite_grads"])
                    + jnp.sum(astats["nonfinite_params"])
                ) > 0
                ok = jnp.where(bad_flag, 0.0, 1.0)
                alive = alive * ok
                bad = 1.0 - ok
                astats = dict(astats, masked=bad)
            outs = (
                tree_map(lambda x: x[None], st),
                losses[None],
                alive[None],
                bad[None],
            )
            if audit:
                outs = outs + (tree_map(lambda x: x[None], astats),)
            return outs

        out_specs = (P(axis), P(axis), P(axis), P(axis))
        if audit:
            out_specs = out_specs + (P(axis),)
        # NO donation: the round-entry params double as the delta
        # anchor, so their buffers must outlive the local program (the
        # fused default path keeps its donating round; delta averaging
        # inherently carries one extra param copy — PERF.md).
        # batch_spec: the trainer's generalized batch partitioning
        # (sequence parallelism) — same in_spec + check_rep backport
        # rules as the fused round (trainers.py)
        if batch_spec is None:
            batch_in_spec, shmap_kw = P(axis), {}
        else:
            from sparknet_tpu.parallel.ring_attention import (
                seq_shmap_kwargs,
            )

            batch_in_spec, shmap_kw = batch_spec, seq_shmap_kwargs()
        self._local = jax.jit(
            shard_map(
                local_body,
                mesh=mesh,
                in_specs=(P(axis), batch_in_spec, P(), P(axis)),
                out_specs=out_specs,
                **shmap_kw,
            )
        )
        obs.track_jit(self._local)

        def _dequant(q, scale, mode: str):
            if mode == "int8":
                sc = scale.reshape((-1,) + (1,) * (q.ndim - 1))
                return q.astype(jnp.float32) * sc
            if mode == "bf16":
                return q.astype(jnp.float32)
            return q  # fp32

        def encode_fn(leaves, anchors, resids, modes_idx, with_err):
            # delta = theta_end - anchor (+ error-feedback residual);
            # quantize per tensor.  Pure per-worker compute: GSPMD
            # keeps every op local to the worker's shard.  with_err
            # (static) additionally folds the quantization-error
            # readout (max |err|, |delta|^2, |err|^2 for the live SNR
            # gauge) into the SAME program — the residual IS the error,
            # so the reductions fuse with work already being done
            # instead of paying a second full-model dequant pass.
            qs, scales, new_resids = [], [], []
            max_abs = jnp.zeros(())
            err_sq = jnp.zeros(())
            delta_sq = jnp.zeros(())
            for x, a, r, mi in zip(leaves, anchors, resids, modes_idx):
                mode = self._modes_static[mi]
                delta = (x - a) + r
                zero_scale = jnp.zeros((x.shape[0],), jnp.float32)
                if mode == "bf16":
                    q = delta.astype(jnp.bfloat16)
                    scale = zero_scale
                elif mode == "int8":
                    red = tuple(range(1, delta.ndim))
                    amax = (
                        jnp.max(jnp.abs(delta), axis=red)
                        if red else jnp.abs(delta)
                    )
                    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
                    sc = scale.reshape((-1,) + (1,) * (delta.ndim - 1))
                    q = jnp.clip(
                        jnp.rint(delta / sc), -127, 127
                    ).astype(jnp.int8)
                else:  # fp32
                    q = delta
                    scale = zero_scale
                qs.append(q)
                scales.append(scale)
                err = delta - _dequant(q, scale, mode)
                new_resids.append(err)
                if with_err:
                    max_abs = jnp.maximum(max_abs, jnp.max(jnp.abs(err)))
                    err_sq = err_sq + jnp.sum(jnp.square(err))
                    delta_sq = delta_sq + jnp.sum(jnp.square(delta))
            err_out = (max_abs, delta_sq, err_sq) if with_err else None
            return tuple(qs), tuple(scales), tuple(new_resids), err_out

        self._encode = jax.jit(encode_fn, static_argnums=(3, 4))

        def allreduce_fn(qs, scales, alive, modes_idx):
            # masked mean of the dequantized deltas over the dp axis.
            # where(), not multiplication: a dead replica's NaN delta
            # must not leak through 0*NaN into the reduce.  The sum
            # over the sharded leading axis IS the collective.
            denom0 = jnp.sum(jnp.where(alive > 0, 1.0, 0.0))
            denom = jnp.maximum(denom0, 1.0)
            means = []
            for q, scale, mi in zip(qs, scales, modes_idx):
                dq = _dequant(q, scale, self._modes_static[mi])
                am = alive.reshape((-1,) + (1,) * (q.ndim - 1))
                contrib = jnp.where(am > 0, dq, jnp.zeros_like(dq))
                means.append(jnp.sum(contrib, axis=0) / denom)
            return tuple(means), denom0

        self._allreduce = jax.jit(allreduce_fn, static_argnums=(3,))

        def apply_barriered_fn(own, anchors, means, resids, alive, bad,
                               denom0):
            # consensus apply: every worker lands on anchor + mean —
            # the masked slot receives the survivor consensus exactly
            # like the fused round's wmean overwrite, and its error-
            # feedback residual resets on rejoin.  If NO worker is
            # finite, keep own params so the host sentry sees the
            # damage (the fused-round contract).
            have = denom0 > 0
            rejoin = jnp.logical_and(alive <= 0, have)
            new_leaves, new_resids = [], []
            for x, a, m, r in zip(own, anchors, means, resids):
                rm = rejoin.reshape((-1,) + (1,) * (x.ndim - 1))
                new_leaves.append(jnp.where(have, a + m, x))
                new_resids.append(jnp.where(rm, jnp.zeros_like(r), r))
            return tuple(new_leaves), tuple(new_resids)

        self._apply_barriered = jax.jit(apply_barriered_fn)

        def zero_bad_history_fn(history, bad, denom0):
            # an audit-masked worker's momentum still holds the
            # poisoned window — zero it, mirroring the fused round's
            # rejoin contract (bad == 0 selects the original leaves
            # exactly, so healthy rounds are untouched)
            rejoined = jnp.logical_and(bad > 0, denom0 > 0)

            def zero(h):
                rm = rejoined.reshape((-1,) + (1,) * (h.ndim - 1))
                return jnp.where(rm, jnp.zeros_like(h), h)

            return tree_map(zero, history)

        self._zero_bad_history = jax.jit(zero_bad_history_fn)

        def apply_correction_fn(own, anchors, qs, scales, means,
                                modes_idx):
            # overlapped healthy-path apply: every worker already
            # advanced overlap_steps past the encode point, so add the
            # consensus-minus-own-contribution correction to params AND
            # anchor — local progress since the encode is preserved,
            # and anchors stay consistent up to residual drift.
            new_leaves, new_anchors = [], []
            for x, a, q, scale, m, mi in zip(
                own, anchors, qs, scales, means, modes_idx
            ):
                corr = m - _dequant(q, scale, self._modes_static[mi])
                new_leaves.append(x + corr)
                new_anchors.append(a + corr)
            return tuple(new_leaves), tuple(new_anchors)

        self._apply_correction = jax.jit(
            apply_correction_fn, static_argnums=(5,)
        )

    # ------------------------------------------------------------------
    # comm-leaf plumbing: params leaves + (optionally) stats leaves form
    # one flat list; stats always ride fp32 (tiny next to params)
    def _setup(self, state) -> None:
        params_leaves, params_def = jax.tree_util.tree_flatten(state.params)
        stats_leaves, stats_def = jax.tree_util.tree_flatten(state.stats)
        if not self.average_stats:
            stats_leaves = []
        self._treedefs = (params_def, stats_def, len(params_leaves))
        modes = (
            [self.compress] * len(params_leaves)
            + ["fp32"] * len(stats_leaves)
        )
        self._modes = modes
        self._modes_static = tuple(modes)
        leaves = params_leaves + stats_leaves
        # byte-balanced contiguous chunking of the comm leaves
        sizes = [_per_worker_nbytes(x, m) for x, m in zip(leaves, modes)]
        total = sum(sizes)
        k = min(self.chunks, len(leaves))
        target = total / k if k else total
        slices, start, acc = [], 0, 0
        for i, s in enumerate(sizes):
            acc += s
            if acc >= target and len(slices) < k - 1:
                slices.append(slice(start, i + 1))
                start, acc = i + 1, 0
        slices.append(slice(start, len(leaves)))
        self._chunk_slices = [s for s in slices if s.stop > s.start]
        self._payload_bytes_per_round = _RING_FACTOR * total
        tm = obs.training_metrics()
        if tm is not None:
            tm.kernel_path.labels("epilogue").set(
                1.0 if self.fused else 0.0
            )
        restore, self._resid_restore = self._resid_restore, None
        if restore is not None:
            # journaled EF residuals restored before the first round
            if len(restore) != len(leaves) or any(
                tuple(r.shape) != tuple(x.shape)
                for r, x in zip(restore, leaves)
            ):
                raise ValueError(
                    "restored jobstate residuals do not match this "
                    "plane's comm leaves (model/worker-count drift?)"
                )
            self._resid = [jnp.asarray(r) for r in restore]
        else:
            self._resid = [jnp.zeros_like(x) for x in leaves]

    # ------------------------------------------------------------------
    # epilogue routing: the same three program contracts as the jitted
    # unfused closures, but one Pallas kernel per comm chunk on the
    # fused path (ops/pallas_comm.py) — delta + quantize + EF residual
    # (and dequant + apply + anchor) each a single pass over the chunk
    # instead of an op chain round-tripping full-model intermediates
    # through HBM.  Bit-identical by construction; routing is decided
    # once at __init__ (self.fused).
    def _count_fused(self, stage: str) -> None:
        tm = obs.training_metrics()
        if tm is not None:
            tm.kernel_fused_chunks.labels(stage).inc(
                len(self._chunk_slices)
            )

    def _encode_all(self, leaves, with_err):
        if not self.fused:
            idx = tuple(range(len(leaves)))
            return self._encode(
                tuple(leaves), tuple(self._anchor), tuple(self._resid),
                idx, with_err,
            )
        from sparknet_tpu.ops import pallas_comm

        qs: list = []
        scales: list = []
        new_resids: list = []
        errs: list = []
        for sl in self._chunk_slices:
            q, sc, nr, err = pallas_comm.fused_encode(
                tuple(leaves[sl]), tuple(self._anchor[sl]),
                tuple(self._resid[sl]), self._modes_static[sl],
                with_err, None,
            )
            qs.extend(q)
            scales.extend(sc)
            new_resids.extend(nr)
            if with_err:
                errs.append(err)
        self._count_fused("encode")
        err_out = None
        if with_err:
            allv = jnp.stack(errs)  # (chunks, workers, 3)
            err_out = (
                jnp.max(allv[..., 0]),
                jnp.sum(allv[..., 1]),
                jnp.sum(allv[..., 2]),
            )
        return tuple(qs), tuple(scales), tuple(new_resids), err_out

    def _apply_barriered_all(self, leaves, means, alive, bad, denom0):
        if not self.fused:
            return self._apply_barriered(
                tuple(leaves), tuple(self._anchor), tuple(means),
                tuple(self._resid), alive, bad, denom0,
            )
        from sparknet_tpu.ops import pallas_comm

        new_leaves: list = []
        new_resids: list = []
        for sl in self._chunk_slices:
            nl, nr = pallas_comm.fused_apply_barriered(
                tuple(leaves[sl]), tuple(self._anchor[sl]),
                tuple(means[sl]), tuple(self._resid[sl]),
                alive, denom0, None,
            )
            new_leaves.extend(nl)
            new_resids.extend(nr)
        self._count_fused("apply")
        return tuple(new_leaves), tuple(new_resids)

    def _apply_correction_all(self, leaves, q, scales, means):
        if not self.fused:
            idx = tuple(range(len(leaves)))
            return self._apply_correction(
                tuple(leaves), tuple(self._anchor), tuple(q),
                tuple(scales), tuple(means), idx,
            )
        from sparknet_tpu.ops import pallas_comm

        new_leaves: list = []
        new_anchors: list = []
        for sl in self._chunk_slices:
            nl, na = pallas_comm.fused_apply_correction(
                tuple(leaves[sl]), tuple(self._anchor[sl]),
                tuple(q[sl]), tuple(scales[sl]), tuple(means[sl]),
                self._modes_static[sl], None,
            )
            new_leaves.extend(nl)
            new_anchors.extend(na)
        self._count_fused("apply")
        return tuple(new_leaves), tuple(new_anchors)

    def _comm_leaves(self, state) -> list:
        leaves = list(jax.tree_util.tree_leaves(state.params))
        if self.average_stats:
            leaves += list(jax.tree_util.tree_leaves(state.stats))
        return leaves

    def _rebuild(self, state, leaves, history=None):
        params_def, stats_def, nparams = self._treedefs
        params = jax.tree_util.tree_unflatten(params_def, leaves[:nparams])
        stats = (
            jax.tree_util.tree_unflatten(stats_def, leaves[nparams:])
            if self.average_stats
            else state.stats
        )
        return type(state)(
            params, stats,
            state.history if history is None else history,
            state.iter,
        )

    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Drop carried comm state — the rollback/rejoin/broadcast
        entry: a restored state has no valid anchor, residual, or
        in-flight collective (a stale correction applied onto restored
        params would corrupt them)."""
        p = self._pending
        if p is not None and p["thread"] is not None:
            try:
                p["thread"].join()
            except Exception:  # pragma: no cover - defensive
                pass
        self._pending = None
        self._pending_err = None
        self._anchor = None
        self._resid_restore = None  # a stale pre-broadcast restore dies too
        if self._resid is not None:
            self._resid = [jnp.zeros_like(r) for r in self._resid]

    def export_state(self) -> Optional[dict]:
        """Host copy of the carried error-feedback residuals — the
        comm-plane half of a full-job-state snapshot (``io/checkpoint``
        ``extra_state``).  A resumed run that does NOT restore this
        silently resets the EF bias correction and diverges from the
        uninterrupted trajectory (measured: ``bench.py --mode=recover``
        ``--no_journal`` leg).  Call at a round boundary with no
        in-flight overlapped collective (``finalize()`` first)."""
        if self._resid is None:
            return None
        if self._pending is not None:
            raise RuntimeError(
                "export_state with an overlapped collective in flight — "
                "finalize() the round first"
            )
        return {
            "compress": self.compress,
            "resid": {
                str(i): np.asarray(jax.device_get(r))
                for i, r in enumerate(self._resid)
            },
        }

    def restore_state(self, exported: dict) -> None:
        """Load residuals exported by ``export_state``.  Call AFTER the
        restore path's ``reset()`` (``broadcast_state`` triggers it) —
        the restore order is: place the snapshot params, then put the
        journaled residuals back.  A compress-mode or shape mismatch
        fails loudly: silently training on wrong residuals is exactly
        the bug this state exists to prevent."""
        if exported.get("compress") != self.compress:
            raise ValueError(
                "jobstate residuals were recorded under compress=%r, "
                "this plane runs %r"
                % (exported.get("compress"), self.compress)
            )
        resid = exported["resid"]
        leaves = [resid[str(i)] for i in range(len(resid))]
        if self._resid is not None:
            if len(leaves) != len(self._resid):
                raise ValueError(
                    f"jobstate has {len(leaves)} residual leaves, plane "
                    f"carries {len(self._resid)}"
                )
            for got, want in zip(leaves, self._resid):
                if tuple(got.shape) != tuple(want.shape):
                    raise ValueError(
                        f"residual shape {got.shape} != {want.shape}"
                    )
            self._resid = [jnp.asarray(l) for l in leaves]
        else:
            # first round hasn't run: _setup consumes these instead of
            # zeros (shape-checked there against the real comm leaves)
            self._resid_restore = [np.asarray(l) for l in leaves]

    def _join_pending(self) -> dict:
        """Wait for the in-flight chunk collectives; re-raise comm-
        thread errors on the caller."""
        p = self._pending
        # sparknet: join-ok(bounded by the in-flight chunk collectives: _pace_chunks always terminates, storing errors instead of raising)
        p["thread"].join()
        holder = p["holder"]
        if holder.get("error") is not None:
            self._pending = None
            raise holder["error"]
        return holder

    @property
    def payload_bytes_per_round(self) -> int:
        return self._payload_bytes_per_round

    @property
    def has_pending(self) -> bool:
        return self._pending is not None

    # ------------------------------------------------------------------
    def _sleep_cost(self, chunk_bytes: int) -> None:
        if self.cost_ms_per_mb > 0:
            time.sleep(self.cost_ms_per_mb * (chunk_bytes / (1 << 20)) / 1e3)

    def _dispatch_chunks(self, q, scales, alive):
        """Dispatch every chunk's collective from the CALLING thread —
        the device queue executes programs in dispatch order, so the
        chunks land right behind this round's encode and run as soon as
        the deltas exist, BEFORE the next round's local window the
        caller dispatches afterwards.  (Dispatching from the comm
        thread instead would race that window into the queue ahead of
        the chunks and serialize the 'overlapped' collective behind a
        full local window — measured, not hypothetical.)"""
        outs = []
        denom0 = None
        for sl in self._chunk_slices:
            idx = tuple(range(sl.start, sl.stop))
            nbytes = _RING_FACTOR * sum(
                _per_worker_nbytes(x, self._modes[i])
                for i, x in zip(idx, q[sl])
            )
            m, d0 = self._allreduce(
                tuple(q[sl]), tuple(scales[sl]), alive, idx
            )
            outs.append((sl, m, nbytes))
            denom0 = d0
        return outs, denom0

    def _pace_chunks(self, q, outs, denom0, holder) -> None:
        """Pace the modeled wire over the already-dispatched chunks
        (comm thread in overlap mode, inline in barriered mode).  Each
        chunk's span covers the optional interconnect cost-model sleep
        plus the block on its mean — the span times the wire, not the
        dispatch."""
        try:
            # the wire cannot carry a delta before it exists: wait for
            # the encode (and the local window it depends on) before
            # pacing chunks — in overlap mode this is the comm thread
            # parking until round r's window is done, in barriered mode
            # it keeps the round an honest local-then-collective sum
            # sparknet: sync-ok(the wire wait: comm thread parks until the encode lands — overlapped in overlap mode, the deliberate barrier otherwise)
            jax.block_until_ready(q)
            means: list = [None] * len(q)
            for sl, m, nbytes in outs:
                with obs.span("allreduce", chunk=sl.start, nbytes=nbytes):
                    self._sleep_cost(nbytes)
                    # sparknet: sync-ok(chunk landing: the span times the wire, not the dispatch — comm-thread side of the overlap)
                    jax.block_until_ready(m)
                means[sl] = list(m)
            holder["means"] = means
            holder["denom0"] = denom0
        except BaseException as e:  # re-raised at the next join
            holder["error"] = e

    def _apply_pending_correction(self, state, stage: str):
        """Land the joined pending collective as the overlap
        correction on ``state`` (and the anchor)."""
        p = self._pending
        holder = p["holder"]
        with obs.span("dequantize", stage=stage):
            leaves = self._comm_leaves(state)
            new_leaves, new_anchor = self._apply_correction_all(
                leaves, p["q"], p["scales"], holder["means"]
            )
            state = self._rebuild(state, list(new_leaves))
            self._anchor = list(new_anchor)
        self._pending = None
        return state

    def _local_call(self, state, batches, rng, live):
        with obs.span("execute"):
            return self._local(state, batches, rng, live)

    # ------------------------------------------------------------------
    def flush_quant_error(self) -> Optional[dict]:
        """Land the previous round's dispatched quantization-error
        readout into the gauges (values are ready by now — no stall).
        Returns the readout dict, or None when nothing is pending."""
        pending = self._pending_err
        if pending is None:
            return None
        self._pending_err = None
        from sparknet_tpu import obs as _obs

        max_abs, delta_sq, err_sq = (
            # sparknet: sync-ok(3-scalar readout dispatched with LAST round's encode — ready by now, fetched without stalling the dispatch path)
            float(v) for v in jax.device_get(pending)
        )
        if err_sq > 0:
            # sparknet: sync-ok(host floats fetched above — pure host math)
            snr_db = 10.0 * float(np.log10(max(delta_sq, 1e-45) / err_sq))
        else:
            snr_db = 300.0  # error underflowed to exactly 0
        tm = _obs.training_metrics()
        if tm is not None:
            tm.quant_error.labels(self.compress).set(max_abs)
            tm.quant_snr_db.labels(self.compress).set(round(snr_db, 3))
        return {
            "compress": self.compress,
            "max_abs_err": max_abs,
            "snr_db": round(snr_db, 3),
        }

    def round(self, state, batches, rng, live, live_host):
        """One comm-plane averaging round.  ``live`` is the placed
        (num_workers,) mask, ``live_host`` its host value.  Returns the
        fused round's contract: ``(state, losses[, astats])``."""
        if self._treedefs is None:
            self._setup(state)
        self.flush_quant_error()  # last round's readout (ready: no sync)

        tau = jax.tree_util.tree_leaves(batches)[0].shape[1]
        astats = None

        if self._pending is not None:
            # overlapped steady state: the first overlap_steps of THIS
            # round run while round r-1's collective is in flight, then
            # the correction lands and the window finishes
            s = min(self.overlap_steps, tau)
            seg1 = tree_map(lambda x: x[:, :s], batches)
            out = self._local_call(state, seg1, rng, live)
            state, losses, alive, bad = out[:4]
            if self.audit:
                astats = out[4]
            self._join_pending()
            state = self._apply_pending_correction(state, "correction")
            if tau - s > 0:
                seg2 = tree_map(lambda x: x[:, s:], batches)
                out2 = self._local_call(state, seg2, rng, live)
                state = out2[0]
                losses = jnp.concatenate([losses, out2[1]], axis=1)
                alive = alive * out2[2]
                bad = jnp.maximum(bad, out2[3])
                if self.audit:
                    # per-iter stat leaves ((w, s, ...)) concatenate
                    # along the window; per-window flags (masked,
                    # (w,)) combine as max
                    astats = tree_map(
                        lambda a, b: (
                            jnp.concatenate([a, b], axis=1)
                            if a.ndim >= 2 else jnp.maximum(a, b)
                        ),
                        astats, out2[4],
                    )
        else:
            # first round, or barriered steady state: the round-entry
            # params ARE the broadcast anchor
            self._anchor = self._comm_leaves(state)
            out = self._local_call(state, batches, rng, live)
            state, losses, alive, bad = out[:4]
            if self.audit:
                astats = out[4]

        # ---- encode this round's deltas ----
        leaves = self._comm_leaves(state)
        # per-round quantization-error telemetry (delta max-abs-err +
        # SNR, labeled by compress mode like the payload family): the
        # PR-6 bit-accuracy band, observable in LIVE runs.  The
        # 3-scalar readout is folded into the encode program itself
        # (static with_err leg — the residual IS the error, so the
        # reductions fuse with work already being done) and fetched one
        # round later by flush_quant_error, so the gauge never adds a
        # sync or a second model pass to the dispatch path.
        # compress="none" (the overlap-only plane) quantizes nothing —
        # skip the readout entirely; fp32 keeps its deliberate
        # exactly-zero/300 dB export (pinned in test_comm) as the
        # bit-accuracy control.
        tm = obs.training_metrics()
        with_err = tm is not None and self.compress != "none"
        with obs.span("quantize", compress=self.compress):
            q, scales, new_resid, err = self._encode_all(leaves, with_err)
        q, scales = list(q), list(scales)
        self._resid = list(new_resid)

        if tm is not None:
            tm.collective_bytes.labels(self.compress).inc(
                self._payload_bytes_per_round
            )
            if with_err:
                self._pending_err = err

        # Overlap only on the all-alive path: a masked/dead worker
        # forces the strict barriered apply (consensus overwrite,
        # residual reset, momentum zeroing).  The decision is host-
        # side: live_host is host data already; the in-graph audit
        # verdict costs one tiny (num_workers,) read — the same
        # per-round D2H budget the host sentry already pays.
        # sparknet: sync-ok(live_host is the host-side mask, never a device array)
        all_alive = bool(np.all(np.asarray(live_host) > 0))
        if all_alive and self.mask_nonfinite:
            # sparknet: sync-ok(one tiny (num_workers,) audit-verdict read — the same per-round D2H budget the host sentry pays; documented above)
            all_alive = not bool(np.any(np.asarray(jax.device_get(bad)) > 0))

        outs, denom0 = self._dispatch_chunks(q, scales, alive)
        if self.overlap and all_alive:
            holder: dict = {}
            th = threading.Thread(
                target=self._pace_chunks,
                args=(q, outs, denom0, holder),
                name="comm-averaging",
                daemon=True,
            )
            self._pending = {
                "q": q, "scales": scales, "holder": holder, "thread": th,
            }
            # from here deltas are measured against the encode point
            self._anchor = leaves
            th.start()
        else:
            holder = {}
            self._pace_chunks(q, outs, denom0, holder)
            if holder.get("error") is not None:
                raise holder["error"]
            with obs.span("dequantize", stage="barriered"):
                new_leaves, new_resid2 = self._apply_barriered_all(
                    leaves, holder["means"], alive, bad, holder["denom0"]
                )
                self._resid = list(new_resid2)
                history = state.history
                if self.mask_nonfinite:
                    history = self._zero_bad_history(
                        history, bad, holder["denom0"]
                    )
                state = self._rebuild(state, list(new_leaves), history)
            self._anchor = None  # re-seeded from the next round's entry

        if self.audit:
            return state, losses, astats
        return state, losses

    # ------------------------------------------------------------------
    def finalize(self, state):
        """Land the in-flight overlapped collective into ``state`` —
        call before an eval or at the end of training so the last
        round's average is applied.  No-op when nothing is pending."""
        self.flush_quant_error()  # the last round's gauges land too
        if self._pending is None:
            return state
        self._join_pending()
        return self._apply_pending_correction(state, "finalize")
