"""The two reference data-parallel training modes on a device mesh.

1. ``ParameterAveragingTrainer`` — SparkNet's algorithm (reference driver
   loop ``CifarApp.scala:95-136``): every worker keeps its own full replica
   of params *and solver history*, runs tau local SGD iterations with no
   communication, then parameters (only) are averaged across workers:
   ``psum(theta)/N``.  History is never averaged — the reference's
   ``getWeights`` reads param blobs only (``Net.scala:151-171``).  The whole
   round is ONE jitted program: the Spark driver hop, java serialization,
   and float-by-float JNA copies all vanish into an XLA collective.

2. ``AllReduceTrainer`` — the engine's in-node P2PSync mode
   (``parallel.cpp:287-380``): synchronous per-iteration gradient summing.
   Expressed as pjit sharding: params replicated, batch sharded over ``dp``;
   XLA inserts the gradient all-reduce automatically.  Optional tensor
   parallelism: a sharding policy places large param blobs over the ``mp``
   axis and GSPMD propagates.

Both run unchanged on the 8-device CPU simulation, a real TPU slice, or a
multi-host pod (see ``mesh.initialize_distributed``).
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from sparknet_tpu import obs
from sparknet_tpu.obs import profile as obs_profile
from sparknet_tpu.parallel.hierarchy import HierarchySpec
from sparknet_tpu.solver import Solver, TrainState
from sparknet_tpu.utils.rngs import default_train_key

tree_map = jax.tree_util.tree_map


# Sharding cache, keyed on MESH IDENTITY: the per-mesh dict lives on
# the mesh object itself, so its lifetime is exactly the mesh's — a
# process that recreates meshes (every test file does) can never grow a
# module-level cache monotonically, and an equal mesh (jax interns
# Mesh, so equal specs ARE the same object) reuses the same shardings.
# A module-level lru keyed on Mesh would instead pin every mesh it ever
# saw (NamedSharding holds the mesh strongly, so even a weak-key dict
# can't evict).  Fallback for a Mesh that rejects attributes: a small
# bounded dict, cleared on overflow like ``_place_live``'s.
_SHARDING_ATTR = "_sparknet_shardings"
_sharding_fallback: Dict = {}


def _mesh_sharding_cache(mesh: Mesh) -> Dict:
    cache = getattr(mesh, _SHARDING_ATTR, None)
    if cache is None:
        cache = {}
        try:
            setattr(mesh, _SHARDING_ATTR, cache)
        except (AttributeError, TypeError):  # pragma: no cover
            if len(_sharding_fallback) >= 64:
                _sharding_fallback.clear()
            cache = _sharding_fallback.setdefault(mesh, {})
    return cache


def leading_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """The leading-axis placement ``NamedSharding(mesh, P(axis))``,
    built ONCE per (mesh, axis) — the training loops place a batch with
    this every round, and rebuilding the sharding object per round is
    avoidable host work on the hot path.  Cached ON the mesh object
    (mesh identity), so repeated trainer/mesh construction cannot grow
    a global cache."""
    cache = _mesh_sharding_cache(mesh)
    key = ("lead", axis)
    s = cache.get(key)
    if s is None:
        s = cache.setdefault(key, NamedSharding(mesh, P(axis)))
    return s


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement ``NamedSharding(mesh, P())``, cached
    like ``leading_sharding``."""
    cache = _mesh_sharding_cache(mesh)
    s = cache.get("repl")
    if s is None:
        s = cache.setdefault("repl", NamedSharding(mesh, P()))
    return s


def replicate(tree, mesh: Mesh):
    """Place a pytree fully replicated over the mesh (no new axes; the
    inverse is a no-op — just use the tree)."""
    return jax.device_put(tree, replicated_sharding(mesh))


def export_worker_history(host_state) -> Dict:
    """Per-worker momentum stacks as a ``.jobstate.npz`` fragment
    (the ``workers`` key of the journaled-state inventory): the
    consensus snapshot keeps worker 0's history only — broadcast
    would replicate it over every worker — so the true stacks ride
    beside it.  One implementation shared by every journaled driver
    (``runtime/recover.py``, ``apps/lm_app.py``)."""
    return {
        "history": {
            str(i): np.asarray(l)
            for i, l in enumerate(
                jax.tree_util.tree_leaves(host_state.history)
            )
        }
    }


def restore_worker_history(state, workers_fragment, mesh: Mesh,
                           axis: str = "dp"):
    """Put journaled per-worker momentum stacks back onto a
    broadcast-restored state (the inverse of
    ``export_worker_history``); shape mismatches fail loudly — the
    jobstate belongs to a different trainer geometry."""
    hd = workers_fragment["history"]
    cur, treedef = jax.tree_util.tree_flatten(state.history)
    leaves = [np.asarray(hd[str(i)]) for i in range(len(cur))]
    if any(
        tuple(l.shape) != tuple(c.shape) for l, c in zip(leaves, cur)
    ):
        raise ValueError(
            "jobstate worker history does not match this trainer's "
            "shapes"
        )
    return state._replace(
        history=shard_leading(
            jax.tree_util.tree_unflatten(treedef, leaves), mesh, axis
        )
    )


def first_worker(stacked_tree):
    """Slice worker 0 out of a *worker-stacked* tree (leaves carry a leading
    ``num_workers`` axis — the ParameterAveragingTrainer state layout).  Not
    for ``replicate()`` output, which has no stacking axis."""
    return tree_map(lambda x: x[0], stacked_tree)


def shard_leading(tree, mesh: Mesh, axis: str = "dp"):
    """Shard every leaf's leading dimension over ``axis`` (the per-worker
    stacking used by the averaging trainer and for per-worker batches)."""
    return jax.device_put(tree, leading_sharding(mesh, axis))


def local_worker_slice(mesh: Mesh, axis: str = "dp") -> slice:
    """This process's contiguous block of the ``axis`` dimension (worker
    indices whose mesh position lands on local devices).  The host-side
    data-sharding rule of a multi-host run: each host loads/feeds only
    its own workers — the Spark-partitions-per-executor analog."""
    devs = np.moveaxis(mesh.devices, mesh.axis_names.index(axis), 0)
    pos = [
        i
        for i in range(mesh.shape[axis])
        if all(
            d.process_index == jax.process_index()
            for d in np.atleast_1d(devs[i]).flat
        )
    ]
    if not pos:
        raise ValueError("this process owns no workers on the mesh")
    if pos != list(range(pos[0], pos[-1] + 1)):
        raise ValueError(f"non-contiguous local worker block {pos}")
    return slice(pos[0], pos[-1] + 1)


def shard_leading_global(tree_local, mesh: Mesh, axis: str = "dp"):
    """Multi-host ``shard_leading``: every process passes only its LOCAL
    workers' leading block (see ``local_worker_slice``); the result is one
    global array spanning all hosts.  Single-process it expects the full
    leading dim and degrades to ``shard_leading``."""
    if jax.process_count() == 1:
        return shard_leading(tree_local, mesh, axis)
    sharding = leading_sharding(mesh, axis)
    n = mesh.shape[axis]

    def mk(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, x, (n,) + tuple(x.shape[1:])
        )

    return tree_map(mk, tree_local)


def replicate_global(tree, mesh: Mesh):
    """Fully-replicated placement that also works multi-host (every process
    passes the same host value — the initial weight broadcast semantics)."""
    sharding = replicated_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def mk(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    return tree_map(mk, tree)


class ParameterAveragingTrainer:
    """tau-step local SGD + parameter averaging over the ``dp`` axis."""

    # placed-live-mask LRU bound (masks are small; the bound exists so
    # churning membership views can never grow the cache monotonically)
    _LIVE_CACHE_MAX = 64

    def __init__(
        self,
        solver: Solver,
        mesh: Mesh,
        axis: str = "dp",
        average_stats: bool = True,
        average_params: bool = True,
        mask_nonfinite: bool = True,
        compress: str = "none",
        overlap_avg: bool = False,
        comm_chunks: Optional[int] = None,
        overlap_steps: Optional[int] = None,
        comm_cost_ms_per_mb: Optional[float] = None,
        comm_fused: Optional[bool] = None,
        hierarchy: Optional[HierarchySpec] = None,
        batch_spec=None,
    ):
        """``average_params=False`` skips the cross-worker pmean — a
        DIAGNOSTIC mode (workers then train fully independently): the
        scaling bench A/Bs it against the real round to attribute round
        time to compute vs collective.

        ``compress``/``overlap_avg`` engage the comm plane
        (``parallel/comm.py``): delta-quantized (bf16/int8) chunked
        collectives, optionally overlapped with the next round's first
        local steps.  The default (``compress='none'``,
        ``overlap_avg=False``) keeps the classic fused round,
        bit-identical to the pre-comm-plane trainer.

        With the solver's numerics audit on (``solver.audit`` — set it
        BEFORE constructing the trainer; the audit arity is baked into
        the shard_map output spec), ``round`` returns a third value:
        the per-worker audit stats tree.  ``mask_nonfinite`` then also
        arms the IN-GRAPH sentry mask: a worker whose local window
        produced any non-finite grad/param is excluded from this
        round's average before the ``psum`` — the poison never reaches
        the survivors, and the masked slot is overwritten with the
        survivor mean (it rejoins healthy next round).  If NO worker is
        finite the round keeps each worker's own (poisoned) params so
        the host sentry sees the damage and escalates, instead of a
        silent all-zero average.

        ``hierarchy`` (``parallel/hierarchy.py``) declares the two-tier
        averaging schedule: rounds where ``(r + 1) %
        cross_slice_every != 0`` average WITHIN each slice only (pass
        ``round_index`` to ``round()`` so resumed runs keep the
        absolute schedule); every K-th round runs the ordinary GLOBAL
        round — the same jitted program as today, so compression and
        overlap compose unchanged on the cross-slice tier.  A flat
        spec (one slice, or K == 1) yields the single-tier schedule
        and is bit-identical to ``hierarchy=None`` by construction.

        ``batch_spec`` generalizes the round's batch partitioning
        beyond the worker-major CNN layout: a ``PartitionSpec`` (or a
        pytree of them matching the batch dict) used as the shard_map
        in_spec for ``batches`` — e.g. the transformer LM passes
        ``{"tokens": P("dp", None, None, "sp"), ...}`` so each round's
        (num_workers, tau, B, T) token arrays shard their sequence
        dim over the ``sp`` ring while the leading dim keeps the dp
        worker split.  ``None`` keeps today's ``P(axis)`` (every CNN
        app, bit-identical).  A spec naming axes beyond ``axis``
        implies ring collectives inside the body, which needs the
        check_rep backport on pre-varying jax
        (``ring_attention.seq_shmap_kwargs``)."""
        self.solver = solver
        self.mesh = mesh
        self.axis = axis
        self.num_workers = mesh.shape[axis]
        self.audit = bool(getattr(solver, "audit", False))
        self.mask_nonfinite = bool(mask_nonfinite) and self.audit
        self.average_params = bool(average_params)
        self.average_stats = bool(average_stats)
        # batch pytree partitioning: P(axis) (worker-major, the CNN
        # apps) unless the caller declares per-leaf specs (sequence
        # parallelism).  Extra axes in the spec mean ring collectives
        # run inside the round body, which trips pre-varying jax's
        # replication checker — same backport as ring_attention.
        self.batch_spec = batch_spec
        batch_in_spec = P(axis) if batch_spec is None else batch_spec
        if batch_spec is None:
            shmap_kw = {}
        else:
            from sparknet_tpu.parallel.ring_attention import (
                seq_shmap_kwargs,
            )

            shmap_kw = seq_shmap_kwargs()

        # the comm plane (parallel/comm.py): engaged for compressed
        # and/or overlapped averaging; None on the default path, which
        # keeps the fused round below bit-identical to the classic
        # trainer
        from sparknet_tpu.parallel import comm as _comm

        if compress not in _comm.COMPRESS_MODES:
            raise ValueError(
                f"compress={compress!r}: expected one of "
                f"{_comm.COMPRESS_MODES}"
            )
        self.compress = compress
        self._comm = None
        if (compress != "none" or overlap_avg) and average_params:
            self._comm = _comm.CommPlane(
                solver, mesh, axis,
                compress=compress,
                overlap=overlap_avg,
                chunks=(
                    _comm.DEFAULT_CHUNKS
                    if comm_chunks is None else comm_chunks
                ),
                overlap_steps=(
                    _comm.DEFAULT_OVERLAP_STEPS
                    if overlap_steps is None else overlap_steps
                ),
                cost_ms_per_mb=comm_cost_ms_per_mb,
                average_stats=average_stats,
                mask_nonfinite=mask_nonfinite,
                batch_spec=batch_spec,
                # fused Pallas epilogue routing (None = the shared
                # lowerable() gate; True forces the kernels, the
                # KERNELS_r21 A/B lever)
                fused=comm_fused,
            )
        self._fused_payload_bytes: Optional[int] = None

        # two-tier hierarchical averaging (parallel/hierarchy.py): the
        # spec's slice grouping + K.  Flat specs never build the slice
        # program — every round is the global round (bit-identity).
        if hierarchy is not None and hierarchy.num_workers != self.num_workers:
            raise ValueError(
                f"hierarchy spec covers {hierarchy.num_workers} workers, "
                f"mesh has {self.num_workers}"
            )
        self.hierarchy = hierarchy
        self._two_tier = hierarchy is not None and not hierarchy.is_flat()
        # schedule fallback when round() isn't handed an absolute
        # round_index: counts this trainer's own round() calls
        self._auto_round = 0

        audit = self.audit
        mask_nf = self.mask_nonfinite

        def round_body(state, batches, rng, live):
            # shard_map hands each worker a leading axis of size 1
            st = tree_map(lambda x: x[0], state)
            bt = tree_map(lambda x: x[0], batches)
            widx = jax.lax.axis_index(axis)
            lrng = jax.random.fold_in(rng, widx)
            st, out = solver._step_tau(st, bt, lrng)
            if audit:
                losses, astats = out
            else:
                losses = out
            # averaging round: params (and BN stats) only, never history.
            # Survivor-aware: the average is a masked weighted mean over
            # LIVE workers — psum(where(live, theta, 0))/psum(live) — so
            # a dead dp worker's replica is excluded instead of
            # poisoning every survivor, and the dead slot itself is
            # overwritten with the survivor mean (it rejoins healthy).
            # where(), not multiplication: a dead replica holding
            # NaN/Inf garbage (diverged or interrupted step) must not
            # leak through 0*NaN=NaN into the psum.  With live == ones
            # this is exactly psum(theta)/N, the original pmean.
            alive = live[0]
            if mask_nf:
                # in-graph sentry mask: this worker's window produced a
                # non-finite grad or param -> drop it from the average
                bad = (
                    jnp.sum(astats["nonfinite_grads"])
                    + jnp.sum(astats["nonfinite_params"])
                ) > 0
                ok = jnp.where(bad, 0.0, 1.0)
                alive = alive * ok
                astats = dict(astats, masked=1.0 - ok)
            denom0 = jax.lax.psum(alive, axis)
            denom = jnp.maximum(denom0, 1.0)

            def wmean(w):
                contrib = jnp.where(alive > 0, w, jnp.zeros_like(w))
                m = jax.lax.psum(contrib, axis) / denom.astype(w.dtype)
                if mask_nf:
                    # no finite worker at all: keep own params (the
                    # host sentry escalates) instead of an all-zero
                    # "average" that would read as healthy
                    return jnp.where(denom0 > 0, m, w)
                return m

            avg_params = (
                tree_map(wmean, st.params) if average_params else st.params
            )
            avg_stats = (
                tree_map(wmean, st.stats)
                if average_stats and average_params
                else st.stats
            )
            history = st.history
            if mask_nf and average_params:
                # the masked slot's params are replaced by the survivor
                # mean, but its momentum history still holds the
                # poisoned window — zero it too, or momentum replays the
                # non-finite update next round and the worker re-
                # diverges (staying masked forever off one bad batch).
                # bad=False selects the original leaves exactly, so
                # healthy rounds keep the bit-identity contract.
                rejoined = jnp.logical_and(bad, denom0 > 0)
                history = tree_map(
                    lambda h: jnp.where(rejoined, jnp.zeros_like(h), h),
                    history,
                )
            st = TrainState(avg_params, avg_stats, history, st.iter)
            if audit:
                return (
                    tree_map(lambda x: x[None], st),
                    losses[None],
                    tree_map(lambda x: x[None], astats),
                )
            return tree_map(lambda x: x[None], st), losses[None]

        # state AND batches are donated: the consumed round's batch
        # buffers are recycled on device (XLA reuses them as scratch /
        # for outputs) instead of coexisting with round r+1's incoming
        # batch — with the pipelined RoundFeed keeping a batch in
        # flight, that halves steady-state batch memory.  Callers pass
        # host numpy batches (safe to reuse: the jit places a fresh
        # device buffer and donates THAT) or a freshly-placed device
        # batch per round (the apps/RoundFeed pattern); a device batch
        # is deleted by the round that consumes it.
        out_specs = (
            (P(axis), P(axis), P(axis)) if audit else (P(axis), P(axis))
        )
        self._round = jax.jit(
            shard_map(
                round_body,
                mesh=mesh,
                in_specs=(P(axis), batch_in_spec, P(), P(axis)),
                out_specs=out_specs,
                **shmap_kw,
            ),
            donate_argnums=(0, 1),
        )
        obs.track_jit(self._round)  # feeds the jit-cache gauge
        # per-mask placed live masks, cached: the chaos/degraded loops
        # pass the SAME mask for many consecutive rounds, and the
        # all-alive default mask is placed exactly once.  A true LRU
        # (move-to-front on hit, evict-oldest at the bound): elastic
        # membership churns a fresh mask per view epoch, and the old
        # clear-the-world overflow dropped the hot all-alive entry
        # along with the churn.
        self._live_cache: "OrderedDict[bytes, jax.Array]" = OrderedDict()

        # intra-slice averaging program (two-tier schedule only): the
        # same local window, but the averaging epilogue is a PER-SLICE
        # masked weighted mean.  Expressed as a stacked per-slice psum
        # (each worker selects its own slice's row) because this jax
        # build's shard_map doesn't lower psum(axis_index_groups=...);
        # on the virtual mesh collectives are shared-memory copies
        # either way, and the tier byte accounting below models the
        # ICI-vs-DCN split (hierarchy.py module docstring).
        self._slice_round = None
        if self._two_tier:
            slice_ids = jnp.asarray(hierarchy.slice_ids(), jnp.int32)
            num_slices = hierarchy.num_slices

            def slice_body(state, batches, rng, live):
                st = tree_map(lambda x: x[0], state)
                bt = tree_map(lambda x: x[0], batches)
                widx = jax.lax.axis_index(axis)
                lrng = jax.random.fold_in(rng, widx)
                st, out = solver._step_tau(st, bt, lrng)
                if audit:
                    losses, astats = out
                else:
                    losses = out
                alive = live[0]
                if mask_nf:
                    bad = (
                        jnp.sum(astats["nonfinite_grads"])
                        + jnp.sum(astats["nonfinite_params"])
                    ) > 0
                    ok = jnp.where(bad, 0.0, 1.0)
                    alive = alive * ok
                    astats = dict(astats, masked=1.0 - ok)
                sid = slice_ids[widx]
                onehot = (
                    jnp.arange(num_slices, dtype=jnp.int32) == sid
                ).astype(jnp.float32)
                # per-slice live counts, visible to every worker; each
                # worker reads its OWN slice's count
                denom0_all = jax.lax.psum(onehot * alive, axis)
                denom0 = jnp.take(denom0_all, sid)
                denom = jnp.maximum(denom0, 1.0)

                def smean(w):
                    contrib = jnp.where(alive > 0, w, jnp.zeros_like(w))
                    stacked = (
                        onehot.reshape((num_slices,) + (1,) * w.ndim)
                        * contrib[None]
                    )
                    sums = jax.lax.psum(stacked, axis)
                    m = jnp.take(
                        sums, sid, axis=0
                    ) / denom.astype(w.dtype)
                    # a fully-departed slice keeps its own params (its
                    # slots are stale until readmission broadcasts) —
                    # unlike the global round there may be NO live
                    # worker in this group even on a healthy fleet
                    return jnp.where(denom0 > 0, m, w)

                avg_params = (
                    tree_map(smean, st.params)
                    if average_params else st.params
                )
                avg_stats = (
                    tree_map(smean, st.stats)
                    if average_stats and average_params
                    else st.stats
                )
                history = st.history
                if mask_nf and average_params:
                    # audit-masked worker rejoining its slice mean:
                    # zero its momentum (the fused round's contract)
                    rejoined = jnp.logical_and(bad, denom0 > 0)
                    history = tree_map(
                        lambda h: jnp.where(
                            rejoined, jnp.zeros_like(h), h
                        ),
                        history,
                    )
                st = TrainState(avg_params, avg_stats, history, st.iter)
                if audit:
                    return (
                        tree_map(lambda x: x[None], st),
                        losses[None],
                        tree_map(lambda x: x[None], astats),
                    )
                return tree_map(lambda x: x[None], st), losses[None]

            self._slice_round = jax.jit(
                shard_map(
                    slice_body,
                    mesh=mesh,
                    in_specs=(P(axis), batch_in_spec, P(), P(axis)),
                    out_specs=out_specs,
                    **shmap_kw,
                ),
                donate_argnums=(0, 1),
            )
            obs.track_jit(self._slice_round)

        def eval_body(state, batches, counts):
            # heterogeneous partitions: every worker's batches are padded
            # to the max count; only its own first `counts[w]` batches
            # score (equal partitions just pass counts == nb everywhere)
            st = tree_map(lambda x: x[0], state)
            bt = tree_map(lambda x: x[0], batches)
            scores = solver._forward_test(
                st.params, st.stats, bt, count=counts[0]
            )
            # global accumulation (the RDD reduce of test scores,
            # CifarApp.scala:113)
            return {k: jax.lax.psum(v, axis) for k, v in scores.items()}

        self._eval = jax.jit(
            shard_map(
                eval_body,
                mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis)),
                out_specs=P(),
            )
        )

    # ------------------------------------------------------------------
    def init_state(self, seed: int = 0) -> TrainState:
        """All workers start from identical weights (the initial broadcast,
        CifarApp.scala:92-97); per-worker slots stacked on axis 0 and
        sharded over ``dp``."""
        st = self.solver.init_state(seed)
        n = self.num_workers
        if jax.process_count() == 1:
            stacked = tree_map(
                lambda x: jnp.broadcast_to(x, (n,) + x.shape), st
            )
            return shard_leading(stacked, self.mesh, self.axis)
        # multi-host: identical init everywhere; each process materializes
        # its local workers' shards from the broadcast value
        sharding = leading_sharding(self.mesh, self.axis)

        def mk(x):
            x = np.asarray(x)
            full = np.broadcast_to(x, (n,) + x.shape)
            return jax.make_array_from_callback(
                full.shape, sharding, lambda idx: full[idx]
            )

        return tree_map(mk, st)

    def broadcast_state(self, st: TrainState) -> TrainState:
        """Re-place a SINGLE-replica TrainState (a snapshot restore)
        onto the mesh: every worker slot gets the same value — the
        reference's restore-on-every-executor semantics.  The resume
        entry for ``imagenet_run_db_app --resume``, the chaos harness,
        and the sentry's rollback path."""
        if self._comm is not None:
            # a restored state invalidates the comm plane's carried
            # anchor/residual and any in-flight collective — a stale
            # correction applied onto restored params would corrupt
            # them (the residual reset mirrors the momentum-zeroing
            # rejoin contract)
            self._comm.reset()
        n = self.num_workers
        stacked = tree_map(
            lambda x: np.broadcast_to(
                np.asarray(x), (n,) + np.asarray(x).shape
            ).copy(),
            jax.device_get(st),
        )
        if jax.process_count() == 1:
            return shard_leading(stacked, self.mesh, self.axis)
        return shard_leading_global(
            tree_map(
                lambda x: x[local_worker_slice(self.mesh, self.axis)],
                stacked,
            ),
            self.mesh,
            self.axis,
        )

    # --- full job state (crash consistency, io/checkpoint extra_state)
    def export_comm_state(self):
        """The comm plane's carried error-feedback residuals as a
        host-side jobstate fragment, or None on the classic fused
        round (no carried state).  Snapshot this beside params so a
        resumed run continues the EF-SGD trajectory bit-identically
        (``runtime/recover.py``)."""
        if self._comm is None:
            return None
        return self._comm.export_state()

    def restore_comm_state(self, exported) -> None:
        """Load residuals exported by ``export_comm_state`` — call
        AFTER ``broadcast_state`` (which resets the plane) so the
        journaled residuals land on the freshly placed params."""
        if exported is None:
            return
        if self._comm is None:
            raise ValueError(
                "jobstate carries comm residuals but this trainer runs "
                "the classic fused round (compress/overlap off)"
            )
        self._comm.restore_state(exported)

    def reset_comm_state(self) -> None:
        """Drop carried comm state (fresh-run entry for a reused
        trainer: in-process chaos/recover harnesses)."""
        if self._comm is not None:
            self._comm.reset()

    def _place_live(self, live_mask) -> jax.Array:
        """Place a host (num_workers,) 0/1 mask over the dp axis.
        Cached per distinct mask value — the loops pass the same mask
        round after round (all-alive, or one fixed fault pattern), so
        the placement happens once, not once per round."""
        # sparknet: sync-ok(live_mask is a host 0/1 array, never a device value; placement cached per mask)
        live = np.asarray(live_mask, np.float32).reshape(-1)
        if live.shape[0] != self.num_workers:
            raise ValueError(
                f"live_mask has {live.shape[0]} entries, mesh has "
                f"{self.num_workers} workers"
            )
        key = live.tobytes()
        cached = self._live_cache.get(key)
        if cached is not None:
            # LRU hit: keep hot masks (the all-alive default, a standing
            # fault pattern) resident while membership churn turns over
            self._live_cache.move_to_end(key)
            return cached
        sharding = leading_sharding(self.mesh, self.axis)
        if jax.process_count() > 1:
            placed = jax.make_array_from_callback(
                live.shape, sharding, lambda idx: live[idx]
            )
        else:
            placed = jax.device_put(live, sharding)
        while len(self._live_cache) >= self._LIVE_CACHE_MAX:
            # evict the coldest entry only: a churning mask stream
            # (every membership view epoch is a new mask value) stays
            # bounded WITHOUT dropping the hot entries alongside it
            self._live_cache.popitem(last=False)
        self._live_cache[key] = placed
        return placed

    def round(
        self,
        state: TrainState,
        batches: Dict[str, jax.Array],
        rng=None,
        live_mask=None,
        round_index: Optional[int] = None,
    ):
        """One averaging round: ``batches[blob]`` is (num_workers, tau, ...)
        — worker-major, tau-deep.  Returns (state, losses (workers, tau)).

        ``live_mask`` (num_workers,) of 0/1 marks which dp workers
        survive this round: dead workers are excluded from the average
        (masked weighted mean) and receive the survivor mean — a lost
        partition degrades throughput, never the weights.  ``None``
        means all alive (identical numerics to the unmasked round).

        ``round_index`` is the ABSOLUTE round — only the two-tier
        hierarchy schedule consumes it (which rounds cross slices);
        omitted, the trainer counts its own calls, which is correct
        for fresh runs but loses the absolute schedule across resumes.

        With the solver's numerics audit on, returns ``(state, losses,
        stats)`` where ``stats`` is the per-worker audit tree (leaves
        (num_workers, tau); plus ``masked`` (num_workers,) when the
        in-graph non-finite mask is armed)."""
        rng = rng if rng is not None else default_train_key(0)
        # sparknet: sync-ok(round_index is a host int from the driver loop, never a device value)
        r = self._auto_round if round_index is None else int(round_index)
        self._auto_round = r + 1
        # two-tier schedule: intra-slice rounds between cross-slice
        # (global) ones; flat specs and hierarchy=None are always cross
        intra = self._two_tier and not self.hierarchy.is_cross_round(r)
        # "average" is the whole averaging round (this method IS one
        # round of the SparkNet algorithm); "execute" nests inside it as
        # the fused XLA program's dispatch/execution.  Span timing stays
        # dispatch-honest: no extra device sync is added here.
        astats = None
        with obs.span("average"):
            if live_mask is None:
                live_mask = np.ones((self.num_workers,), np.float32)
            live = self._place_live(live_mask)  # cached per mask value
            if intra:
                # a pending overlapped CROSS-slice collective lands at
                # this round boundary (its correction is global
                # consensus — applying it after a slice-local average
                # would de-synchronize slices); with K > 1 the overlap
                # window is the boundary gap, disclosed in PERF.md
                if self._comm is not None:
                    state = self._comm.finalize(state)
                with obs.span("execute"):
                    if self.audit:
                        state, losses, astats = self._slice_round(
                            state, batches, rng, live
                        )
                    else:
                        state, losses = self._slice_round(
                            state, batches, rng, live
                        )
                tm = obs.training_metrics()
                if tm is not None and self.average_params:
                    tm.collective_bytes.labels("none").inc(
                        self._payload_bytes(state)
                    )
            elif self._comm is not None:
                # comm plane: delta-quantized chunked collectives,
                # optionally overlapped with the next round's compute
                out = self._comm.round(
                    state, batches, rng, live, live_mask
                )
                if self.audit:
                    state, losses, astats = out
                else:
                    state, losses = out
            else:
                with obs.span("execute"):
                    if self.audit:
                        state, losses, astats = self._round(
                            state, batches, rng, live
                        )
                    else:
                        state, losses = self._round(
                            state, batches, rng, live
                        )
                tm = obs.training_metrics()
                if tm is not None and self.average_params:
                    # the fused fp32 collective's modeled wire bytes
                    # (ring factor x params+stats payload) — computed
                    # once, charged per round
                    tm.collective_bytes.labels("none").inc(
                        self._payload_bytes(state)
                    )
            # tier-split byte/round accounting for hierarchy runs: the
            # intra series models the ICI (in-slice) fabric, the cross
            # series the DCN — the quantity the two-tier schedule
            # divides by K (bench.py --mode=elastic pins the ratio)
            tm = obs.training_metrics()
            if (
                tm is not None
                and self.hierarchy is not None
                and self.average_params
            ):
                tier = "intra" if intra else "cross"
                payload = self._payload_bytes(state)
                if not intra and self._comm is not None:
                    payload = self._comm.payload_bytes_per_round or payload
                tm.hierarchy_rounds.labels(tier).inc()
                tm.hierarchy_bytes.labels(tier).inc(payload)
            # recorded lazily: smoothed_loss pulls the worker-mean of the
            # addressable shards on read (Solver._drain_losses) — no
            # device->host sync in the round loop
            self.solver.note_losses(losses)
        tm = obs.training_metrics()
        if tm is not None:
            tm.rounds.inc()
            tm.iters.inc(losses.shape[-1])  # tau (shape read: no sync)
        prof = obs_profile.active()
        if prof is not None:
            # round-anatomy profiler (--profile): static work sizes once,
            # then the per-shard execute probe + round finalize.  Outside
            # the average span so the probe's sync never inflates it.
            self._note_profile_work(prof, int(losses.shape[-1]), state)
            prof.observe_round(losses)
        obs.report_healthy()  # a completed round clears /healthz
        if self.audit:
            return state, losses, astats
        return state, losses

    def _payload_bytes(self, state) -> int:
        """Modeled per-round fp32 collective payload bytes (ring factor
        x params+stats), computed once per trainer from the state's
        shapes."""
        if self._fused_payload_bytes is None:
            from sparknet_tpu.parallel import comm as _comm

            self._fused_payload_bytes = _comm.fused_round_payload_bytes(
                state, self.average_stats
            )
        return self._fused_payload_bytes

    def _note_profile_work(self, prof, tau: int, state) -> None:
        """Hand the profiler this trainer's modeled per-round work: MXU
        FLOPs (analytic shape walk) and collective payload bytes (comm
        plane when engaged, else the fused fp32 model)."""
        # memo: a WEAKREF to the noting trainer lives on the profiler —
        # id()-based keys on either side collide when a fresh object
        # recycles a freed address, silently starving the new one of
        # its work sizes
        noted = getattr(prof, "_work_noted_by", None)
        if noted is not None and noted[0]() is self and noted[1] == tau:
            return
        prof._work_noted_by = (weakref.ref(self), tau)
        flops = None
        try:
            from sparknet_tpu.utils.flops import train_flops

            flops = train_flops(self.solver.net) * tau * self.num_workers
        except Exception:  # a net without static shapes stays unmodeled
            pass
        if self._comm is not None:
            payload = self._comm.payload_bytes_per_round or None
            compress = self._comm.compress
        else:
            if self.average_params:
                self._payload_bytes(state)
            payload = self._fused_payload_bytes
            compress = "none"
        prof.note_round_work(
            flops_per_round=flops,
            comm_bytes_per_round=payload,
            compress=compress,
            num_workers=self.num_workers,
        )

    def finalize(self, state: TrainState) -> TrainState:
        """Land any in-flight overlapped averaging collective into
        ``state`` (``--overlap_avg``): call before an eval or at the
        end of training so the last round's average is applied.
        No-op on the default (fused) path and when nothing is
        pending."""
        if self._comm is not None:
            return self._comm.finalize(state)
        return state

    def test_and_store_result(
        self, state: TrainState, batches: Dict[str, jax.Array], counts=None
    ) -> Dict[str, float]:
        """Distributed eval: ``batches[blob]`` is (num_workers, nb, ...);
        returns accumulated scores over ALL workers' batches.  With
        heterogeneous test partitions, pad every worker to the same nb and
        pass ``counts`` (num_workers,) int32 — each worker scores only its
        own first ``counts[w]`` batches (the reference's per-partition
        full-pass sampler, CifarApp.scala:103-106)."""
        if counts is None:
            nb = (
                next(iter(batches.values())).shape[1]
                if jax.process_count() > 1
                else len(next(iter(batches.values()))[0])
            )
            counts = np.full((self.num_workers,), nb, np.int32)
        counts = np.asarray(counts, np.int32)
        if jax.process_count() > 1 and counts.shape[0] == self.num_workers:
            # pass the GLOBAL counts on every host; place like the state
            sharding = leading_sharding(self.mesh, self.axis)
            counts_arr = jax.make_array_from_callback(
                counts.shape, sharding, lambda idx: counts[idx]
            )
        else:
            counts_arr = jnp.asarray(counts, jnp.int32)
        out = self._eval(state, batches, counts_arr)
        return {k: float(v) for k, v in jax.device_get(out).items()}

    @staticmethod
    def pad_partitions(parts):
        """Stack per-worker {blob: (nb_w, ...)} dicts of UNEQUAL nb_w into
        ({blob: (N, nb_max, ...)} zero-padded, counts (N,)) for
        ``test_and_store_result`` — the pad-and-mask layout."""
        keys = parts[0].keys()
        counts = np.array(
            [len(next(iter(p.values()))) for p in parts], np.int32
        )
        nb_max = int(counts.max())
        stacked = {}
        for k in keys:
            ref = parts[0][k]
            out = np.zeros((len(parts), nb_max) + ref.shape[1:], ref.dtype)
            for w, p in enumerate(parts):
                out[w, : len(p[k])] = p[k]
            stacked[k] = out
        return stacked, counts


class AllReduceTrainer:
    """Synchronous gradient all-reduce DP (the P2PSync replacement), with
    optional tensor-parallel param placement over ``mp``."""

    def __init__(
        self,
        solver: Solver,
        mesh: Mesh,
        dp_axis: str = "dp",
        mp_axis: Optional[str] = None,
    ):
        self.solver = solver
        self.mesh = mesh
        self.dp_axis = dp_axis
        if mp_axis is not None and mp_axis not in mesh.axis_names:
            raise ValueError(
                f"mp_axis {mp_axis!r} is not a mesh axis {mesh.axis_names}"
            )
        self.mp_axis = mp_axis

        repl = NamedSharding(mesh, P())
        # batches are (tau, global_batch, ...): shard the batch dim over dp
        batch_sharding = NamedSharding(mesh, P(None, dp_axis))
        # structure/shapes only — no RNG or device memory spent
        params0, stats0 = jax.eval_shape(solver.net.init, 0)
        param_shardings = self._param_shardings(params0)
        # history mirrors each param blob's placement; stats replicated
        if solver.method in ("ADADELTA", "ADAM"):
            history_shardings = (param_shardings, param_shardings)
        else:
            history_shardings = param_shardings
        state_shardings = TrainState(
            params=param_shardings,
            stats=tree_map(lambda _: repl, stats0),
            history=history_shardings,
            iter=repl,
        )
        self._state_shardings = state_shardings
        self._jit_round = jax.jit(
            solver._step_tau,
            donate_argnums=(0,),
            in_shardings=(state_shardings, batch_sharding, repl),
            out_shardings=(state_shardings, repl),
        )
        self._batch_sharding = batch_sharding
        obs.track_jit(self._jit_round)  # feeds the jit-cache gauge

    @property
    def batch_sharding(self):
        """The (tau, global_batch) placement ``step()`` applies — public
        for feeds that issue the put on a producer thread (RoundFeed);
        ``step()`` on an already-so-placed batch re-puts as a no-op."""
        return self._batch_sharding

    def _param_shardings(self, params):
        """TP policy: shard the output-channel dim of large param blobs over
        ``mp`` when divisible; everything else replicated.  GSPMD inserts
        the activation collectives."""
        mesh = self.mesh

        def place(x):
            if (
                self.mp_axis
                and x.ndim >= 2
                and x.shape[0] % mesh.shape[self.mp_axis] == 0
                and x.size >= 4096
            ):
                return NamedSharding(
                    mesh, P(self.mp_axis, *([None] * (x.ndim - 1)))
                )
            return NamedSharding(mesh, P())

        return tree_map(place, params)

    def init_state(self, seed: int = 0) -> TrainState:
        st = self.solver.init_state(seed)
        return jax.device_put(st, self._state_shardings)

    def shard_state(self, state: TrainState) -> TrainState:
        """Place an existing (host or single-device) TrainState onto the
        mesh — the resume/warm-start entry (``Solver::Restore`` before
        ``P2PSync::Run``, tools/caffe.cpp:207-216)."""
        return jax.device_put(state, self._state_shardings)

    def step(self, state: TrainState, batches: Dict[str, jax.Array], rng=None):
        """tau synchronous steps on a globally-sharded batch
        (batches[blob]: (tau, global_B, ...)).  With the solver's
        numerics audit on (readable here at step time — the jit's
        output sharding is a pytree prefix, so no rebuild is needed),
        returns ``(state, losses, stats)``."""
        rng = rng if rng is not None else default_train_key(0)
        audit = bool(getattr(self.solver, "audit", False))
        stats = None
        with obs.span("execute"):
            batches = jax.device_put(batches, self._batch_sharding)
            state, out = self._jit_round(state, batches, rng)
            if audit:
                losses, stats = out
            else:
                losses = out
            self.solver.note_losses(losses)
        tm = obs.training_metrics()
        if tm is not None:
            tm.rounds.inc()
            tm.iters.inc(losses.shape[0])  # tau (shape read: no sync)
        # --profile: finalize the profiled round (losses are replicated
        # here, so no per-worker shard probe — phases/skew come from the
        # span stream and the feed's worker hooks)
        obs_profile.observe_round_if_active(losses)
        obs.report_healthy()
        if audit:
            return state, losses, stats
        return state, losses
