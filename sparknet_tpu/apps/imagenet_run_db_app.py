"""ImageNetRunDBApp — phase 2 of the two-phase ImageNet DB path.

Reference: ``src/main/scala/apps/ImageNetRunDBApp.scala:40-117`` — read
the infoFile for per-worker test batch counts, build per-worker solvers
whose engine ``DataLayer`` reads the DBs, **warm-start from a
.caffemodel** (``net.loadWeightsFromFile``, ``:72-77``), then the
τ=50 averaging loop testing every 10 rounds.  The reference's periodic
weight save (commented out at ``:95-100``) is wired in here for real:
``--snapshot_every N`` writes model+solver state through
``io/checkpoint.py`` and ``--resume`` continues from the newest one —
kill -> resume -> eval is a tested path (tests/test_db_apps.py).

Run:
    python -m sparknet_tpu.apps.imagenet_run_db_app --db_dir=DB_DIR \
        --rounds=20 --warm_start=weights.caffemodel
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

TAU = 50  # syncInterval, ImageNetRunDBApp.scala:104


def _broadcast_state(trainer, st):
    """Restore semantics: every worker restarts from the snapshot file,
    exactly like the reference restoring the same .solverstate on each
    executor (now shared trainer machinery — the sentry's rollback path
    uses the same re-placement)."""
    return trainer.broadcast_state(st)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--db_dir", required=True)
    parser.add_argument("--model", default="caffenet")
    parser.add_argument("--rounds", type=int, default=10)
    parser.add_argument("--tau", type=int, default=0, help="0 = reference (50)")
    parser.add_argument("--test_every", type=int, default=10)
    parser.add_argument("--crop", type=int, default=0)
    parser.add_argument("--no_mirror", action="store_true")
    parser.add_argument("--warm_start", default=None,
                        help=".caffemodel[.h5] to load weights from")
    parser.add_argument("--snapshot_every", type=int, default=0,
                        help="snapshot every N rounds")
    parser.add_argument("--snapshot_prefix", default=None)
    parser.add_argument("--resume", action="store_true",
                        help="continue from the newest snapshot")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--serial_feed", action="store_true",
        help="disable the pipelined round feed (PERF.md: relay-degraded "
        "links)",
    )
    parser.add_argument(
        "--cache_dir", default=None,
        help="when --db_dir is a gs://|s3://|http(s)://|file:// url, "
        "stage the DB files through the host-local content-addressed "
        "chunk cache rooted here (data/chunk_cache.py) — a restarted "
        "run re-verifies local bytes instead of re-downloading",
    )
    parser.add_argument(
        "--cache_bytes", default="0",
        help="chunk-cache LRU byte budget, e.g. 512M / 8G "
        "(0 = unbounded)",
    )
    parser.add_argument(
        "--shuffle_epochs", type=int, default=0,
        help="split --rounds into N epochs and re-permute which worker "
        "reads which train DB shard between them (seeded shuffle-by-"
        "assignment, data/shuffle.py) — no bytes move, only the "
        "worker->shard table (0/1 = fixed assignment; resumes must "
        "pass the same --rounds/--shuffle_epochs for stable epoch "
        "boundaries)",
    )
    from sparknet_tpu import obs
    from sparknet_tpu.io import journal as journal_mod
    from sparknet_tpu.parallel import comm, hierarchy

    obs.add_cli_args(parser)  # --obs / --obs_port / --trace_out
    comm.add_cli_args(parser)  # --compress / --overlap_avg
    hierarchy.add_cli_args(parser)  # --slices / --cross_slice_every / --elastic
    journal_mod.add_cli_args(parser)  # --journal / --no_journal / ...
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import config as cfg, models, runtime
    from sparknet_tpu.apps.scores import primary_accuracy
    from sparknet_tpu.data import RoundFeed, stack_windows
    from sparknet_tpu.io import caffemodel, checkpoint
    from sparknet_tpu.parallel import (
        first_worker,
        make_mesh,
        shard_leading,
    )
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import TrainingLog

    log = TrainingLog(tag="imagenet_run_db")
    # --db_dir may be an object-store url: the DB files stage through
    # the chunk cache to verified local paths (CRC-manifested, atomic,
    # quarantine-on-corruption) — phase 2 runs straight off a bucket,
    # and a restart re-verifies local bytes instead of re-downloading
    from sparknet_tpu.data import object_store

    remote_db = object_store.is_object_store_url(args.db_dir)
    if remote_db:
        import tempfile

        from sparknet_tpu.data import chunk_cache

        if (
            args.cache_dir is None
            and args.snapshot_prefix is None
            and (args.resume or args.snapshot_every)
        ):
            # snapshots would land in a fresh temp cache dir that the
            # NEXT invocation cannot find — --resume would report "no
            # snapshots" while valid ones sit stranded in /tmp
            raise SystemExit(
                "imagenet_run_db: a remote --db_dir with "
                "--snapshot_every/--resume needs a stable --cache_dir "
                "or an explicit --snapshot_prefix (snapshots in a "
                "temp-dir cache would be unfindable on restart)"
            )
        cache_root = args.cache_dir or tempfile.mkdtemp(
            prefix="sparknet_db_cache_"
        )
        _store = object_store.open_store(args.db_dir)
        _cache = chunk_cache.ChunkCache(
            cache_root, byte_budget=chunk_cache.parse_bytes(args.cache_bytes)
        )
        log.log(f"staging {args.db_dir} through chunk cache {cache_root}")

        def db_path(name: str) -> str:
            return _cache.local_path(_store, name)
    else:

        def db_path(name: str) -> str:
            return os.path.join(args.db_dir, name)

    with open(db_path("imagenet_db_info.json")) as f:
        info = json.load(f)
    n_workers = int(info["workers"])
    full = int(info["full_size"])
    args.tau = args.tau or TAU
    crop = args.crop or (227 if full >= 256 else (full * 7) // 8)
    log.log(f"testPartitionSizes = {info['test_batches']}")
    num_test_mbs = int(sum(info["test_batches"]))

    mean = caffemodel.load_mean_image(
        db_path("imagenet_mean.binaryproto")
    )

    # per-worker native pipelines: train crops randomly + mirrors, test
    # center-crops — DataTransformer semantics in the reader thread
    pipes = [
        runtime.DataPipeline(
            db_path(f"ilsvrc12_train_db_{w}.sndb"),
            batch_size=int(info["train_batch"]),
            shape=(3, full, full),
            crop=crop,
            mirror=not args.no_mirror,
            train=True,
            mean=mean,
            seed=args.seed + w,
        )
        for w in range(n_workers)
    ]
    test_pipes = [
        runtime.DataPipeline(
            db_path(f"ilsvrc12_val_db_{w}.sndb"),
            batch_size=int(info["test_batch"]),
            shape=(3, full, full),
            crop=crop,
            train=False,
            mean=mean,
            seed=args.seed,
        )
        for w in range(n_workers)
    ]

    from sparknet_tpu.models.builders import BUILDERS

    netp = (
        models.load_model(args.model, classes=int(info["classes"]))
        if args.model in BUILDERS  # prototxt-backed models take no kwargs
        else models.load_model(args.model)
    )
    netp = cfg.replace_data_layers(
        netp,
        [(int(info["train_batch"]), 3, crop, crop), (int(info["train_batch"]),)],
        [(int(info["test_batch"]), 3, crop, crop), (int(info["test_batch"]),)],
    )
    solver = Solver(models.load_model_solver(args.model), net_param=netp)
    # --health sentry (before the trainer: audit arity bakes into the
    # shard_map output spec); rollback restores through this app's own
    # snapshot prefix below
    from sparknet_tpu.obs import health as health_mod

    sentry = health_mod.sentry_from_args(args, solver, echo=log.log)
    mesh = make_mesh({"dp": n_workers}, devices=jax.devices()[:n_workers])
    if getattr(args, "elastic", False):
        log.log(
            "--elastic: the membership controller is wired in "
            "cifar_app (this app applies the --slices/"
            "--cross_slice_every hierarchy schedule; preemption "
            "masking rides the fleet plane)"
        )
    trainer = hierarchy.averaging_trainer_from_args(
        args, solver, mesh, n_workers
    )
    state = trainer.init_state(seed=args.seed)

    prefix = args.snapshot_prefix or os.path.join(
        cache_root if remote_db else args.db_dir, "imagenet_db"
    )
    if sentry is not None:
        sentry.restore_fn = health_mod.make_restore_fn(
            solver, prefix, trainer=trainer
        )
    # --journal: the crash-consistency round ledger beside the
    # snapshots; a --resume that finds one consumes it automatically
    # (ledger-guided rewind to the last COMMITTED boundary + the
    # journaled driver state put back)
    jr = journal_mod.journal_from_args(
        args, journal_mod.default_journal_path(prefix),
        resuming=args.resume,
    )
    if jr is not None:
        log.log(f"run journal: {jr.path} (fsync={jr.fsync})")
    start_round = 0
    if args.resume:
        # fault-tolerant resume: CRC-verified, newest-valid-wins — a
        # corrupt/truncated newest snapshot (preemption mid-write) is
        # quarantined and the scan falls back to an older valid one
        job_state = None
        try:
            if jr is not None and jr.last_committed_round is not None:
                st, used, job_state, jinfo = (
                    checkpoint.restore_newest_valid_journaled(
                        solver, prefix, jr
                    )
                )
                if jinfo["in_flight_round"] is not None:
                    tm = obs.training_metrics()
                    if tm is not None:
                        tm.recover_replayed.inc()
                    log.log(
                        "journal: round %d was in flight at the crash "
                        "— re-executing it" % jinfo["in_flight_round"]
                    )
            else:
                st, used = checkpoint.restore_newest_valid(solver, prefix)
        except FileNotFoundError:
            raise SystemExit(f"--resume: no {prefix}_iter_*.solverstate*")
        except checkpoint.SnapshotCorrupt as e:
            raise SystemExit(f"--resume: {e}")
        state = _broadcast_state(trainer, st)
        if job_state:
            # driver-side state the snapshot's TrainState never
            # carried: comm-plane EF residuals + sentry scalars
            if "comm" in job_state:
                trainer.restore_comm_state(job_state["comm"])
            if sentry is not None and "sentry" in job_state:
                sentry.load_state(job_state["sentry"])
        start_round = int(np.asarray(st.iter)) // args.tau
        log.log(f"resumed from {used} at round {start_round}")
    elif args.warm_start:
        # ImageNetRunDBApp.scala:75 loadWeightsFromFile
        st = checkpoint.load_weights_into_state(
            solver, first_worker(jax.device_get(state)), args.warm_start
        )
        state = _broadcast_state(trainer, st)
        log.log(f"warm start from {args.warm_start}")
    log.log("initialize nets on workers")

    # pad-and-mask heterogeneous test partitions from the infoFile
    counts = np.asarray(info["test_batches"], np.int32)
    nb_max = int(counts.max())
    tb = {
        "data": np.zeros(
            (n_workers, nb_max, int(info["test_batch"]), 3, crop, crop),
            np.float32,
        ),
        "label": np.zeros(
            (n_workers, nb_max, int(info["test_batch"])), np.float32
        ),
    }
    for w, pipe in enumerate(test_pipes):
        for b in range(int(counts[w])):
            x, y = pipe.next()
            tb["data"][w, b] = x
            tb["label"][w, b] = y
    test_on_dev = shard_leading(tb, mesh)

    def evaluate():
        scores = trainer.test_and_store_result(
            state, test_on_dev, counts=counts
        )
        return primary_accuracy(scores) / max(1, num_test_mbs)

    # cross-epoch shuffle-by-assignment (--shuffle_epochs): worker w
    # reads train shard perm[w] for the epoch — a seeded permutation
    # pure in (seed, epoch), derived from the ABSOLUTE round index so a
    # resumed run re-derives the same table.  No bytes move; only the
    # worker->shard assignment.
    shuffle_on = args.shuffle_epochs > 1
    rounds_per_epoch = (
        -(-args.rounds // args.shuffle_epochs) if shuffle_on else None
    )

    def pipe_order(r):
        if not shuffle_on:
            return range(n_workers)
        from sparknet_tpu.data import shuffle as shuffle_mod

        e = min(r // rounds_per_epoch, args.shuffle_epochs - 1)
        return shuffle_mod.permutation(n_workers, args.seed, e)

    def assemble(r, out):
        # worker_timer: with --profile each worker's DB pull time feeds
        # the round profiler's straggler attribution (no-op otherwise)
        windows = []
        for w, p in enumerate(pipe_order(r)):
            pipe = pipes[p]
            with obs.profile.worker_timer(r, w, n_workers):
                batches = [pipe.next() for _ in range(args.tau)]
                windows.append(
                    {
                        "data": np.stack([b[0] for b in batches]),
                        "label": np.stack([b[1] for b in batches]),
                    }
                )
        return stack_windows(windows, out)

    # pipelined feed, resume-aware: rounds are absolute, so a resumed
    # run's producer starts at start_round and the reader pipelines pick
    # up where the DB cursors sit (--serial_feed: old serial path)
    run_obs = obs.start_from_args(args, echo=log.log)
    feed = RoundFeed(
        assemble,
        mesh=mesh,
        pipelined=not args.serial_feed,
        start_round=start_round,
        num_rounds=args.rounds,
    )
    try:
        for r in range(start_round, start_round + args.rounds):
            if r % args.test_every == 0:
                # land any in-flight overlapped average before scoring
                state = trainer.finalize(state)
                log.log(f"{evaluate() * 100:.2f}% accuracy", i=r)
            log.log("training", i=r)
            if jr is not None:
                # write-ahead intent: restart knows round r was in
                # flight whatever happens next
                jr.begin_round(r, iter=r * args.tau, cursor=r)
            if sentry is not None:
                state, _ = sentry.guarded_round(
                    trainer, state, feed.next_round(r), round_index=r
                )
            else:
                state, _ = trainer.round(
                    state, feed.next_round(r), round_index=r
                )
            log.log(f"trained, smoothed_loss {solver.smoothed_loss:.4f}", i=r)
            if args.snapshot_every and (r + 1) % args.snapshot_every == 0:
                # a snapshot must capture the round's AVERAGE, not a
                # mid-flight overlapped state
                state = trainer.finalize(state)
                st = first_worker(jax.device_get(state))
                extra = {"cursor": {"round": r + 1}}
                comm_state = trainer.export_comm_state()
                if comm_state is not None:
                    extra["comm"] = comm_state
                if sentry is not None:
                    extra["sentry"] = sentry.export_state()
                model_path, state_path = checkpoint.snapshot(
                    solver, st, prefix, extra_state=extra
                )
                if jr is not None:
                    # the durable boundary: the commit rides the
                    # published snapshot ref (exactly-once rewind
                    # target for restore_newest_valid_journaled)
                    jr.commit_round(
                        r, iter=(r + 1) * args.tau,
                        snapshot=os.path.basename(state_path),
                    )
                log.log(f"snapshot -> {model_path}", i=r)

        state = trainer.finalize(state)  # last round's average lands
        acc = evaluate()
        log.log(f"final accuracy {acc * 100:.2f}%")
        print(f"final accuracy {acc * 100:.2f}%")
        return 0
    except health_mod.SentryHalt as e:
        # no snapshot of the condemned weights; the newest snapshot on
        # disk predates the anomaly and stays the restore point
        log.log(f"training halted by the health sentry: {e}")
        return 1
    finally:
        # telemetry closes AFTER the final-accuracy line so the JSONL
        # run log carries the run's headline result too
        if jr is not None:
            jr.close()
        feed.stop()
        run_obs.close()
        log.close()
        for p in pipes + test_pipes:
            p.close()


if __name__ == "__main__":
    raise SystemExit(main())
