"""CifarDBApp — the DB-path training driver.

Reference: ``src/main/scala/apps/CifarDBApp.scala`` — phase 1 writes
per-worker DB shards + mean.binaryproto through the shim
(``CreateDB``/``ComputeMean``), phase 2 trains with the engine's own
``DataLayer`` reading those DBs (no callback data path).  Here phase 1
writes native record DBs + the binary mean file, phase 2 feeds the same
averaging loop from ``runtime.DataPipeline`` reader threads — the native
data plane end to end.

Run:
    python -m sparknet_tpu.apps.cifar_db_app --workers=2 --rounds=6
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np


def create_dbs(data_dir: str, out_dir: str, n_workers: int, seed: int = 0):
    """Phase 1: shard train set into per-worker DBs, write test DB + mean
    (CreateDB + ComputeMean parity)."""
    from sparknet_tpu import runtime
    from sparknet_tpu.data import CifarLoader
    from sparknet_tpu.io import caffemodel

    loader = CifarLoader(data_dir, seed=seed)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for w in range(n_workers):
        path = os.path.join(out_dir, f"train_shard_{w}.sndb")
        runtime.write_datum_db(
            path, loader.train_images[w::n_workers], loader.train_labels[w::n_workers]
        )
        paths.append(path)
    test_path = os.path.join(out_dir, "test.sndb")
    runtime.write_datum_db(test_path, loader.test_images, loader.test_labels)
    mean_path = os.path.join(out_dir, "mean.binaryproto")
    caffemodel.save_mean_image(loader.mean_image, mean_path)
    return paths, test_path, mean_path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", default=None)
    parser.add_argument("--db_dir", default=None)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--tau", type=int, default=10)
    parser.add_argument("--batch", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--serial_feed", action="store_true",
        help="disable the pipelined round feed (PERF.md: relay-degraded "
        "links)",
    )
    from sparknet_tpu import obs
    from sparknet_tpu.io import journal as journal_mod
    from sparknet_tpu.parallel import comm, hierarchy

    obs.add_cli_args(parser)  # --obs / --obs_port / --trace_out
    comm.add_cli_args(parser)  # --compress / --overlap_avg
    hierarchy.add_cli_args(parser)  # --slices / --cross_slice_every / --elastic
    journal_mod.add_cli_args(parser)  # --journal / --no_journal / ...
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import models, runtime
    from sparknet_tpu.data import CifarLoader, RoundFeed, stack_windows
    from sparknet_tpu.io import caffemodel
    from sparknet_tpu.parallel import make_mesh, shard_leading
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import TrainingLog

    log = TrainingLog(tag="cifar_db")
    data_dir = args.data
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="cifar_synth_")
        CifarLoader.write_synthetic(data_dir, num_train=4000, num_test=500)
        log.log(f"synthesized CIFAR data in {data_dir}")
    db_dir = args.db_dir or tempfile.mkdtemp(prefix="cifar_dbs_")

    shard_paths, test_path, mean_path = create_dbs(
        data_dir, db_dir, args.workers, args.seed
    )
    log.log(f"created {len(shard_paths)} train DBs + test DB in {db_dir} "
            f"(native={runtime.native_available()})")

    mean = caffemodel.load_mean_image(mean_path)
    pipes = [
        runtime.DataPipeline(
            p,
            batch_size=args.batch,
            shape=(3, 32, 32),
            mean=mean,
            train=True,
            seed=args.seed + w,
        )
        for w, p in enumerate(shard_paths)
    ]
    test_pipe = runtime.DataPipeline(
        test_path, batch_size=args.batch, shape=(3, 32, 32), mean=mean, train=False
    )

    mesh = make_mesh(
        {"dp": args.workers}, devices=jax.devices()[: args.workers]
    )
    solver = Solver(models.load_model_solver("cifar10_full"))
    # --health sentry (before the trainer: audit arity bakes into the
    # shard_map output spec); no snapshots here -> rollback = halt
    from sparknet_tpu.obs import health as health_mod

    sentry = health_mod.sentry_from_args(args, solver, echo=log.log)
    if getattr(args, "elastic", False):
        log.log(
            "--elastic: the membership controller is wired in "
            "cifar_app (this app applies the --slices/"
            "--cross_slice_every hierarchy schedule; preemption "
            "masking rides the fleet plane)"
        )
    trainer = hierarchy.averaging_trainer_from_args(
        args, solver, mesh, args.workers
    )
    state = trainer.init_state(seed=args.seed)
    log.log("nets ready")

    def assemble(r, out):
        # reader-thread pulls + worker stack, on the RoundFeed producer:
        # round r+1's DB reads and H2D overlap round r's execute.
        # worker_timer: with --profile each worker's DB pull time feeds
        # the round profiler's straggler attribution (no-op otherwise)
        windows = []
        for w, p in enumerate(pipes):
            with obs.profile.worker_timer(r, w, len(pipes)):
                batches = [p.next() for _ in range(args.tau)]
                windows.append(
                    {
                        "data": np.stack([b[0] for b in batches]),
                        "label": np.stack([b[1] for b in batches]),
                    }
                )
        return stack_windows(windows, out)

    run_obs = obs.start_from_args(args, echo=log.log)
    # --journal: the round ledger (io/journal.py).  This app keeps no
    # snapshots, so commits mark in-memory round completion only
    # (durable=False) — a progress/postmortem record, not a resume
    # target; the resume-capable drivers (cli train,
    # imagenet_run_db_app) attach snapshot refs.
    jr = journal_mod.journal_from_args(args, "cifar_db_run.journal")
    feed = RoundFeed(
        assemble,
        mesh=mesh,
        pipelined=not args.serial_feed,
        num_rounds=args.rounds,
    )
    try:
        for r in range(args.rounds):
            if jr is not None:
                jr.begin_round(r, iter=r * args.tau, cursor=r)
            if sentry is not None:
                state, _ = sentry.guarded_round(
                    trainer, state, feed.next_round(r), round_index=r
                )
            else:
                state, _ = trainer.round(
                    state, feed.next_round(r), round_index=r
                )
            log.log(
                f"round {r} trained, smoothed_loss {solver.smoothed_loss:.4f}"
            )
            if jr is not None:
                jr.commit_round(r, iter=(r + 1) * args.tau, durable=False)

        state = trainer.finalize(state)  # last round's average lands
        # eval from the test DB
        nb = 2
        tb = [test_pipe.next() for _ in range(args.workers * nb)]
        test_batches = {
            "data": np.stack([b[0] for b in tb]).reshape(
                args.workers, nb, args.batch, 3, 32, 32
            ),
            "label": np.stack([b[1] for b in tb]).reshape(
                args.workers, nb, args.batch
            ),
        }
        scores = trainer.test_and_store_result(
            state, shard_leading(test_batches, mesh)
        )
        acc = scores.get("accuracy", 0.0) / (args.workers * nb)
        log.log(f"final accuracy {acc:.4f}")
        return 0
    except health_mod.SentryHalt as e:
        log.log(f"training halted by the health sentry: {e}")
        return 1
    finally:
        # telemetry closes AFTER the final-accuracy line so the JSONL
        # run log carries the run's headline result too
        if jr is not None:
            jr.close()
        feed.stop()
        run_obs.close()
        log.close()
        for p in pipes:
            p.close()
        test_pipe.close()


if __name__ == "__main__":
    raise SystemExit(main())
