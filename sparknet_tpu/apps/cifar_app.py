"""CifarApp — distributed CIFAR-10 training driver.

Reference: ``src/main/scala/apps/CifarApp.scala`` — the canonical SparkNet
loop: load + partition data across workers, build per-worker nets,
then rounds of broadcast -> tau local steps -> reduce/average, testing
every ``test_every`` rounds, all phase-logged.  Here the broadcast/reduce
plane is the mesh collective inside ``ParameterAveragingTrainer.round``, so
one call does what steps 1-5 of the reference loop did (and the
2x|theta|xN floats never touch the host).

Run:
    python -m sparknet_tpu.apps.cifar_app --data=DIR --workers=4 --rounds=50
(synthesizes CIFAR-format data when --data is omitted)
"""

from __future__ import annotations

import argparse
import tempfile

import numpy as np


TAU = 10  # reference: syncInterval = 10, CifarApp.scala:119


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", default=None, help="CIFAR binary dir")
    parser.add_argument("--workers", type=int, default=0, help="0 = all devices")
    parser.add_argument("--rounds", type=int, default=50)
    parser.add_argument("--tau", type=int, default=TAU)
    parser.add_argument("--test_every", type=int, default=10)  # CifarApp.scala:101
    parser.add_argument("--batch", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--serial_feed", action="store_true",
        help="disable the pipelined round feed (assemble+H2D on the "
        "training loop) — for relay-degraded links where overlapped "
        "transfers collapse throughput (PERF.md)",
    )
    from sparknet_tpu import obs
    from sparknet_tpu.io import journal as journal_mod
    from sparknet_tpu.parallel import comm, hierarchy

    obs.add_cli_args(parser)  # --obs / --obs_port / --trace_out
    comm.add_cli_args(parser)  # --compress / --overlap_avg
    hierarchy.add_cli_args(parser)  # --slices/--cross_slice_every/--elastic
    journal_mod.add_cli_args(parser)  # --journal / --no_journal / ...
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import models
    from sparknet_tpu.apps.scores import primary_accuracy
    from sparknet_tpu.data import (
        CifarLoader,
        MinibatchSampler,
        RoundFeed,
        stack_windows,
    )
    from sparknet_tpu.parallel import (
        ParameterAveragingTrainer,
        local_worker_slice,
        make_mesh,
        shard_leading_global,
    )
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import TrainingLog

    distributed = jax.process_count() > 1
    log = TrainingLog(tag="cifar", echo=jax.process_index() == 0)
    data_dir = args.data
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="cifar_synth_")
        CifarLoader.write_synthetic(data_dir, num_train=5000, num_test=1000)
        log.log(f"synthesized CIFAR-format data in {data_dir}")

    n_workers = args.workers or (
        jax.device_count() if distributed else jax.local_device_count()
    )
    if distributed and n_workers != jax.device_count():
        raise SystemExit("multi-host runs must use --workers == all devices")
    log.log(f"num workers: {n_workers}")

    loader = CifarLoader(data_dir, seed=args.seed)
    log.log("loaded data")

    mesh = make_mesh({"dp": n_workers}, devices=jax.devices()[:n_workers])
    # this host's contiguous block of workers (every host computes the
    # same global partitioning, then keeps only its own — the Spark
    # partitions-per-executor analog)
    mine = local_worker_slice(mesh) if distributed else slice(0, n_workers)

    x, y = loader.minibatches(args.batch, train=True)
    if len(x) < n_workers * args.tau:
        raise SystemExit(
            f"need >= {n_workers * args.tau} minibatches, have {len(x)}"
        )
    # repartition into contiguous near-equal blocks (RDD repartition
    # analog) — partition sizes may differ by one batch; each worker's
    # window sampler draws tau from its OWN partition size
    samplers = [
        MinibatchSampler(
            {"data": xs, "label": ys},
            num_sampled_batches=args.tau,
            seed=args.seed + w,
        )
        for w, (xs, ys) in enumerate(
            zip(np.array_split(x, n_workers), np.array_split(y, n_workers))
        )
        if mine.start <= w < mine.stop
    ]
    xt, yt = loader.minibatches(args.batch, train=False)
    # heterogeneous test partitions (Spark parallelize gives near-equal
    # splits; ragged tails are scored, not dropped): pad-and-mask
    test_parts = [
        {"data": xs, "label": ys}
        for xs, ys in zip(
            np.array_split(xt, n_workers), np.array_split(yt, n_workers)
        )
    ]
    num_test_batches = len(xt)

    solver = Solver(models.load_model_solver("cifar10_full"))
    # --health: numerics audit + divergence sentry.  Built BEFORE the
    # trainer (the audit arity bakes into the shard_map output spec);
    # this app keeps no snapshots, so rollback degrades to halt.
    from sparknet_tpu.obs import health as health_mod

    sentry = health_mod.sentry_from_args(args, solver, echo=log.log)
    # --compress/--overlap_avg: comm-plane averaging (delta-quantized,
    # chunked, optionally overlapped — parallel/comm.py);
    # --slices/--cross_slice_every: two-tier hierarchical schedule
    spec = hierarchy.spec_from_args(args, n_workers)
    # --stale_bound: swap in the bounded-staleness trainer (same round
    # surface; this app feeds every worker each round, so boundaries
    # see full arrival sets — the flag matters for drivers that model
    # arrivals, runtime/recover.py and the chaos harness)
    trainer = hierarchy.averaging_trainer_from_args(
        args, solver, mesh, n_workers, hierarchy=spec
    )
    # --elastic: the membership controller (runtime/membership.py)
    # maintains epoch-numbered roster views that drive each round's
    # live_mask; a SIGTERM preemption notice marks THIS process's
    # slice ($SPARKNET_SLICE_ID, the launcher sets it; defaults to the
    # last slice) leaving at the next round boundary, and the departed
    # slice rejoins from the survivor consensus (this app keeps no
    # snapshots) --rejoin_after boundaries later — the single-process
    # stand-in for the orchestrator's relaunch notice (AutoRejoin;
    # external drivers use note_join / fleet views instead).
    membership_ctl = None
    auto_rejoin = None
    if args.elastic:
        import os as _os

        from sparknet_tpu.runtime import membership as membership_mod

        membership_ctl = membership_mod.MembershipController(
            spec
            if spec is not None
            else hierarchy.HierarchySpec.flat(n_workers),
            echo=log.log,
        )
        my_slice = int(
            _os.environ.get(
                "SPARKNET_SLICE_ID",
                membership_ctl.spec.num_slices - 1,
            )
        )
        membership_ctl.sigterm_marks(my_slice)
        auto_rejoin = membership_mod.AutoRejoin(
            membership_ctl, args.rejoin_after
        )
        obs.set_membership(membership_ctl)
    state = trainer.init_state(seed=args.seed)
    test_batches, test_counts = ParameterAveragingTrainer.pad_partitions(
        test_parts
    )
    test_on_dev = shard_leading_global(
        {k: v[mine] for k, v in test_batches.items()}
        if distributed
        else test_batches,
        mesh,
    )
    log.log("finished setting up nets and weights")

    def evaluate(r=None):
        scores = trainer.test_and_store_result(
            state, test_on_dev, counts=test_counts
        )
        for name in sorted(scores):
            log.log(f"test output {name} = {scores[name] / num_test_batches:.4f}")
        return primary_accuracy(scores) / num_test_batches

    # pipelined round feed: round r+1's windows are drawn, stacked into
    # recycled buffers and device_put on a producer thread while round r
    # executes (RoundFeed; --serial_feed restores the old serial path
    # with identical numerics)
    run_obs = obs.start_from_args(args, echo=log.log)
    # --journal: the round ledger (io/journal.py).  This app keeps no
    # snapshots, so commits mark in-memory round completion only
    # (durable=False) — a progress/postmortem record carrying the view
    # epoch; the resume-capable drivers attach snapshot refs.
    jr = journal_mod.journal_from_args(args, "cifar_run.journal")
    # timed_worker_windows: with --profile the per-worker draw times
    # feed the round profiler's straggler attribution (plain list
    # comprehension otherwise)
    feed = RoundFeed(
        lambda r, out: stack_windows(
            obs.profile.timed_worker_windows(
                r, [s.next_window for s in samplers]
            ),
            out,
        ),
        place=lambda host: shard_leading_global(host, mesh),
        pipelined=not args.serial_feed,
        num_rounds=args.rounds,
    )
    from sparknet_tpu.utils import SignalHandler, SolverAction

    try:
        # the SIGTERM handler is installed only to deliver preemption
        # notices to the membership hook; SIGINT/SIGHUP keep their
        # default behavior (this app has no snapshot machinery)
        with SignalHandler(
            sigint_effect=SolverAction.NONE,
            sighup_effect=SolverAction.NONE,
            sigterm_hooks=membership_ctl is not None,
        ):
            for r in range(args.rounds):
                if r % args.test_every == 0:  # test before train, CifarApp.scala:101
                    # land any in-flight overlapped average before scoring
                    state = trainer.finalize(state)
                    log.log(f"round {r}, accuracy {evaluate(r):.4f}")
                if jr is not None:
                    jr.begin_round(
                        r, iter=r * args.tau, cursor=r,
                        view_epoch=(
                            membership_ctl.view.epoch
                            if membership_ctl is not None else 0
                        ),
                    )
                mask = None
                if membership_ctl is not None:
                    # roster changes land at the round boundary; a
                    # relaunched slice rejoins from the survivor
                    # consensus (momentum zeroed)
                    membership_ctl.advance(r)
                    auto_rejoin.on_round(r)
                    if membership_ctl.pending_joiners():
                        state, _ = membership_mod.readmit_from_survivors(
                            trainer, state, membership_ctl, r,
                            echo=log.log,
                        )
                    mask = membership_ctl.live_mask()
                    if not mask.any():
                        log.log(
                            f"round {r}: no live workers in the "
                            "membership view; stopping"
                        )
                        break
                if sentry is not None:
                    state, _ = sentry.guarded_round(
                        trainer, state, feed.next_round(r),
                        live_mask=mask, round_index=r,
                    )
                else:
                    state, _ = trainer.round(
                        state, feed.next_round(r),
                        live_mask=mask, round_index=r,
                    )
                log.log(
                    f"round {r} trained, smoothed_loss {solver.smoothed_loss:.4f}"
                )
                if jr is not None:
                    jr.commit_round(
                        r, iter=(r + 1) * args.tau, durable=False
                    )
        state = trainer.finalize(state)  # last round's average lands
        log.log(f"final accuracy {evaluate():.4f}")
        return 0
    except health_mod.SentryHalt as e:
        log.log(f"training halted by the health sentry: {e}")
        return 1
    finally:
        if membership_ctl is not None:
            membership_ctl.detach()
        if jr is not None:
            jr.close()
        feed.stop()
        run_obs.close()
        log.close()


if __name__ == "__main__":
    raise SystemExit(main())
