"""CifarApp — distributed CIFAR-10 training driver.

Reference: ``src/main/scala/apps/CifarApp.scala`` — the canonical SparkNet
loop: load + partition data across workers, build per-worker nets,
then rounds of broadcast -> tau local steps -> reduce/average, testing
every ``test_every`` rounds, all phase-logged.  Here the broadcast/reduce
plane is the mesh collective inside ``ParameterAveragingTrainer.round``, so
one call does what steps 1-5 of the reference loop did (and the
2x|theta|xN floats never touch the host).

Run:
    python -m sparknet_tpu.apps.cifar_app --data=DIR --workers=4 --rounds=50
(synthesizes CIFAR-format data when --data is omitted)
"""

from __future__ import annotations

import argparse
import tempfile

import numpy as np


TAU = 10  # reference: syncInterval = 10, CifarApp.scala:119


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", default=None, help="CIFAR binary dir")
    parser.add_argument("--workers", type=int, default=0, help="0 = all devices")
    parser.add_argument("--rounds", type=int, default=50)
    parser.add_argument("--tau", type=int, default=TAU)
    parser.add_argument("--test_every", type=int, default=10)  # CifarApp.scala:101
    parser.add_argument("--batch", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import models
    from sparknet_tpu.data import CifarLoader, MinibatchSampler
    from sparknet_tpu.parallel import (
        ParameterAveragingTrainer,
        make_mesh,
        shard_leading,
    )
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import TrainingLog

    log = TrainingLog(tag="cifar")
    data_dir = args.data
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="cifar_synth_")
        CifarLoader.write_synthetic(data_dir, num_train=5000, num_test=1000)
        log.log(f"synthesized CIFAR-format data in {data_dir}")

    n_workers = args.workers or jax.local_device_count()
    log.log(f"num workers: {n_workers}")

    loader = CifarLoader(data_dir, seed=args.seed)
    log.log("loaded data")

    x, y = loader.minibatches(args.batch, train=True)
    if len(x) < n_workers * args.tau:
        raise SystemExit(
            f"need >= {n_workers * args.tau} minibatches, have {len(x)}"
        )
    # repartition into contiguous near-equal blocks (RDD repartition
    # analog) — partition sizes may differ by one batch; each worker's
    # window sampler draws tau from its OWN partition size
    samplers = [
        MinibatchSampler(
            {"data": xs, "label": ys},
            num_sampled_batches=args.tau,
            seed=args.seed + w,
        )
        for w, (xs, ys) in enumerate(
            zip(np.array_split(x, n_workers), np.array_split(y, n_workers))
        )
    ]
    xt, yt = loader.minibatches(args.batch, train=False)
    # heterogeneous test partitions (Spark parallelize gives near-equal
    # splits; ragged tails are scored, not dropped): pad-and-mask
    test_parts = [
        {"data": xs, "label": ys}
        for xs, ys in zip(
            np.array_split(xt, n_workers), np.array_split(yt, n_workers)
        )
    ]
    num_test_batches = len(xt)

    mesh = make_mesh({"dp": n_workers}, devices=jax.devices()[:n_workers])
    solver = Solver(models.load_model_solver("cifar10_full"))
    trainer = ParameterAveragingTrainer(solver, mesh)
    state = trainer.init_state(seed=args.seed)
    test_batches, test_counts = ParameterAveragingTrainer.pad_partitions(
        test_parts
    )
    test_on_dev = shard_leading(test_batches, mesh)
    log.log("finished setting up nets and weights")

    for r in range(args.rounds):
        if r % args.test_every == 0:  # test before train, CifarApp.scala:101
            scores = trainer.test_and_store_result(state, test_on_dev, counts=test_counts)
            acc = scores.get("accuracy", 0.0) / num_test_batches
            log.log(f"round {r}, accuracy {acc:.4f}")
        windows = [s.next_window() for s in samplers]
        stacked = {
            k: np.stack([w[k] for w in windows]) for k in windows[0]
        }
        state, _ = trainer.round(state, shard_leading(stacked, mesh))
        log.log(f"round {r} trained, smoothed_loss {solver.smoothed_loss:.4f}")

    scores = trainer.test_and_store_result(state, test_on_dev, counts=test_counts)
    acc = scores.get("accuracy", 0.0) / num_test_batches
    log.log(f"final accuracy {acc:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
