"""Test-score selection shared by the app drivers.

The reference logs every named test-net output (``solver.cpp:397-410``) and
the apps then report "accuracy" from the blob of that name
(``CifarApp.scala:113-115``).  Nets whose accuracy tops are named
differently (GoogLeNet aux heads emit ``loss1/top-1``-style names,
``caffe/models/bvlc_googlenet/train_val.prototxt``) must not silently score
0 — accuracy-like outputs are recognized by name pattern instead.
"""

from __future__ import annotations

from typing import Dict


def accuracy_keys(scores: Dict[str, float]):
    """Score names that are accuracies: 'accuracy', '*top-1', '*top-5',
    '*/accuracy*' — the zoo's naming conventions."""
    out = []
    for name in sorted(scores):
        low = name.lower()
        if "accuracy" in low or "top-1" in low or "top-5" in low:
            out.append(name)
    return out


def primary_accuracy(scores: Dict[str, float]) -> float:
    """The single headline accuracy: exact 'accuracy' if present, else the
    top-1-like output of the FINAL head (GoogLeNet's loss3), else the last
    accuracy-like name, else raise — never a silent 0.0."""
    if "accuracy" in scores:
        return scores["accuracy"]
    keys = accuracy_keys(scores)
    if not keys:
        raise KeyError(
            f"no accuracy-like test output among {sorted(scores)}; "
            "name one 'accuracy' or '*top-1'"
        )
    top1 = [k for k in keys if "top-5" not in k.lower()]
    return scores[(top1 or keys)[-1]]
