"""ImageNetApp — distributed ImageNet training driver (the flagship app).

Reference: ``src/main/scala/apps/ImageNetApp.scala`` — load tar shards from
the bucket, force-resize to 256x256, compute + broadcast the mean image,
then the parameter-averaging loop with tau=50 (``syncInterval``,
``:155``), testing every 10 rounds (``:118``), with per-image random-crop
(train) / center-crop (test) + mean-subtraction preprocessing closures
(``:128-180``).

TPU-native deltas:
- The preprocessing closures run on-device inside the jitted round
  (``sparknet_tpu.data.transforms``); minibatches cross host->device as
  uint8 at full 256x256.
- Broadcast + reduce of weights is the mesh collective inside
  ``ParameterAveragingTrainer.round`` — weights never visit the host.
- The mean image is computed in one streaming pass per partition and
  reduced (``ComputeMean`` semantics), then saved as mean.binaryproto.

Run:
    python -m sparknet_tpu.apps.imagenet_app --data=DIR --workers=4
(DIR holds tar shards + train.txt/val.txt; synthesizes JPEG shards when
--data is omitted)
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

TAU = 50  # reference: syncInterval = 50, ImageNetApp.scala:155
FULL_SIZE = 256  # fullHeight/fullWidth, ImageNetApp.scala:23-24
CROP_SIZE = 227  # croppedHeight/croppedWidth, ImageNetApp.scala:25-26


def load_minibatch_partitions(
    loader, prefix: str, labels_file: str, n_workers: int, batch: int,
    height: int, width: int, keep: slice = slice(None),
    epoch=None, shuffle_seed: int = 0,
):
    """Partition shards over workers and pack each partition into uint8
    minibatches (materialized — performance is best if the data fits in
    memory, same caveat as the reference app's .persist()).  ``keep``
    selects which workers' partitions to materialize — a multi-host run
    loads only its own block while every host agrees on the global
    partitioning.  ``epoch`` routes shard ownership through the
    cross-epoch shuffle-by-assignment service (``data/shuffle.py``);
    None keeps the legacy round-robin deal."""
    from sparknet_tpu.data import ScaleAndConvert

    conv = ScaleAndConvert(batch, height, width)
    parts = loader.partitions(
        prefix, labels_file, num_parts=n_workers,
        epoch=epoch, shuffle_seed=shuffle_seed,
    )
    out = []
    for w, part in enumerate(parts):
        if keep != slice(None) and not (keep.start <= w < keep.stop):
            continue
        mbs = list(conv.make_minibatches(part))
        out.append(mbs)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", default=None,
                        help="dir with tar shards + train.txt/val.txt")
    parser.add_argument("--train_prefix", default="train.")
    parser.add_argument("--test_prefix", default="val.")
    parser.add_argument("--train_labels", default="train.txt")
    parser.add_argument("--test_labels", default="val.txt")
    parser.add_argument("--model", default="alexnet",
                        help="alexnet | caffenet | googlenet | resnet50")
    parser.add_argument("--workers", type=int, default=0, help="0 = all devices")
    parser.add_argument("--rounds", type=int, default=10)
    parser.add_argument("--tau", type=int, default=0, help="0 = reference (50)")
    parser.add_argument("--test_every", type=int, default=10)
    parser.add_argument("--train_batch", type=int, default=0)
    parser.add_argument("--test_batch", type=int, default=0)
    parser.add_argument("--full_size", type=int, default=0)
    parser.add_argument("--crop", type=int, default=0)
    parser.add_argument("--classes", type=int, default=1000)
    parser.add_argument("--no_mirror", action="store_true")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--serial_feed", action="store_true",
        help="disable the pipelined round feed (assemble+H2D on the "
        "training loop) — for relay-degraded links (PERF.md)",
    )
    parser.add_argument(
        "--cache_dir", default=None,
        help="front the object store with the host-local content-"
        "addressed chunk cache rooted here (data/chunk_cache.py): "
        "epoch 1 fills it, later epochs read local disk — multi-epoch "
        "runs go I/O-flat (only meaningful when --data is a "
        "gs://|s3://|http(s)://|file:// url)",
    )
    parser.add_argument(
        "--cache_bytes", default="0",
        help="chunk-cache LRU byte budget, e.g. 512M / 8G "
        "(0 = unbounded)",
    )
    parser.add_argument(
        "--shuffle_epochs", type=int, default=0,
        help="split --rounds into N epochs and reshuffle shard->worker "
        "ownership between them via the seeded shuffle-by-assignment "
        "service (data/shuffle.py): a global reshuffle moves only the "
        "assignment table, and with --cache_dir the repeat reads hit "
        "the local cache (0/1 = single fixed assignment, the legacy "
        "behavior)",
    )
    from sparknet_tpu import obs
    from sparknet_tpu.io import journal as journal_mod
    from sparknet_tpu.parallel import comm, hierarchy

    obs.add_cli_args(parser)  # --obs / --obs_port / --trace_out
    comm.add_cli_args(parser)  # --compress / --overlap_avg
    hierarchy.add_cli_args(parser)  # --slices / --cross_slice_every / --elastic
    journal_mod.add_cli_args(parser)  # --journal / --no_journal / ...
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import config as cfg, models
    from sparknet_tpu.data import (
        ImageNetLoader,
        MinibatchSampler,
        RoundFeed,
        compute_mean,
        reduce_mean_sums,
        stack_windows,
        transforms,
        write_synthetic_imagenet,
    )
    from sparknet_tpu.apps.scores import primary_accuracy
    from sparknet_tpu.io.caffemodel import save_mean_image
    from sparknet_tpu.parallel import (
        ParameterAveragingTrainer,
        local_worker_slice,
        make_mesh,
        shard_leading_global,
    )
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils import TrainingLog

    distributed = jax.process_count() > 1
    log = TrainingLog(tag="imagenet", echo=jax.process_index() == 0)
    synthetic = args.data is None
    if synthetic:
        # scaled-down defaults so the offline demo fits one host
        args.train_batch = args.train_batch or 8
        args.test_batch = args.test_batch or 4
        args.tau = args.tau or 4
        args.full_size = args.full_size or 64
        args.crop = args.crop or 56
        args.classes = min(args.classes, 4)
        data_dir = tempfile.mkdtemp(prefix="imagenet_synth_")
        n_shards = max(2, args.workers or jax.local_device_count())
        write_synthetic_imagenet(
            data_dir, num_shards=n_shards,
            images_per_shard=args.train_batch * (args.tau + 1),
            classes=args.classes, seed=args.seed,
        )
        write_synthetic_imagenet(
            data_dir, num_shards=n_shards,
            images_per_shard=args.test_batch * 2, classes=args.classes,
            labels_file="val.txt", shard_prefix="val.", seed=args.seed + 1,
        )
        log.log(f"synthesized JPEG tar shards in {data_dir}")
    else:
        # reference constants (ImageNetApp.scala:20-26)
        args.train_batch = args.train_batch or 256
        args.test_batch = args.test_batch or 50
        args.tau = args.tau or TAU
        args.full_size = args.full_size or FULL_SIZE
        args.crop = args.crop or CROP_SIZE
        data_dir = args.data

    n_workers = args.workers or (
        jax.device_count() if distributed else jax.local_device_count()
    )
    if distributed and n_workers != jax.device_count():
        raise SystemExit("multi-host runs must use --workers == all devices")
    log.log(f"num workers: {n_workers}")

    mesh = make_mesh({"dp": n_workers}, devices=jax.devices()[:n_workers])
    mine = local_worker_slice(mesh) if distributed else slice(0, n_workers)

    from sparknet_tpu.data import chunk_cache

    loader = ImageNetLoader(
        data_dir,
        cache_dir=args.cache_dir,
        cache_bytes=chunk_cache.parse_bytes(args.cache_bytes),
    )
    if loader.cache is not None:
        log.log(
            f"chunk cache at {loader.cache.root} "
            f"(budget {loader.cache.byte_budget or 'unbounded'} bytes)"
        )
    # cross-epoch shuffle-by-assignment: --shuffle_epochs N splits the
    # run into N epochs; each epoch's shard->worker ownership is a
    # seeded permutation pure in (seed, epoch) — the reshuffle moves
    # only the assignment table, and repeat reads hit the chunk cache
    shuffle_on = args.shuffle_epochs > 1
    rounds_per_epoch = (
        -(-args.rounds // args.shuffle_epochs) if shuffle_on else None
    )

    def load_train_parts(epoch):
        return load_minibatch_partitions(
            loader, args.train_prefix, args.train_labels, n_workers,
            args.train_batch, args.full_size, args.full_size, keep=mine,
            epoch=epoch, shuffle_seed=args.seed,
        )

    log.log("loading train data")
    train_parts = load_train_parts(0 if shuffle_on else None)
    log.log("loading test data")
    test_parts = load_minibatch_partitions(
        loader, args.test_prefix, args.test_labels, n_workers,
        args.test_batch, args.full_size, args.full_size, keep=mine,
    )

    def global_sum(n: int) -> int:
        if not distributed:
            return n
        from jax.experimental import multihost_utils

        return int(multihost_utils.process_allgather(np.int64(n)).sum())

    num_train_mbs = global_sum(sum(len(p) for p in train_parts))
    log.log(f"numTrainMinibatches = {num_train_mbs}")
    num_test_mbs = global_sum(sum(len(p) for p in test_parts))
    log.log(f"numTestMinibatches = {num_test_mbs}")
    if min(len(p) for p in train_parts) < args.tau:
        raise SystemExit(
            f"every worker needs >= tau={args.tau} train minibatches; "
            f"partition sizes {[len(p) for p in train_parts]}"
        )
    if min(len(p) for p in test_parts) == 0:
        raise SystemExit(
            f"every worker needs >= 1 test minibatch; partition sizes "
            f"{[len(p) for p in test_parts]} (fewer val shards than "
            f"workers? reduce --workers or add shards)"
        )

    log.log("computing mean image")
    local_sums = [compute_mean(iter(p), return_sum=True) for p in train_parts]
    if distributed:
        # cross-host ComputeMean reduce: allgather every host's (sum,
        # count) partial (one image-sized accumulator per host).  The int64
        # sums ride as hi/lo int32 halves — allgather demotes int64 when
        # x64 is off, and count*255 can exceed int32 on big corpora.
        from jax.experimental import multihost_utils

        total = sum(s for s, _ in local_sums)
        count = sum(c for _, c in local_sums)
        hi = (total >> 20).astype(np.int32)
        lo = (total & ((1 << 20) - 1)).astype(np.int32)
        g_hi, g_lo, g_cnt = multihost_utils.process_allgather(
            (hi, lo, np.int32(count))
        )
        host_totals = (np.asarray(g_hi, np.int64) << 20) + np.asarray(
            g_lo, np.int64
        )
        mean = reduce_mean_sums(
            [(t, int(c)) for t, c in zip(host_totals, np.asarray(g_cnt))]
        )
    else:
        mean = reduce_mean_sums(local_sums)
    # a bucket/HTTP data root is not writable from here: the mean
    # artifact lands next to the cache (or a temp dir) instead
    from sparknet_tpu.data import object_store

    if object_store.is_object_store_url(data_dir):
        mean_dir = (
            loader.cache.root if loader.cache is not None
            else tempfile.mkdtemp(prefix="imagenet_mean_")
        )
    else:
        mean_dir = data_dir
    mean_path = os.path.join(mean_dir, "mean.binaryproto")
    save_mean_image(mean, mean_path)
    log.log(f"mean image -> {mean_path}")

    # per-worker samplers over that worker's partition (contiguous random
    # window of tau per round, MinibatchSampler semantics); seeds keyed by
    # GLOBAL worker index so a multi-host run draws like a 1-host run
    # (and by epoch, so a reshuffled epoch draws fresh windows)
    def build_samplers(parts, epoch=0):
        return [
            MinibatchSampler(
                {
                    "data": np.stack([mb[0] for mb in part]),
                    "label": np.stack(
                        [mb[1].astype(np.float32) for mb in part]
                    ),
                },
                num_sampled_batches=args.tau,
                seed=args.seed + mine.start + i + 7919 * epoch,
            )
            for i, part in enumerate(parts)
        ]

    samplers = build_samplers(train_parts)
    # test batches: heterogeneous per-worker counts, pad-and-mask — every
    # minibatch is scored even when val shards split unevenly
    test_batches, test_counts = ParameterAveragingTrainer.pad_partitions(
        [
            {
                "data": np.stack([mb[0] for mb in p]),
                "label": np.stack([mb[1].astype(np.float32) for mb in p]),
            }
            for p in test_parts
        ]
    )
    if distributed:
        # agree globally on the pad length and counts vector
        from jax.experimental import multihost_utils

        g_counts = multihost_utils.process_allgather(
            np.asarray(test_counts, np.int32)
        ).reshape(-1)
        nb_max = int(g_counts.max())
        if nb_max > test_batches["data"].shape[1]:
            pad = nb_max - test_batches["data"].shape[1]
            test_batches = {
                k: np.pad(v, [(0, 0), (0, pad)] + [(0, 0)] * (v.ndim - 2))
                for k, v in test_batches.items()
            }
        test_counts = g_counts
    num_test_used = int(np.asarray(test_counts).sum())
    del train_parts, test_parts  # samplers/test_batches hold the only copy

    # net: cropped feed shapes (replaceDataLayers, ImageNetApp.scala:103-104)
    from sparknet_tpu.models.builders import BUILDERS

    netp = (
        models.load_model(args.model, classes=args.classes)
        if args.model in BUILDERS  # prototxt-backed models take no kwargs
        else models.load_model(args.model)
    )
    netp = cfg.replace_data_layers(
        netp,
        [(args.train_batch, 3, args.crop, args.crop), (args.train_batch,)],
        [(args.test_batch, 3, args.crop, args.crop), (args.test_batch,)],
    )
    solver_param = models.load_model_solver(args.model).copy()
    solver = Solver(
        solver_param,
        net_param=netp,
        train_transform=transforms.train_transform(
            mean, args.crop, mirror=not args.no_mirror
        ),
        test_transform=transforms.test_transform(mean, args.crop),
    )

    # --health sentry (before the trainer: audit arity bakes into the
    # shard_map output spec); no snapshots here -> rollback = halt
    from sparknet_tpu.obs import health as health_mod

    sentry = health_mod.sentry_from_args(args, solver, echo=log.log)
    if getattr(args, "elastic", False):
        log.log(
            "--elastic: the membership controller is wired in "
            "cifar_app (this app applies the --slices/"
            "--cross_slice_every hierarchy schedule; preemption "
            "masking rides the fleet plane)"
        )
    trainer = hierarchy.averaging_trainer_from_args(
        args, solver, mesh, n_workers
    )
    state = trainer.init_state(seed=args.seed)
    test_on_dev = shard_leading_global(test_batches, mesh)
    log.log("finished setting up nets and weights")

    def evaluate(r=-1):
        scores = trainer.test_and_store_result(
            state, test_on_dev, counts=test_counts
        )
        for name in sorted(scores):  # solver.cpp:397-410 logs every output
            log.log(
                f"test output {name} = {scores[name] / max(1, num_test_used):.4f}",
                i=r,
            )
        return primary_accuracy(scores) / max(1, num_test_used)

    # pipelined round feed: the uint8 windows for round r+1 are stacked
    # into recycled buffers and device_put on a producer thread while
    # round r executes (--serial_feed restores the serial path)
    run_obs = obs.start_from_args(args, echo=log.log)
    # epoch switching runs on the feed's producer thread (assemble is
    # called once per round, in order): at an epoch boundary the shard
    # assignment re-deals and the partitions reload — through the chunk
    # cache those reloads are local-disk hits, overlapped under the
    # previous round's execute like any other assembly work
    sampler_state = {"epoch": 0, "samplers": samplers}

    def draw_windows(r):
        if shuffle_on:
            e = min(r // rounds_per_epoch, args.shuffle_epochs - 1)
            if e != sampler_state["epoch"]:
                parts = load_train_parts(e)
                if min(len(p) for p in parts) < args.tau:
                    raise RuntimeError(
                        f"epoch {e}: a worker's reshuffled partition has "
                        f"fewer than tau={args.tau} minibatches; sizes "
                        f"{[len(p) for p in parts]}"
                    )
                sampler_state["samplers"] = build_samplers(parts, e)
                sampler_state["epoch"] = e
                log.log(
                    f"epoch {e}: shard ownership reshuffled "
                    "(shuffle-by-assignment; repeat reads served by the "
                    "chunk cache)", i=r,
                )
        return [s.next_window for s in sampler_state["samplers"]]

    # timed_worker_windows: with --profile the per-worker draw times
    # feed the round profiler's straggler attribution
    feed = RoundFeed(
        lambda r, out: stack_windows(
            obs.profile.timed_worker_windows(r, draw_windows(r)),
            out,
        ),
        place=lambda host: shard_leading_global(host, mesh),
        pipelined=not args.serial_feed,
        num_rounds=args.rounds,
    )
    # --journal: the round ledger (io/journal.py).  This app keeps no
    # snapshots, so commits mark in-memory round completion only
    # (durable=False); the resume-capable drivers attach snapshot refs.
    jr = journal_mod.journal_from_args(args, "imagenet_run.journal")
    try:
        for r in range(args.rounds):
            if r % args.test_every == 0:  # test-then-train, ImageNetApp.scala:118
                # land any in-flight overlapped average before scoring
                state = trainer.finalize(state)
                log.log(f"{evaluate(r) * 100:.2f}% accuracy", i=r)
            log.log("training", i=r)
            if jr is not None:
                jr.begin_round(r, iter=r * args.tau, cursor=r)
            if sentry is not None:
                state, _ = sentry.guarded_round(
                    trainer, state, feed.next_round(r), round_index=r
                )
            else:
                state, _ = trainer.round(
                    state, feed.next_round(r), round_index=r
                )
            log.log(
                f"trained, smoothed_loss {solver.smoothed_loss:.4f}", i=r
            )
            if jr is not None:
                jr.commit_round(r, iter=(r + 1) * args.tau, durable=False)
        state = trainer.finalize(state)  # last round's average lands
        acc = evaluate()
        log.log(f"final accuracy {acc * 100:.2f}%")
        if jax.process_index() == 0:
            print(f"final accuracy {acc * 100:.2f}%")
        return 0
    except health_mod.SentryHalt as e:
        log.log(f"training halted by the health sentry: {e}")
        return 1
    finally:
        # telemetry closes AFTER the final-accuracy line so the JSONL
        # run log carries the run's headline result too
        if jr is not None:
            jr.close()
        feed.stop()
        run_obs.close()
        log.close()


if __name__ == "__main__":
    raise SystemExit(main())
