"""ImageNetCreateDBApp — phase 1 of the two-phase ImageNet DB path.

Reference: ``src/main/scala/apps/ImageNetCreateDBApp.scala:60-133`` —
load the tar shards, ScaleAndConvert to full-size uint8 minibatches,
coalesce to one partition per worker, write per-worker train/test
LevelDBs through the shim, record per-worker test batch counts in an
infoFile, and compute + save the mean image.  TPU-native deltas: the DBs
are the native runtime's record format (``runtime.write_datum_db``;
LMDB *reading* compat lives in ``io/lmdb.py``), images are stored
full-size so phase 2 can crop on device, and the infoFile holds every
worker's count (the reference's one-file-per-worker overwrite pattern
kept only the last).

Run:
    python -m sparknet_tpu.apps.imagenet_create_db_app --data=DIR \
        --out=DB_DIR --workers=4
(synthesizes JPEG tar shards when --data is omitted)
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile

import numpy as np

FULL_SIZE = 256  # fullHeight/fullWidth (ImageNetCreateDBApp.scala:26-27)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", default=None,
                        help="dir with tar shards + train.txt/val.txt")
    parser.add_argument("--out", default=None, help="output DB dir")
    parser.add_argument("--train_prefix", default="train.")
    parser.add_argument("--test_prefix", default="val.")
    parser.add_argument("--train_labels", default="train.txt")
    parser.add_argument("--test_labels", default="val.txt")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--train_batch", type=int, default=0)
    parser.add_argument("--test_batch", type=int, default=0)
    parser.add_argument("--full_size", type=int, default=0)
    parser.add_argument("--classes", type=int, default=1000)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from sparknet_tpu import runtime
    from sparknet_tpu.apps.imagenet_app import load_minibatch_partitions
    from sparknet_tpu.data import (
        ImageNetLoader,
        compute_mean,
        reduce_mean_sums,
        write_synthetic_imagenet,
    )
    from sparknet_tpu.io.caffemodel import save_mean_image
    from sparknet_tpu.utils import TrainingLog

    log = TrainingLog(tag="imagenet_create_db")
    synthetic = args.data is None
    if synthetic:
        args.train_batch = args.train_batch or 8
        args.test_batch = args.test_batch or 4
        args.full_size = args.full_size or 64
        args.classes = min(args.classes, 4)
        data_dir = tempfile.mkdtemp(prefix="imagenet_synth_")
        write_synthetic_imagenet(
            data_dir, num_shards=max(2, args.workers),
            images_per_shard=args.train_batch * 6, classes=args.classes,
            seed=args.seed,
        )
        write_synthetic_imagenet(
            data_dir, num_shards=max(2, args.workers),
            images_per_shard=args.test_batch * 2, classes=args.classes,
            labels_file="val.txt", shard_prefix="val.", seed=args.seed + 1,
        )
        log.log(f"synthesized JPEG tar shards in {data_dir}")
    else:
        args.train_batch = args.train_batch or 256
        args.test_batch = args.test_batch or 50
        args.full_size = args.full_size or FULL_SIZE
        data_dir = args.data

    out_dir = args.out or tempfile.mkdtemp(prefix="imagenet_dbs_")
    os.makedirs(out_dir, exist_ok=True)

    loader = ImageNetLoader(data_dir)
    log.log("processing train data")
    train_parts = load_minibatch_partitions(
        loader, args.train_prefix, args.train_labels, args.workers,
        args.train_batch, args.full_size, args.full_size,
    )
    num_train_mbs = sum(len(p) for p in train_parts)
    log.log(f"numTrainMinibatches = {num_train_mbs}")
    log.log("processing test data")
    test_parts = load_minibatch_partitions(
        loader, args.test_prefix, args.test_labels, args.workers,
        args.test_batch, args.full_size, args.full_size,
    )
    num_test_mbs = sum(len(p) for p in test_parts)
    log.log(f"numTestMinibatches = {num_test_mbs}")
    log.log(f"trainPartitionSizes = {[len(p) for p in train_parts]}")
    log.log(f"testPartitionSizes = {[len(p) for p in test_parts]}")

    log.log("write train data to DB")
    for w, part in enumerate(train_parts):
        path = os.path.join(out_dir, f"ilsvrc12_train_db_{w}.sndb")
        runtime.write_datum_db(
            path,
            np.concatenate([mb[0] for mb in part]),
            np.concatenate([mb[1] for mb in part]),
        )
    log.log("write test data to DB")
    for w, part in enumerate(test_parts):
        path = os.path.join(out_dir, f"ilsvrc12_val_db_{w}.sndb")
        runtime.write_datum_db(
            path,
            np.concatenate([mb[0] for mb in part]),
            np.concatenate([mb[1] for mb in part]),
        )

    # infoFile (imagenet_num_test_batches.txt role): per-worker test
    # batch counts + the shapes phase 2 needs
    info = {
        "workers": args.workers,
        "full_size": args.full_size,
        "classes": args.classes,
        "train_batch": args.train_batch,
        "test_batch": args.test_batch,
        "train_batches": [len(p) for p in train_parts],
        "test_batches": [len(p) for p in test_parts],
    }
    info_path = os.path.join(out_dir, "imagenet_db_info.json")
    with open(info_path, "w") as f:
        json.dump(info, f, indent=1)
    log.log(f"infoFile -> {info_path}")

    log.log("computing mean image")
    mean = reduce_mean_sums(
        [compute_mean(iter(p), return_sum=True) for p in train_parts]
    )
    mean_path = os.path.join(out_dir, "imagenet_mean.binaryproto")
    save_mean_image(mean, mean_path)
    log.log(f"mean image -> {mean_path}")
    log.log("finished creating databases")
    print(out_dir)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
