"""Training-driver apps — the L1 layer (reference: ``src/main/scala/apps``)."""
