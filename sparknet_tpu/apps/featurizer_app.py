"""FeaturizerApp — batch feature extraction.

Reference: ``src/main/scala/apps/FeaturizerApp.scala:88-103`` — broadcast
weights once, forward each minibatch, pull a named blob back as an NDArray.
Here ``JaxNet.forward`` returns every blob, so the tap is a dict lookup.

Run:
    python -m sparknet_tpu.apps.featurizer_app --model=NAME --blob=ip1 \
        [--weights=F.caffemodel] [--batches=4] [--out=features.npz]
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="cifar10_full")
    parser.add_argument("--blob", default="ip1")
    parser.add_argument("--weights", default=None)
    parser.add_argument("--batches", type=int, default=4)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import models
    from sparknet_tpu.io import caffemodel
    from sparknet_tpu.net import JaxNet

    netp = (
        models.load_model(args.model)
        if not args.model.endswith(".prototxt")
        else __import__("sparknet_tpu.config", fromlist=["load_net_prototxt"])
        .load_net_prototxt(args.model)
    )
    net = JaxNet(netp, phase="TEST")
    params, stats = net.init(0)
    if args.weights:
        loaded = caffemodel.load_weights(args.weights)
        params, stats = caffemodel.apply_blobs(net, params, stats, loaded)

    rng = np.random.RandomState(0)
    feats = []
    fwd = jax.jit(net.forward)
    for i in range(args.batches):
        batch = {}
        for blob in net.feed_blobs:
            shape = net.blob_shapes[blob]
            batch[blob] = (
                rng.randint(0, 10, shape).astype(np.float32)
                if "label" in blob
                else rng.randn(*shape).astype(np.float32)
            )
        blobs = fwd(params, stats, batch)
        if args.blob not in blobs:
            raise SystemExit(
                f"blob {args.blob!r} not in net; have {sorted(blobs)}"
            )
        feats.append(np.asarray(blobs[args.blob]))
    features = np.stack(feats)
    print(f"extracted {args.blob}: {features.shape}")
    if args.out:
        np.savez(args.out, features=features)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
