"""FeaturizerApp — batch feature extraction.

Reference: ``src/main/scala/apps/FeaturizerApp.scala:88-103`` — broadcast
weights once, forward each minibatch, pull a named blob back as an NDArray.
Here ``JaxNet.forward`` returns every blob, so the tap is a dict lookup.

Run:
    python -m sparknet_tpu.apps.featurizer_app --model=NAME --blob=ip1 \
        --data=DIR|DB [--weights=F.caffemodel] [--batches=4] \
        [--out=features.npz]
(real minibatches come from --data or the net's Data-layer source;
--allow_synthetic featurizes random batches for smoke tests only)
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="cifar10_full")
    parser.add_argument("--blob", default="ip1")
    parser.add_argument("--weights", default=None)
    parser.add_argument("--data", default=None,
                        help="CIFAR binary dir or SNDB path")
    parser.add_argument("--allow_synthetic", action="store_true",
                        help="smoke-test only: featurize random batches")
    parser.add_argument("--batches", type=int, default=4)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu import models
    from sparknet_tpu.data.source import resolve_batches
    from sparknet_tpu.io import caffemodel
    from sparknet_tpu.net import JaxNet

    netp = (
        models.load_model(args.model)
        if not args.model.endswith(".prototxt")
        else __import__("sparknet_tpu.config", fromlist=["load_net_prototxt"])
        .load_net_prototxt(args.model)
    )
    net = JaxNet(netp, phase="TEST")
    params, stats = net.init(0)
    if args.weights:
        loaded = caffemodel.load_weights(args.weights)
        params, stats = caffemodel.apply_blobs(net, params, stats, loaded)

    # real minibatches (FeaturizerApp.scala:88-103 pulls from the RDD)
    stacked = resolve_batches(
        net, netp, args.data, args.batches, phase="TEST",
        allow_synthetic=args.allow_synthetic,
    )
    feats = []
    fwd = jax.jit(net.forward)
    for i in range(args.batches):
        batch = {k: v[i] for k, v in stacked.items()}
        blobs = fwd(params, stats, batch)
        if args.blob not in blobs:
            raise SystemExit(
                f"blob {args.blob!r} not in net; have {sorted(blobs)}"
            )
        feats.append(np.asarray(blobs[args.blob]))
    features = np.stack(feats)
    print(f"extracted {args.blob}: {features.shape}")
    if args.out:
        if args.out.endswith((".h5", ".hdf5")):
            # the HDF5Output layer's role (``hdf5_output_layer.cpp``
            # writes tapped blobs as named datasets): activation taps
            # export in the interchange format
            import h5py

            with h5py.File(args.out, "w") as h:
                h[args.blob] = features
            print(f"wrote {args.out} (HDF5, dataset {args.blob!r})")
        else:
            np.savez(args.out, features=features)
            print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
