"""LMApp — sequence-parallel language modeling on the averaging stack.

The first non-CNN workload (ROADMAP scenario diversity): a byte-level
decoder-only transformer (``models/transformer_lm.py``) trained by the
SAME ``ParameterAveragingTrainer`` / RoundFeed / obs / health /
journal / elastic machinery every CIFAR/ImageNet app uses — proving
the stack is SparkNet-class for sequence models, not just Caffe-era
convnets.

Mesh layout: ``dp x sp``.  The ``dp`` axis is the familiar worker
axis (tau local steps, then parameter averaging); ``--sp N`` addition-
ally shards every worker's SEQUENCE dimension N ways — attention runs
the ``parallel/ring_attention.py`` construction inside the round's
``shard_map`` (KV rotating one ICI hop per ring step), gradients psum
over the ring (``Solver(grad_reduce_axes=("sp",))``), and the
trajectory matches the sp=1 run up to float associativity (pinned by
``bench.py --mode=lm``).

Data: documents fetched through ``object_store`` + ``ChunkCache``
(``data/text.py``), windows drawn by absolute-iteration cursor — the
journal's round intents carry the text cursor, ``.jobstate.npz``
carries it beside the per-worker momentum stacks, and ``--resume`` is
journal-guided and BIT-IDENTICAL (the window sequence never skips or
replays; ``tests/test_lm.py`` kills and resumes to prove it).

Run:
    python -m sparknet_tpu.apps.lm_app --rounds 20 --sp 2
(synthesizes a seeded corpus and serves it through a file:// chunk
cache when --corpus is omitted)
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

TAU = 4


def add_lm_model_args(parser) -> None:
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--depth", type=int, default=2)
    parser.add_argument("--heads", type=int, default=2)
    parser.add_argument("--base_lr", type=float, default=0.1)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--weight_decay", type=float, default=1e-4)
    parser.add_argument(
        "--dense_attention", action="store_true",
        help="train with the dense XLA attention reference instead of "
        "the Pallas flash kernel (the kernel is the default wherever it "
        "lowers natively — ops/pallas_attention.lowerable(); this flag "
        "is the explicit fallback, and the A/B lever for KERNELS_r21)",
    )


def build_lm_solver(args, sp: int):
    """(TransformerLM, Solver) from parsed args — shared with ``cli
    train --lm`` and the bench."""
    from sparknet_tpu import models
    from sparknet_tpu.config import parse_solver_prototxt
    from sparknet_tpu.solver import Solver

    lm = models.build_transformer_lm(
        dim=args.dim,
        depth=args.depth,
        heads=args.heads,
        seq_len=args.seq_len,
        sp_axis="sp" if sp > 1 else None,
        sp_size=sp,
        # --dense_attention is the explicit fallback; the default
        # ("auto") rides the Pallas flash kernel wherever it lowers
        # natively (getattr: bench Namespaces predate the flag)
        attention=(
            "dense" if getattr(args, "dense_attention", False) else "auto"
        ),
    )
    solver_param = parse_solver_prototxt(
        f"base_lr: {args.base_lr} "
        'lr_policy: "fixed" '
        f"momentum: {args.momentum} "
        f"weight_decay: {args.weight_decay} "
        "average_loss: 20"
    )
    solver = Solver(
        solver_param,
        net=lm,
        grad_reduce_axes=("sp",) if sp > 1 else (),
    )
    from sparknet_tpu import obs
    from sparknet_tpu.ops import pallas_attention

    tm = obs.training_metrics()
    if tm is not None:
        on_kernel = lm.attention == "flash" or (
            lm.attention == "auto" and pallas_attention.lowerable()
        )
        tm.kernel_path.labels("attention").set(1.0 if on_kernel else 0.0)
    return lm, solver


def lm_batch_spec(sp: int):
    """The round-batch partition specs: worker-major over dp, sequence
    over the sp ring — the trainers' ``batch_spec`` generalization."""
    from jax.sharding import PartitionSpec as P

    if sp <= 1:
        return None
    spec = P("dp", None, None, "sp")
    return {"tokens": spec, "targets": spec}


def lm_batch_sharding(mesh, sp: int):
    """Matching placement pytree for RoundFeed's producer-thread put."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("dp", None, None, "sp") if sp > 1 else P("dp")
    s = NamedSharding(mesh, spec)
    return {"tokens": s, "targets": s}


def resume_lm_job(solver, trainer, mesh, prefix, jr, sampler=None,
                  tau: int = TAU):
    """Journal-guided full-job-state resume (the recover.py recipe on
    the LM): rewind to the last COMMITTED boundary, broadcast the
    consensus params, put back per-worker momentum stacks, comm EF
    residuals and the sentry EMA from ``.jobstate.npz``, and verify
    the text cursor's corpus geometry.  Returns ``(state, start_round,
    job_state, info)`` — state None means nothing restorable (start
    fresh at round 0)."""
    import jax

    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.parallel import restore_worker_history

    state = js = info = None
    if jr is not None:
        if jr.last_committed_round is None:
            # a ledger with no committed boundary: the reconciler's
            # rule says round 0 (and any snapshot a torn first
            # boundary published for an UNCOMMITTED round) must be
            # ignored — start fresh and re-execute from round 0,
            # never consume a snapshot the ledger does not vouch for
            return None, 0, None, jr.reconcile()
        st, used, js, info = checkpoint.restore_newest_valid_journaled(
            solver, prefix, jr
        )
    else:
        try:
            st, used = checkpoint.restore_newest_valid(solver, prefix)
        except FileNotFoundError:
            return None, 0, None, None
    state = trainer.broadcast_state(st)  # resets the comm plane
    start_round = (
        info["resume_round"]
        if info is not None
        else int(np.asarray(jax.device_get(st.iter))) // max(1, tau)
    )
    if js:
        if "comm" in js:
            trainer.restore_comm_state(js["comm"])
        if "workers" in js:
            # per-worker momentum: the consensus snapshot carries
            # worker 0's history only; the true stacks ride jobstate
            state = restore_worker_history(state, js["workers"], mesh)
        if sampler is not None and "cursor" in js and isinstance(
            js["cursor"], dict
        ) and "text_iter" in js["cursor"]:
            sampler.verify_cursor(js["cursor"])
    return state, start_round, js, info


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--corpus", default=None,
        help="text corpus root: a directory or any object-store URL "
        "(gs:// s3:// http(s):// file://) — *.txt documents are "
        "fetched through the chunk cache; omitted = a seeded "
        "synthetic corpus served through a file:// cache",
    )
    parser.add_argument(
        "--cache_dir", default=None,
        help="chunk-cache root for an object-store --corpus; pass a "
        "STABLE path to make re-runs I/O-free (default: a temp dir — "
        "verified fetches, but no cross-run reuse)",
    )
    parser.add_argument("--cache_bytes", default=0)
    parser.add_argument(
        "--workers", type=int, default=0,
        help="dp worker count (0 = devices // sp)",
    )
    parser.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel ring width: each dp worker's sequence "
        "dim shards --sp ways and attention runs the ring "
        "construction (parallel/ring_attention.py).  Needs "
        "workers x sp devices and seq_len %% sp == 0",
    )
    parser.add_argument("--rounds", type=int, default=40)
    parser.add_argument("--tau", type=int, default=TAU)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--log_every", type=int, default=5)
    parser.add_argument(
        "--serial_feed", action="store_true",
        help="disable the pipelined round feed (assemble+H2D on the "
        "training loop) — for relay-degraded links (PERF.md)",
    )
    parser.add_argument(
        "--snapshot_prefix", default=None,
        help="snapshot path prefix; with --snapshot_every, every k-th "
        "round boundary publishes a full-job-state snapshot "
        "(params + per-worker momentum + comm residuals + sentry + "
        "text cursor) the journal's commit references",
    )
    parser.add_argument("--snapshot_every", type=int, default=0)
    parser.add_argument(
        "--resume", action="store_true",
        help="journal-guided resume from --snapshot_prefix: rewind to "
        "the last committed round, restore the full job state, "
        "continue bit-identically (windows never skip or replay)",
    )
    add_lm_model_args(parser)
    from sparknet_tpu import obs
    from sparknet_tpu.io import journal as journal_mod
    from sparknet_tpu.parallel import comm, hierarchy

    obs.add_cli_args(parser)
    comm.add_cli_args(parser)
    hierarchy.add_cli_args(parser)
    journal_mod.add_cli_args(parser)
    args = parser.parse_args(argv)

    import jax

    from sparknet_tpu.data import (
        RoundFeed,
        TextWindowSampler,
        load_corpus,
        stack_windows,
        write_synthetic_corpus,
    )
    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.obs import health as health_mod
    from sparknet_tpu.parallel import first_worker, make_mesh
    from sparknet_tpu.utils import SignalHandler, SolverAction, TrainingLog

    sp = max(1, args.sp)
    if args.seq_len % sp:
        raise SystemExit(
            f"lm: --seq_len {args.seq_len} must divide by --sp {sp} "
            "(the ring rotates equal sequence shards)"
        )
    if args.resume and not args.snapshot_prefix:
        raise SystemExit("lm: --resume needs --snapshot_prefix")
    n_workers = args.workers or max(1, jax.local_device_count() // sp)
    need = n_workers * sp
    if jax.local_device_count() < need:
        raise SystemExit(
            f"lm: dp={n_workers} x sp={sp} needs {need} devices, jax "
            f"sees {jax.local_device_count()}"
        )
    log = TrainingLog(tag="lm")
    axes = {"dp": n_workers, "sp": sp} if sp > 1 else {"dp": n_workers}
    mesh = make_mesh(axes, devices=jax.devices()[:need])
    log.log(f"mesh: dp={n_workers} sp={sp} ({need} devices)")

    corpus_root = args.corpus
    if corpus_root is None:
        synth = tempfile.mkdtemp(prefix="lm_synth_corpus_")
        write_synthetic_corpus(synth, seed=args.seed)
        # even the synthetic corpus goes through object_store + the
        # chunk cache: the LM data path IS the verified-fetch path
        corpus_root = "file://" + synth
        log.log(f"synthesized corpus at {corpus_root}")
    docs = load_corpus(
        corpus_root, cache_dir=args.cache_dir, cache_bytes=args.cache_bytes
    )
    log.log(f"corpus: {len(docs)} documents, "
            f"{sum(len(d) for d in docs)} bytes")

    lm, solver = build_lm_solver(args, sp)
    log.log(
        f"model: dim={args.dim} depth={args.depth} heads={args.heads} "
        f"seq_len={args.seq_len} ({lm.num_params()} params)"
    )
    prefix = args.snapshot_prefix
    sentry = health_mod.sentry_from_args(args, solver, echo=log.log)
    spec = hierarchy.spec_from_args(args, n_workers)
    trainer = hierarchy.averaging_trainer_from_args(
        args, solver, mesh, n_workers,
        hierarchy=spec, batch_spec=lm_batch_spec(sp),
    )
    if sentry is not None and prefix:
        sentry.restore_fn = health_mod.make_restore_fn(
            solver, prefix, trainer=trainer
        )

    # --elastic: membership views drive the round's live_mask; SIGTERM
    # marks this process's slice leaving at the next boundary and
    # AutoRejoin requests readmission (the cifar_app contract, riding
    # the LM unchanged)
    membership_ctl = None
    auto_rejoin = None
    if args.elastic:
        from sparknet_tpu.runtime import membership as membership_mod

        membership_ctl = membership_mod.MembershipController(
            spec
            if spec is not None
            else hierarchy.HierarchySpec.flat(n_workers),
            echo=log.log,
        )
        my_slice = int(
            os.environ.get(
                "SPARKNET_SLICE_ID", membership_ctl.spec.num_slices - 1
            )
        )
        membership_ctl.sigterm_marks(my_slice)
        auto_rejoin = membership_mod.AutoRejoin(
            membership_ctl, args.rejoin_after
        )
        obs.set_membership(membership_ctl)

    # one joined corpus stream, shared by every dp worker's cursor
    base_sampler = TextWindowSampler(
        docs, args.seq_len, args.batch, seed=args.seed, worker=0
    )
    samplers = [base_sampler.for_worker(w) for w in range(n_workers)]
    run_obs = obs.start_from_args(args, echo=log.log)
    jr = journal_mod.journal_from_args(
        args,
        (journal_mod.default_journal_path(prefix)
         if prefix else "lm_run.journal"),
        resuming=args.resume,
    )

    start_round = 0
    state = None
    if args.resume:
        if jr is None and not checkpoint.find_snapshots(prefix):
            # the imagenet_run_db_app loud-failure contract: a typo'd
            # prefix must not silently retrain the whole run from 0
            raise SystemExit(
                f"lm: --resume found no ledger and no snapshots under "
                f"{prefix!r}"
            )
        state, start_round, js, info = resume_lm_job(
            solver, trainer, mesh, prefix, jr, sampler=samplers[0],
            tau=args.tau,
        )
        if state is not None:
            if sentry is not None and js and "sentry" in js:
                sentry.load_state(js["sentry"])
            if membership_ctl is not None and js and "membership" in js:
                # the epoch clock never rewinds across restart (the
                # journaled-state inventory invariant): the restored
                # roster keeps departed slots walking the rejoin path
                membership_ctl.load_state(js["membership"])
            log.log(
                f"resumed at round {start_round} "
                f"(iter {start_round * args.tau})"
            )
            if info is not None and info.get("in_flight_round") is not None:
                tm = obs.training_metrics()
                if tm is not None:
                    tm.recover_replayed.inc()
                log.log(
                    "journal: round %d was in flight at the crash — it "
                    "re-executes" % info["in_flight_round"]
                )
        else:
            # a ledger with no committed boundary: the reconciled
            # decision IS a fresh start (round 0 re-executes; any
            # snapshot from a torn first boundary stays ignored)
            log.log(
                "journal: no committed round — starting fresh at "
                "round 0"
            )
    if state is None:
        trainer.reset_comm_state()
        state = trainer.init_state(seed=args.seed)
    if start_round >= args.rounds:
        log.log(f"run already complete at round {start_round}")
        if membership_ctl is not None:
            membership_ctl.detach()
        run_obs.close()
        if jr is not None:
            jr.close()
        log.close()
        return 0

    tokens_per_round = n_workers * args.tau * args.batch * args.seq_len
    ring_bytes_per_round = (
        lm.ring_hop_bytes_per_iter(args.batch) * args.tau * n_workers
    )

    def assemble(r, out):
        # the per-round draw is a pure function of the absolute round
        # (resume-aware cursors); the span makes text sampling visible
        # in traces beside assemble/h2d
        with obs.span("sample_text", cat="data", round=r):
            windows = obs.profile.timed_worker_windows(
                r,
                [
                    (lambda s=s: s.window_for_round(r, args.tau))
                    for s in samplers
                ],
            )
        return stack_windows(windows, out)

    feed = RoundFeed(
        assemble,
        sharding=lm_batch_sharding(mesh, sp),
        pipelined=not args.serial_feed,
        start_round=start_round,
        num_rounds=args.rounds - start_round,
    )

    def job_extra(r: int):
        it = (r + 1) * args.tau
        import jax as _jax

        from sparknet_tpu.parallel import export_worker_history

        host_state = _jax.device_get(state)
        extra = {
            "cursor": samplers[0].cursor_for_iter(it),
            # per-worker momentum stacks — the shared jobstate recipe
            # (one implementation with runtime/recover.py)
            "workers": export_worker_history(host_state),
        }
        if sentry is not None:
            extra["sentry"] = sentry.export_state()
        if membership_ctl is not None:
            extra["membership"] = membership_ctl.export_state()
        comm_state = trainer.export_comm_state()
        if comm_state is not None:
            extra["comm"] = comm_state
        return extra, first_worker(host_state)

    try:
        with SignalHandler(
            sigint_effect=SolverAction.NONE,
            sighup_effect=SolverAction.NONE,
            sigterm_hooks=membership_ctl is not None,
        ):
            for r in range(start_round, args.rounds):
                if jr is not None:
                    jr.begin_round(
                        r,
                        iter=r * args.tau,
                        cursor=samplers[0].cursor_for_iter(r * args.tau),
                        view_epoch=(
                            membership_ctl.view.epoch
                            if membership_ctl is not None
                            else 0
                        ),
                    )
                mask = None
                if membership_ctl is not None:
                    membership_ctl.advance(r)
                    auto_rejoin.on_round(r)
                    if membership_ctl.pending_joiners():
                        from sparknet_tpu.runtime import (
                            membership as membership_mod,
                        )

                        state, _ = membership_mod.readmit_from_survivors(
                            trainer, state, membership_ctl, r,
                            echo=log.log,
                        )
                    mask = membership_ctl.live_mask()
                    if not mask.any():
                        log.log(
                            f"round {r}: no live workers in the "
                            "membership view; stopping"
                        )
                        break
                if sentry is not None:
                    state, _ = sentry.guarded_round(
                        trainer, state, feed.next_round(r),
                        live_mask=mask, round_index=r,
                    )
                else:
                    state, _ = trainer.round(
                        state, feed.next_round(r),
                        live_mask=mask, round_index=r,
                    )
                tm = obs.training_metrics()
                if tm is not None:
                    # elastic degradation shows up in the counters: a
                    # masked (departed) worker trains no tokens and
                    # moves no ring bytes this round
                    frac = (
                        1.0
                        if mask is None
                        else float(np.sum(mask)) / n_workers
                    )
                    tm.lm_tokens.inc(int(tokens_per_round * frac))
                    if ring_bytes_per_round:
                        tm.lm_ring_bytes.inc(
                            int(ring_bytes_per_round * frac)
                        )
                if r % max(1, args.log_every) == 0 or r == args.rounds - 1:
                    log.log(
                        f"round {r} smoothed_loss "
                        f"{solver.smoothed_loss:.4f}"
                    )
                snapshots_armed = bool(prefix and args.snapshot_every)
                snap_due = (
                    snapshots_armed
                    and (r + 1) % args.snapshot_every == 0
                )
                if snap_due:
                    extra, consensus = job_extra(r)
                    _, state_path = checkpoint.snapshot(
                        solver, consensus, prefix,
                        fmt="BINARYPROTO", extra_state=extra,
                    )
                    if jr is not None:
                        jr.commit_round(
                            r,
                            iter=(r + 1) * args.tau,
                            snapshot=os.path.basename(state_path),
                        )
                elif jr is not None and not prefix:
                    # progress-only ledger (NO snapshot prefix — the
                    # cifar_app contract, resume impossible by
                    # construction): commits mark in-memory completion
                    # for postmortems.  With a prefix set, rounds
                    # without a published snapshot must stay
                    # UNCOMMITTED: the reconciler treats every commit
                    # as a durable boundary, so a commit the restore
                    # path cannot rewind to would make --resume SKIP
                    # rounds (snapshot_every > 1) or crash claiming
                    # durable work vanished (snapshot_every == 0) —
                    # uncommitted rounds instead re-execute
                    # deterministically off the absolute-iter cursor.
                    jr.commit_round(
                        r, iter=(r + 1) * args.tau, durable=False
                    )
        state = trainer.finalize(state)
        log.log(f"final smoothed_loss {solver.smoothed_loss:.4f}")
        return 0
    except health_mod.SentryHalt as e:
        log.log(f"training halted by the health sentry: {e}")
        return 1
    finally:
        if membership_ctl is not None:
            membership_ctl.detach()
        if jr is not None:
            jr.close()
        feed.stop()
        run_obs.close()
        log.close()


if __name__ == "__main__":
    raise SystemExit(main())
