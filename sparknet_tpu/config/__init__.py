"""Config system: proto2-text-compatible net/solver definitions.

Replaces the reference's protobuf config plane (``caffe.proto`` schema +
``ProtoLoader.scala`` + ``ccaffe.cpp:275-304`` parsing services) with typed
dataclasses and a native prototxt parser.
"""

from sparknet_tpu.config.schema import *  # noqa: F401,F403
from sparknet_tpu.config import schema as _schema
from sparknet_tpu.config.prototext import parse, parse_file, dumps, ParseError
from sparknet_tpu.config.schema import (
    NetParameter,
    SolverParameter,
    LayerParameter,
    NetState,
)


def parse_net_prototxt(text: str, permissive: bool = False) -> NetParameter:
    """Parse net prototxt text (reference: ``ProtoLoader.loadNetPrototxt``,
    src/main/scala/libs/ProtoLoader.scala:20-29)."""
    return parse(text, NetParameter, permissive=permissive)


def parse_solver_prototxt(text: str, permissive: bool = False) -> SolverParameter:
    return parse(text, SolverParameter, permissive=permissive)


def load_net_prototxt(path: str, permissive: bool = False) -> NetParameter:
    return parse_file(path, NetParameter, permissive=permissive)


def load_solver_prototxt(path: str, permissive: bool = False) -> SolverParameter:
    solver = parse_file(path, SolverParameter, permissive=permissive)
    # net paths resolve like the reference's (relative to cwd), with a
    # fallback to the solver file's own directory so zoo configs work from
    # any cwd
    import os

    base = os.path.dirname(os.path.abspath(path))

    def resolve(p):
        if p and not os.path.isabs(p) and not os.path.exists(p):
            cand = os.path.join(base, p)
            if os.path.exists(cand):
                return cand
        return p

    solver.net = resolve(solver.net)
    solver.train_net = resolve(solver.train_net)
    solver.test_net = [resolve(p) for p in solver.test_net]
    return solver


def load_solver_prototxt_with_net(
    solver_path: str, net_path: str, keep_snapshot: bool = False
) -> SolverParameter:
    """Load a solver and embed the net definition inline, clearing snapshot
    config unless asked otherwise (reference: ``ProtoLoader.
    loadSolverPrototxtWithNet``, src/main/scala/libs/ProtoLoader.scala:31-43 —
    SparkNet drivers own checkpointing, so file-based solver snapshots are
    disabled by default)."""
    solver = load_solver_prototxt(solver_path)
    solver.net = None
    solver.train_net = None
    solver.test_net = []
    solver.net_param = load_net_prototxt(net_path)
    if not keep_snapshot:
        solver.snapshot = 0
        solver.snapshot_prefix = ""
    return solver


def resolve_solver_net(solver: SolverParameter) -> NetParameter:
    """The solver's net definition, whichever field carries it (inline
    ``net_param``/``train_net_param`` or the ``net``/``train_net`` file
    path) — ``Solver::InitTrainNet``'s resolution order."""
    netp = solver.net_param or solver.train_net_param
    if netp is not None:
        return netp
    path = solver.net or solver.train_net
    if path is None:
        raise ValueError("solver has no net definition")
    return load_net_prototxt(path)


def replace_data_layers(
    net: NetParameter,
    train_batch_shapes,
    test_batch_shapes=None,
) -> NetParameter:
    """Swap leading data layers for host-fed ``HostData`` layers (reference:
    ``ProtoLoader.replaceDataLayers``, src/main/scala/libs/ProtoLoader.scala:
    50-57, which swaps in JavaData ``RDDLayer``s).

    ``train_batch_shapes``/``test_batch_shapes`` are lists of shapes, one per
    top blob of the data layer (typically ``[(N,C,H,W), (N,)]`` for
    data+label).
    """
    from sparknet_tpu.config.schema import BlobShape, JavaDataParameter, NetStateRule

    net = net.copy()
    data_types = {
        "Data",
        "ImageData",
        "HDF5Data",
        "MemoryData",
        "DummyData",
        "WindowData",
        "JavaData",
        "HostData",
        "Input",
    }
    kept = [l for l in net.layer if l.type not in data_types]
    tops = None
    for l in net.layer:
        if l.type in data_types:
            tops = list(l.top)
            break
    if tops is None:
        tops = ["data", "label"]

    def mk(phase, shapes):
        return LayerParameter(
            name=f"{'train' if phase == 'TRAIN' else 'test'}_data",
            type="HostData",
            top=list(tops[: len(shapes)]),
            include=[NetStateRule(phase=phase)],
            java_data_param=JavaDataParameter(
                shape=[BlobShape(dim=list(map(int, s))) for s in shapes]
            ),
        )

    new_layers = [mk("TRAIN", train_batch_shapes)]
    if test_batch_shapes is not None:
        new_layers.append(mk("TEST", test_batch_shapes))
    net.layer = new_layers + kept
    return net
