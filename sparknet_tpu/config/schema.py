"""Typed configuration schema compatible with Caffe's proto2 config language.

This is the framework's config system (reference: ``caffe/src/caffe/proto/
caffe.proto`` — NetParameter at :64, SolverParameter at :102, LayerParameter
at :310).  Instead of protobuf codegen we model the messages as plain typed
dataclasses; ``sparknet_tpu.config.prototext`` binds proto2 text-format files
(.prototxt) to these classes and prints them back.

Only proto2 *text* compatibility is promised (that is what the reference
ships around: every net/solver in the repo is a .prototxt).  Field names,
defaults, and enum literals match the reference schema so its configs parse
unchanged.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional

# ---------------------------------------------------------------------------
# Message base
# ---------------------------------------------------------------------------


class Message:
    """Base marker for config messages (bound by the prototext module)."""

    def copy(self):
        return dataclasses.replace(
            self,
            **{
                f.name: _deep_copy(getattr(self, f.name))
                for f in dataclasses.fields(self)
            },
        )


def _deep_copy(v):
    if isinstance(v, Message):
        return v.copy()
    if isinstance(v, list):
        return [_deep_copy(x) for x in v]
    return v


# ---------------------------------------------------------------------------
# Basic shared messages
# ---------------------------------------------------------------------------


@dataclass
class BlobShape(Message):
    """N-D shape (reference: caffe.proto ``BlobShape``)."""

    dim: List[int] = field(default_factory=list)


@dataclass
class BlobProto(Message):
    """Serialized tensor; used for weights and mean images."""

    shape: Optional[BlobShape] = None
    data: List[float] = field(default_factory=list)
    diff: List[float] = field(default_factory=list)
    # legacy 4-D dimensions
    num: int = 0
    channels: int = 0
    height: int = 0
    width: int = 0


@dataclass
class FillerParameter(Message):
    """Weight-initializer config (reference: ``include/caffe/filler.hpp``)."""

    type: str = "constant"
    value: float = 0.0
    min: float = 0.0
    max: float = 1.0
    mean: float = 0.0
    std: float = 1.0
    sparse: int = -1
    variance_norm: str = "FAN_IN"  # FAN_IN | FAN_OUT | AVERAGE


@dataclass
class NetStateRule(Message):
    """Phase/level/stage inclusion rule (reference: caffe.proto:267-281)."""

    phase: Optional[str] = None  # TRAIN | TEST
    min_level: Optional[int] = None
    max_level: Optional[int] = None
    stage: List[str] = field(default_factory=list)
    not_stage: List[str] = field(default_factory=list)


@dataclass
class NetState(Message):
    phase: str = "TEST"
    level: int = 0
    stage: List[str] = field(default_factory=list)


@dataclass
class ParamSpec(Message):
    """Per-parameter training config incl. sharing (caffe.proto:283-308)."""

    name: Optional[str] = None
    share_mode: Optional[str] = None  # STRICT | PERMISSIVE
    lr_mult: float = 1.0
    decay_mult: float = 1.0


# ---------------------------------------------------------------------------
# Per-layer parameter messages (caffe.proto:310-1043)
# ---------------------------------------------------------------------------


@dataclass
class TransformationParameter(Message):
    scale: float = 1.0
    mirror: bool = False
    crop_size: int = 0
    mean_file: Optional[str] = None
    mean_value: List[float] = field(default_factory=list)
    force_color: bool = False
    force_gray: bool = False


@dataclass
class LossParameter(Message):
    ignore_label: Optional[int] = None
    normalization: str = "VALID"  # FULL | VALID | BATCH_SIZE | NONE
    normalize: Optional[bool] = None  # deprecated alias


@dataclass
class AccuracyParameter(Message):
    top_k: int = 1
    axis: int = 1
    ignore_label: Optional[int] = None


@dataclass
class ArgMaxParameter(Message):
    out_max_val: bool = False
    top_k: int = 1
    axis: Optional[int] = None


@dataclass
class ConcatParameter(Message):
    axis: int = 1
    concat_dim: Optional[int] = None  # legacy


@dataclass
class BatchNormParameter(Message):
    use_global_stats: Optional[bool] = None
    moving_average_fraction: float = 0.999
    eps: float = 1e-5


@dataclass
class BiasParameter(Message):
    axis: int = 1
    num_axes: int = 1
    filler: Optional[FillerParameter] = None


@dataclass
class ScaleParameter(Message):
    axis: int = 1
    num_axes: int = 1
    filler: Optional[FillerParameter] = None
    bias_term: bool = False
    bias_filler: Optional[FillerParameter] = None


@dataclass
class ContrastiveLossParameter(Message):
    margin: float = 1.0
    legacy_version: bool = False


@dataclass
class ConvolutionParameter(Message):
    num_output: int = 0
    bias_term: bool = True
    pad: List[int] = field(default_factory=list)
    kernel_size: List[int] = field(default_factory=list)
    stride: List[int] = field(default_factory=list)
    dilation: List[int] = field(default_factory=list)
    pad_h: int = 0
    pad_w: int = 0
    kernel_h: int = 0
    kernel_w: int = 0
    stride_h: int = 0
    stride_w: int = 0
    group: int = 1
    weight_filler: Optional[FillerParameter] = None
    bias_filler: Optional[FillerParameter] = None
    axis: int = 1
    force_nd_im2col: bool = False
    engine: Optional[str] = None  # DEFAULT | CAFFE | CUDNN (ignored)


@dataclass
class DataParameter(Message):
    source: Optional[str] = None
    batch_size: int = 0
    rand_skip: int = 0
    backend: str = "LEVELDB"  # LEVELDB | LMDB (we map both to our record DB)
    scale: float = 1.0
    mean_file: Optional[str] = None
    crop_size: int = 0
    mirror: bool = False
    force_encoded_color: bool = False
    prefetch: int = 4


@dataclass
class DropoutParameter(Message):
    dropout_ratio: float = 0.5


@dataclass
class DummyDataParameter(Message):
    data_filler: List[FillerParameter] = field(default_factory=list)
    shape: List[BlobShape] = field(default_factory=list)
    num: List[int] = field(default_factory=list)
    channels: List[int] = field(default_factory=list)
    height: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)


@dataclass
class ELUParameter(Message):
    alpha: float = 1.0


@dataclass
class EltwiseParameter(Message):
    operation: str = "SUM"  # PROD | SUM | MAX
    coeff: List[float] = field(default_factory=list)
    stable_prod_grad: bool = True


@dataclass
class EmbedParameter(Message):
    num_output: int = 0
    input_dim: int = 0
    bias_term: bool = True
    weight_filler: Optional[FillerParameter] = None
    bias_filler: Optional[FillerParameter] = None


@dataclass
class ExpParameter(Message):
    base: float = -1.0
    scale: float = 1.0
    shift: float = 0.0


@dataclass
class FlattenParameter(Message):
    axis: int = 1
    end_axis: int = -1


@dataclass
class HDF5DataParameter(Message):
    source: Optional[str] = None
    batch_size: int = 0
    shuffle: bool = False


@dataclass
class HDF5OutputParameter(Message):
    file_name: Optional[str] = None


@dataclass
class HingeLossParameter(Message):
    norm: str = "L1"  # L1 | L2


@dataclass
class ImageDataParameter(Message):
    source: Optional[str] = None
    batch_size: int = 1
    rand_skip: int = 0
    shuffle: bool = False
    new_height: int = 0
    new_width: int = 0
    is_color: bool = True
    scale: float = 1.0
    mean_file: Optional[str] = None
    crop_size: int = 0
    mirror: bool = False
    root_folder: str = ""


@dataclass
class InfogainLossParameter(Message):
    source: Optional[str] = None


@dataclass
class InnerProductParameter(Message):
    num_output: int = 0
    bias_term: bool = True
    weight_filler: Optional[FillerParameter] = None
    bias_filler: Optional[FillerParameter] = None
    axis: int = 1
    transpose: bool = False


@dataclass
class JavaDataParameter(Message):
    """Fork-added host-feed layer config (reference: caffe.proto:991-993).

    In this framework the same role is played by HostDataLayer: a layer whose
    batches are supplied by the host input pipeline each step.
    """

    shape: List[BlobShape] = field(default_factory=list)


@dataclass
class LogParameter(Message):
    base: float = -1.0
    scale: float = 1.0
    shift: float = 0.0


@dataclass
class LRNParameter(Message):
    local_size: int = 5
    alpha: float = 1.0
    beta: float = 0.75
    norm_region: str = "ACROSS_CHANNELS"  # ACROSS_CHANNELS | WITHIN_CHANNEL
    k: float = 1.0
    engine: Optional[str] = None


@dataclass
class MemoryDataParameter(Message):
    batch_size: int = 0
    channels: int = 0
    height: int = 0
    width: int = 0


@dataclass
class MVNParameter(Message):
    normalize_variance: bool = True
    across_channels: bool = False
    eps: float = 1e-9


@dataclass
class PoolingParameter(Message):
    pool: str = "MAX"  # MAX | AVE | STOCHASTIC
    pad: int = 0
    pad_h: int = 0
    pad_w: int = 0
    kernel_size: int = 0
    kernel_h: int = 0
    kernel_w: int = 0
    stride: int = 1
    stride_h: int = 0
    stride_w: int = 0
    global_pooling: bool = False
    engine: Optional[str] = None


@dataclass
class PowerParameter(Message):
    power: float = 1.0
    scale: float = 1.0
    shift: float = 0.0


@dataclass
class PReLUParameter(Message):
    filler: Optional[FillerParameter] = None
    channel_shared: bool = False


@dataclass
class PythonParameter(Message):
    module: Optional[str] = None
    layer: Optional[str] = None
    param_str: str = ""
    share_in_parallel: bool = False


@dataclass
class ReductionParameter(Message):
    operation: str = "SUM"  # SUM | ASUM | SUMSQ | MEAN
    axis: int = 0
    coeff: float = 1.0


@dataclass
class ReLUParameter(Message):
    negative_slope: float = 0.0
    engine: Optional[str] = None


@dataclass
class ReshapeParameter(Message):
    shape: Optional[BlobShape] = None
    axis: int = 0
    num_axes: int = -1


@dataclass
class SigmoidParameter(Message):
    engine: Optional[str] = None


@dataclass
class SliceParameter(Message):
    axis: int = 1
    slice_point: List[int] = field(default_factory=list)
    slice_dim: Optional[int] = None  # legacy


@dataclass
class SoftmaxParameter(Message):
    engine: Optional[str] = None
    axis: int = 1


@dataclass
class SPPParameter(Message):
    pyramid_height: int = 0
    pool: str = "MAX"
    engine: Optional[str] = None


@dataclass
class TanHParameter(Message):
    engine: Optional[str] = None


@dataclass
class ThresholdParameter(Message):
    threshold: float = 0.0


@dataclass
class TileParameter(Message):
    axis: int = 1
    tiles: int = 0


@dataclass
class WindowDataParameter(Message):
    source: Optional[str] = None
    scale: float = 1.0
    mean_file: Optional[str] = None
    batch_size: int = 0
    crop_size: int = 0
    mirror: bool = False
    fg_threshold: float = 0.5
    bg_threshold: float = 0.5
    fg_fraction: float = 0.25
    context_pad: int = 0
    crop_mode: str = "warp"
    cache_images: bool = False
    root_folder: str = ""


@dataclass
class InputParameter(Message):
    shape: List[BlobShape] = field(default_factory=list)


# --- TPU-native extensions (no reference equivalent) -----------------------


@dataclass
class AttentionParameter(Message):
    """Multi-head attention config — TPU-native extension for sequence
    models and the ring-attention sequence-parallel path."""

    num_heads: int = 1
    head_dim: int = 0
    causal: bool = False
    dropout_ratio: float = 0.0
    weight_filler: Optional[FillerParameter] = None
    bias_term: bool = True
    block_size: int = 512  # blockwise/ring attention chunk along sequence


# ---------------------------------------------------------------------------
# LayerParameter
# ---------------------------------------------------------------------------


@dataclass
class LayerParameter(Message):
    """One layer of a net (reference: caffe.proto:310-430)."""

    name: Optional[str] = None
    type: Optional[str] = None
    bottom: List[str] = field(default_factory=list)
    top: List[str] = field(default_factory=list)
    phase: Optional[str] = None
    loss_weight: List[float] = field(default_factory=list)
    param: List[ParamSpec] = field(default_factory=list)
    blobs: List[BlobProto] = field(default_factory=list)
    propagate_down: List[bool] = field(default_factory=list)
    include: List[NetStateRule] = field(default_factory=list)
    exclude: List[NetStateRule] = field(default_factory=list)
    transform_param: Optional[TransformationParameter] = None
    loss_param: Optional[LossParameter] = None
    accuracy_param: Optional[AccuracyParameter] = None
    argmax_param: Optional[ArgMaxParameter] = None
    attention_param: Optional[AttentionParameter] = None
    batch_norm_param: Optional[BatchNormParameter] = None
    bias_param: Optional[BiasParameter] = None
    concat_param: Optional[ConcatParameter] = None
    contrastive_loss_param: Optional[ContrastiveLossParameter] = None
    convolution_param: Optional[ConvolutionParameter] = None
    data_param: Optional[DataParameter] = None
    dropout_param: Optional[DropoutParameter] = None
    dummy_data_param: Optional[DummyDataParameter] = None
    eltwise_param: Optional[EltwiseParameter] = None
    elu_param: Optional[ELUParameter] = None
    embed_param: Optional[EmbedParameter] = None
    exp_param: Optional[ExpParameter] = None
    flatten_param: Optional[FlattenParameter] = None
    hdf5_data_param: Optional[HDF5DataParameter] = None
    hdf5_output_param: Optional[HDF5OutputParameter] = None
    hinge_loss_param: Optional[HingeLossParameter] = None
    image_data_param: Optional[ImageDataParameter] = None
    infogain_loss_param: Optional[InfogainLossParameter] = None
    inner_product_param: Optional[InnerProductParameter] = None
    input_param: Optional[InputParameter] = None
    java_data_param: Optional[JavaDataParameter] = None
    log_param: Optional[LogParameter] = None
    lrn_param: Optional[LRNParameter] = None
    memory_data_param: Optional[MemoryDataParameter] = None
    mvn_param: Optional[MVNParameter] = None
    pooling_param: Optional[PoolingParameter] = None
    power_param: Optional[PowerParameter] = None
    prelu_param: Optional[PReLUParameter] = None
    python_param: Optional[PythonParameter] = None
    reduction_param: Optional[ReductionParameter] = None
    relu_param: Optional[ReLUParameter] = None
    reshape_param: Optional[ReshapeParameter] = None
    scale_param: Optional[ScaleParameter] = None
    sigmoid_param: Optional[SigmoidParameter] = None
    slice_param: Optional[SliceParameter] = None
    softmax_param: Optional[SoftmaxParameter] = None
    spp_param: Optional[SPPParameter] = None
    tanh_param: Optional[TanHParameter] = None
    threshold_param: Optional[ThresholdParameter] = None
    tile_param: Optional[TileParameter] = None
    window_data_param: Optional[WindowDataParameter] = None
    # V1 legacy per-blob multipliers (upgraded into `param` on parse;
    # reference: V1LayerParameter in caffe.proto:1045 + upgrade_proto.cpp)
    blobs_lr: List[float] = field(default_factory=list)
    weight_decay: List[float] = field(default_factory=list)


# ---------------------------------------------------------------------------
# NetParameter / SolverParameter
# ---------------------------------------------------------------------------


@dataclass
class NetParameter(Message):
    """Whole-net config (reference: caffe.proto:64-100)."""

    name: Optional[str] = None
    input: List[str] = field(default_factory=list)
    input_shape: List[BlobShape] = field(default_factory=list)
    input_dim: List[int] = field(default_factory=list)
    force_backward: bool = False
    state: Optional[NetState] = None
    debug_info: bool = False
    layer: List[LayerParameter] = field(default_factory=list)
    # legacy V1 layers parse into the same list
    layers: List[LayerParameter] = field(default_factory=list)


@dataclass
class SolverParameter(Message):
    """Solver config (reference: caffe.proto:102-243)."""

    net: Optional[str] = None
    net_param: Optional[NetParameter] = None
    train_net: Optional[str] = None
    test_net: List[str] = field(default_factory=list)
    train_net_param: Optional[NetParameter] = None
    test_net_param: List[NetParameter] = field(default_factory=list)
    train_state: Optional[NetState] = None
    test_state: List[NetState] = field(default_factory=list)
    test_iter: List[int] = field(default_factory=list)
    test_interval: int = 0
    test_compute_loss: bool = False
    test_initialization: bool = True
    base_lr: float = 0.01
    display: int = 0
    average_loss: int = 1
    max_iter: int = 0
    iter_size: int = 1
    lr_policy: str = "fixed"
    gamma: float = 0.0
    power: float = 0.0
    momentum: float = 0.0
    weight_decay: float = 0.0
    regularization_type: str = "L2"
    stepsize: int = 0
    stepvalue: List[int] = field(default_factory=list)
    clip_gradients: float = -1.0
    snapshot: int = 0
    snapshot_prefix: str = ""
    snapshot_diff: bool = False
    snapshot_format: str = "BINARYPROTO"  # HDF5 | BINARYPROTO
    solver_mode: str = "GPU"  # CPU | GPU — informational on TPU
    device_id: int = 0
    random_seed: int = -1
    type: str = "SGD"
    delta: float = 1e-8
    momentum2: float = 0.999
    rms_decay: float = 0.99
    debug_info: bool = False
    snapshot_after_train: bool = True
    # legacy enum solver_type (SGD=0..ADAM=5)
    solver_type: Optional[str] = None


_LEGACY_SOLVER_TYPES = {
    "0": "SGD",
    "1": "NESTEROV",
    "2": "ADAGRAD",
    "3": "RMSPROP",
    "4": "ADADELTA",
    "5": "ADAM",
    "SGD": "SGD",
    "NESTEROV": "NESTEROV",
    "ADAGRAD": "ADAGRAD",
    "RMSPROP": "RMSPROP",
    "ADADELTA": "ADADELTA",
    "ADAM": "ADAM",
}


def solver_method(p: SolverParameter) -> str:
    """Resolve the solver algorithm, honoring the legacy enum field."""
    if p.solver_type is not None:
        key = str(p.solver_type).upper()
        if key not in _LEGACY_SOLVER_TYPES:
            raise ValueError(
                f"unrecognized solver_type: {p.solver_type!r} "
                f"(expected one of {sorted(set(_LEGACY_SOLVER_TYPES.values()))})"
            )
        return _LEGACY_SOLVER_TYPES[key]
    key = p.type.upper()
    if key not in _LEGACY_SOLVER_TYPES:
        raise ValueError(f"unrecognized solver type: {p.type!r}")
    return key


@dataclass
class SolverState(Message):
    """Checkpointed solver progress (reference: caffe.proto:245-255)."""

    iter: int = 0
    learned_net: Optional[str] = None
    history: List[BlobProto] = field(default_factory=list)
    current_step: int = 0
