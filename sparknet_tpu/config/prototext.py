"""Proto2 text-format parser/printer bound to the dataclass schema.

Plays the role of Caffe's ``ReadProtoFromTextFile`` / protobuf TextFormat
(reference: ``caffe/src/caffe/util/io.cpp:34-57``, surfaced to the driver via
``libccaffe/ccaffe.cpp:275-304``).  The grammar is the subset of proto2 text
format the reference's configs actually use:

    message   := field*
    field     := ident ':' scalar | ident [':'] '{' message '}'
    scalar    := number | 'true' | 'false' | quoted-string | ENUM_IDENT

Repeated fields accumulate across occurrences.  Unknown fields raise by
default (catches typos) unless ``permissive=True``.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List, Optional, Tuple, Type

from sparknet_tpu.config import schema
from sparknet_tpu.config.schema import Message

__all__ = ["parse", "parse_file", "dumps", "ParseError"]


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_PUNCT = {"{", "}", ":", "<", ">"}


def _tokenize(text: str):
    """Yield (token, line) pairs."""
    i, n, line = 0, len(text), 1
    while i < n:
        c = text[i]
        if c == "\n":
            line += 1
            i += 1
        elif c in " \t\r,;":
            i += 1
        elif c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in _PUNCT:
            yield c, line
            i += 1
        elif c in "\"'":
            quote, j, buf = c, i + 1, []
            while j < n and text[j] != quote:
                if text[j] == "\\" and j + 1 < n:
                    esc = text[j + 1]
                    buf.append(
                        {"n": "\n", "t": "\t", "\\": "\\", '"': '"', "'": "'"}.get(
                            esc, esc
                        )
                    )
                    j += 2
                else:
                    buf.append(text[j])
                    j += 1
            if j >= n:
                raise ParseError(f"line {line}: unterminated string")
            yield ("\0STR" + "".join(buf)), line
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n,;:{}<>#\"'":
                j += 1
            yield text[i:j], line
            i = j
    yield None, line


# ---------------------------------------------------------------------------
# Generic parse into nested dicts
# ---------------------------------------------------------------------------


_CLOSER = {"{": "}", "<": ">"}


def _parse_tokens(tokens, closer: str = "") -> Dict[str, List[Any]]:
    """Parse a message body into {field: [values...]}, values are scalars
    (str) or nested dicts.  ``closer`` is the expected closing token (empty
    at top level)."""
    out: Dict[str, List[Any]] = {}
    while True:
        tok, line = next(tokens)
        if tok is None:
            if closer:
                raise ParseError(f"line {line}: unexpected end of input")
            return out
        if tok in ("}", ">"):
            if tok != closer:
                raise ParseError(f"line {line}: unmatched '{tok}'")
            return out
        if not isinstance(tok, str) or tok in _PUNCT:
            raise ParseError(f"line {line}: expected field name, got {tok!r}")
        name = tok
        tok2, line2 = next(tokens)
        if tok2 == ":":
            tok3, line3 = next(tokens)
            if tok3 in ("{", "<"):
                value: Any = _parse_tokens(tokens, _CLOSER[tok3])
            elif tok3 is None or tok3 in _PUNCT:
                raise ParseError(f"line {line3}: expected value for '{name}'")
            else:
                value = tok3
        elif tok2 in ("{", "<"):
            value = _parse_tokens(tokens, _CLOSER[tok2])
        else:
            raise ParseError(f"line {line2}: expected ':' or '{{' after '{name}'")
        out.setdefault(name, []).append(value)


# ---------------------------------------------------------------------------
# Binding dicts -> dataclasses
# ---------------------------------------------------------------------------


def _field_types(cls: Type[Message]) -> Dict[str, Tuple[str, Any]]:
    """Map field name -> (kind, inner type). kind in {scalar, list,
    msg, msglist}."""
    hints = typing.get_type_hints(cls)
    out = {}
    for f in dataclasses.fields(cls):
        t = hints[f.name]
        origin = typing.get_origin(t)
        if origin is list or origin is List:
            (inner,) = typing.get_args(t)
            if isinstance(inner, type) and issubclass(inner, Message):
                out[f.name] = ("msglist", inner)
            else:
                out[f.name] = ("list", inner)
        elif origin is typing.Union:  # Optional[X]
            args = [a for a in typing.get_args(t) if a is not type(None)]
            inner = args[0]
            if isinstance(inner, type) and issubclass(inner, Message):
                out[f.name] = ("msg", inner)
            else:
                out[f.name] = ("scalar", inner)
        elif isinstance(t, type) and issubclass(t, Message):
            out[f.name] = ("msg", t)
        else:
            out[f.name] = ("scalar", t)
    return out


_TYPE_CACHE: Dict[type, Dict[str, Tuple[str, Any]]] = {}


def _coerce(raw: str, target: Any, where: str):
    if isinstance(raw, dict):
        raise ParseError(f"{where}: expected scalar, got message")
    is_str = raw.startswith("\0STR")
    sval = raw[4:] if is_str else raw
    if target is str or target is Optional[str]:
        return sval
    if is_str:
        # quoted value for a non-string field: coerce anyway (protobuf rejects
        # this, but being lenient costs nothing)
        raw = sval
    if target is bool:
        low = raw.lower()
        if low in ("true", "1"):
            return True
        if low in ("false", "0"):
            return False
        raise ParseError(f"{where}: bad bool {raw!r}")
    if target is int:
        try:
            return int(raw, 0)
        except ValueError:
            try:
                fv = float(raw)
            except ValueError:
                raise ParseError(f"{where}: bad int {raw!r}") from None
            if fv != int(fv):
                raise ParseError(f"{where}: bad int {raw!r}")
            return int(fv)
    if target is float:
        try:
            return float(raw)
        except ValueError:
            raise ParseError(f"{where}: bad float {raw!r}") from None
    # fallback: string-ish (enum idents land here when typed Optional[str])
    return sval


def _bind(cls: Type[Message], d: Dict[str, List[Any]], permissive: bool) -> Message:
    if cls not in _TYPE_CACHE:
        _TYPE_CACHE[cls] = _field_types(cls)
    ftypes = _TYPE_CACHE[cls]
    kwargs: Dict[str, Any] = {}
    for name, values in d.items():
        if name not in ftypes:
            if permissive:
                continue
            raise ParseError(f"unknown field '{name}' in {cls.__name__}")
        kind, inner = ftypes[name]
        where = f"{cls.__name__}.{name}"
        if kind == "scalar":
            kwargs[name] = _coerce(values[-1], inner, where)
        elif kind == "list":
            kwargs[name] = [_coerce(v, inner, where) for v in values]
        elif kind == "msg":
            # proto2 TextFormat merges repeated occurrences of a singular
            # message field rather than taking the last one
            merged: Dict[str, List[Any]] = {}
            for v in values:
                if not isinstance(v, dict):
                    raise ParseError(f"{where}: expected message")
                _merge_dict(merged, v)
            kwargs[name] = _bind(inner, merged, permissive)
        else:  # msglist
            items = []
            for v in values:
                if not isinstance(v, dict):
                    raise ParseError(f"{where}: expected message")
                items.append(_bind(inner, v, permissive))
            kwargs[name] = items
    msg = cls(**kwargs)
    if isinstance(msg, schema.NetParameter):
        _upgrade_net(msg)
    return msg


# V1LayerParameter_LayerType enum name -> modern type string
# (reference: upgrade_proto.cpp:852-936 UpgradeV1LayerType)
_V1_LAYER_TYPES = {
    "ABSVAL": "AbsVal",
    "ACCURACY": "Accuracy",
    "ARGMAX": "ArgMax",
    "BNLL": "BNLL",
    "CONCAT": "Concat",
    "CONTRASTIVE_LOSS": "ContrastiveLoss",
    "CONVOLUTION": "Convolution",
    "DECONVOLUTION": "Deconvolution",
    "DATA": "Data",
    "DROPOUT": "Dropout",
    "DUMMY_DATA": "DummyData",
    "EUCLIDEAN_LOSS": "EuclideanLoss",
    "ELTWISE": "Eltwise",
    "EXP": "Exp",
    "FLATTEN": "Flatten",
    "HDF5_DATA": "HDF5Data",
    "HDF5_OUTPUT": "HDF5Output",
    "HINGE_LOSS": "HingeLoss",
    "IM2COL": "Im2col",
    "IMAGE_DATA": "ImageData",
    "INFOGAIN_LOSS": "InfogainLoss",
    "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN",
    "MEMORY_DATA": "MemoryData",
    "MULTINOMIAL_LOGISTIC_LOSS": "MultinomialLogisticLoss",
    "MVN": "MVN",
    "POOLING": "Pooling",
    "POWER": "Power",
    "RELU": "ReLU",
    "SIGMOID": "Sigmoid",
    "SIGMOID_CROSS_ENTROPY_LOSS": "SigmoidCrossEntropyLoss",
    "SILENCE": "Silence",
    "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split",
    "SLICE": "Slice",
    "TANH": "TanH",
    "WINDOW_DATA": "WindowData",
    "THRESHOLD": "Threshold",
    "JAVA_DATA": "JavaData",
}


def _upgrade_net(net: "schema.NetParameter") -> None:
    """Fold legacy V1 constructs into the modern schema, at any nesting depth
    (reference: ``caffe/src/caffe/util/upgrade_proto.cpp``)."""
    if net.layers:
        net.layer = list(net.layers) + list(net.layer)
        net.layers = []
    for layer in net.layer:
        # V1 enum type names (CONVOLUTION, SOFTMAX_LOSS, ...) -> modern strings
        if layer.type in _V1_LAYER_TYPES:
            layer.type = _V1_LAYER_TYPES[layer.type]
        # V1 per-blob multipliers: blobs_lr -> ParamSpec.lr_mult,
        # weight_decay -> ParamSpec.decay_mult
        if layer.blobs_lr and not layer.param:
            layer.param = [
                schema.ParamSpec(
                    lr_mult=lr,
                    decay_mult=(
                        layer.weight_decay[i]
                        if i < len(layer.weight_decay)
                        else 1.0
                    ),
                )
                for i, lr in enumerate(layer.blobs_lr)
            ]
        layer.blobs_lr = []
        layer.weight_decay = []


def _merge_dict(dst: Dict[str, List[Any]], src: Dict[str, List[Any]]) -> None:
    for k, vs in src.items():
        dst.setdefault(k, []).extend(vs)


# ---------------------------------------------------------------------------
# V0 upgrade (reference: upgrade_proto.cpp:96-529 UpgradeV0Net /
# UpgradeV0LayerParameter / UpgradeV0LayerType).  A V0 net wraps each
# layer in a connection: ``layers { layer { <flat fields> } bottom top }``
# with lowercase short type names and per-type flat fields; the upgrade
# routes each flat field into the modern per-type sub-message.  Runs on
# the raw token dicts (before schema binding, where the V0-only fields
# would be unknown); the V1 leg (_upgrade_net) then finishes blobs_lr ->
# ParamSpec.
# ---------------------------------------------------------------------------

_V0_LAYER_TYPES = {
    "accuracy": "Accuracy", "bnll": "BNLL", "concat": "Concat",
    "conv": "Convolution", "data": "Data", "dropout": "Dropout",
    "euclidean_loss": "EuclideanLoss", "flatten": "Flatten",
    "hdf5_data": "HDF5Data", "hdf5_output": "HDF5Output",
    "im2col": "Im2col", "images": "ImageData",
    "infogain_loss": "InfogainLoss", "innerproduct": "InnerProduct",
    "lrn": "LRN", "multinomial_logistic_loss": "MultinomialLogisticLoss",
    "pool": "Pooling", "relu": "ReLU", "sigmoid": "Sigmoid",
    "softmax": "Softmax", "softmax_loss": "SoftmaxWithLoss",
    "split": "Split", "tanh": "TanH", "window_data": "WindowData",
}

# (v0_field, v0_type) -> (sub_message, field) routing; None sub = layer
# level.  Mirrors the if-ladders of UpgradeV0LayerParameter.
_V0_ROUTES = {
    ("num_output", "conv"): ("convolution_param", "num_output"),
    ("num_output", "innerproduct"): ("inner_product_param", "num_output"),
    ("biasterm", "conv"): ("convolution_param", "bias_term"),
    ("biasterm", "innerproduct"): ("inner_product_param", "bias_term"),
    ("weight_filler", "conv"): ("convolution_param", "weight_filler"),
    ("weight_filler", "innerproduct"): ("inner_product_param", "weight_filler"),
    ("bias_filler", "conv"): ("convolution_param", "bias_filler"),
    ("bias_filler", "innerproduct"): ("inner_product_param", "bias_filler"),
    ("pad", "conv"): ("convolution_param", "pad"),
    ("pad", "pool"): ("pooling_param", "pad"),
    ("kernelsize", "conv"): ("convolution_param", "kernel_size"),
    ("kernelsize", "pool"): ("pooling_param", "kernel_size"),
    ("group", "conv"): ("convolution_param", "group"),
    ("stride", "conv"): ("convolution_param", "stride"),
    ("stride", "pool"): ("pooling_param", "stride"),
    ("pool", "pool"): ("pooling_param", "pool"),
    ("dropout_ratio", "dropout"): ("dropout_param", "dropout_ratio"),
    ("local_size", "lrn"): ("lrn_param", "local_size"),
    ("alpha", "lrn"): ("lrn_param", "alpha"),
    ("beta", "lrn"): ("lrn_param", "beta"),
    ("k", "lrn"): ("lrn_param", "k"),
    ("source", "data"): ("data_param", "source"),
    ("source", "hdf5_data"): ("hdf5_data_param", "source"),
    ("source", "images"): ("image_data_param", "source"),
    ("source", "window_data"): ("window_data_param", "source"),
    ("source", "infogain_loss"): ("infogain_loss_param", "source"),
    ("batchsize", "data"): ("data_param", "batch_size"),
    ("batchsize", "hdf5_data"): ("hdf5_data_param", "batch_size"),
    ("batchsize", "images"): ("image_data_param", "batch_size"),
    ("batchsize", "window_data"): ("window_data_param", "batch_size"),
    ("rand_skip", "data"): ("data_param", "rand_skip"),
    ("rand_skip", "images"): ("image_data_param", "rand_skip"),
    ("shuffle_images", "images"): ("image_data_param", "shuffle"),
    ("new_height", "images"): ("image_data_param", "new_height"),
    ("new_width", "images"): ("image_data_param", "new_width"),
    ("concat_dim", "concat"): ("concat_param", "concat_dim"),
    # data transformations (UpgradeNetDataTransformation folds these into
    # transform_param for every data-ish type)
    ("scale", "data"): ("transform_param", "scale"),
    ("scale", "images"): ("transform_param", "scale"),
    ("scale", "window_data"): ("transform_param", "scale"),
    ("meanfile", "data"): ("transform_param", "mean_file"),
    ("meanfile", "images"): ("transform_param", "mean_file"),
    ("meanfile", "window_data"): ("transform_param", "mean_file"),
    ("cropsize", "data"): ("transform_param", "crop_size"),
    ("cropsize", "images"): ("transform_param", "crop_size"),
    ("cropsize", "window_data"): ("transform_param", "crop_size"),
    ("mirror", "data"): ("transform_param", "mirror"),
    ("mirror", "images"): ("transform_param", "mirror"),
    ("mirror", "window_data"): ("transform_param", "mirror"),
    # R-CNN-era detection fields (upgrade_proto.cpp:382-412)
    ("det_fg_threshold", "window_data"): ("window_data_param", "fg_threshold"),
    ("det_bg_threshold", "window_data"): ("window_data_param", "bg_threshold"),
    ("det_fg_fraction", "window_data"): ("window_data_param", "fg_fraction"),
    ("det_context_pad", "window_data"): ("window_data_param", "context_pad"),
    ("det_crop_mode", "window_data"): ("window_data_param", "crop_mode"),
}


def _tok_str(tok: Any) -> str:
    s = str(tok)
    return s[4:] if s.startswith("\0STR") else s


def _v0_type(entry: Dict[str, List[Any]]) -> str:
    inner = entry["layer"][0]
    return _tok_str(inner.get("type", [""])[0])


def _fold_v0_padding(d: Dict[str, List[Any]]) -> None:
    """Merge V0 ``padding`` layers into the following conv/pool layer
    (reference: ``UpgradeV0PaddingLayers``, upgrade_proto.cpp:120-178):
    the padding layer disappears, its ``pad`` lands on the consumer, and
    the consumer's bottom is rewired to the padding layer's input."""
    entries = d.get("layers") or []
    if not any(isinstance(e, dict) and "layer" in e for e in entries):
        return
    blob_src: Dict[str, Any] = {
        _tok_str(t): None for t in d.get("input", [])
    }
    kept = []
    for e in entries:
        is_v0 = isinstance(e, dict) and "layer" in e
        if not (is_v0 and _v0_type(e) == "padding"):
            kept.append(e)
        for j, b in enumerate(e.get("bottom", []) if isinstance(e, dict) else []):
            bname = _tok_str(b)
            if bname not in blob_src:
                # the reference LOG(FATAL)s on unknown inputs
                # (upgrade_proto.cpp:142-144) because every blob there
                # must come from a layer or net input; here externally-fed
                # blobs (feed_shapes / replaceDataLayers flow) are
                # legitimate.  Safe for the fold: a deleted padding
                # layer's top is always registered in blob_src, so an
                # unknown bottom can never dangle on one.
                continue
            src = blob_src[bname]
            if not (isinstance(src, dict) and "layer" in src
                    and _v0_type(src) == "padding"):
                continue
            # the reference declares these geometries undefined and
            # CHECK-fails (upgrade_proto.cpp:152-163): consumer must be a
            # single-bottom conv/pool; padding must be 1-bottom/1-top
            if not (is_v0 and _v0_type(e) in ("conv", "pool")):
                raise ValueError(
                    "V0 padding layer feeds a non-conv/pool layer "
                    f"({_v0_type(e) if is_v0 else 'V1'}) — undefined in "
                    "the reference upgrade (upgrade_proto.cpp:152-155)"
                )
            if len(e.get("bottom", [])) != 1:
                raise ValueError(
                    "V0 padding-fed conv/pool layer must take a single "
                    "bottom (upgrade_proto.cpp:156-157)"
                )
            if len(src.get("bottom", [])) != 1 or len(src.get("top", [])) != 1:
                raise ValueError(
                    "V0 padding layer must have one bottom and one top "
                    "(upgrade_proto.cpp:158-163)"
                )
            e["layer"][0]["pad"] = list(src["layer"][0].get("pad", ["0"]))
            e["bottom"][j] = src["bottom"][0]
        if isinstance(e, dict):
            for t in e.get("top", []):
                blob_src[_tok_str(t)] = e
    d["layers"] = kept


def _upgrade_v0_entry(entry: Dict[str, List[Any]]) -> Dict[str, List[Any]]:
    """One V0 ``layers { layer {...} bottom top }`` connection -> a modern
    layer token dict."""
    inner = entry["layer"][0]
    out: Dict[str, List[Any]] = {}
    for key in ("bottom", "top"):
        if key in entry:
            out[key] = list(entry[key])
    v0_type = str(inner.get("type", [""])[0])
    if v0_type.startswith("\0STR"):
        v0_type = v0_type[4:]
    if "name" in inner:
        out["name"] = list(inner["name"])
    if v0_type:
        if v0_type not in _V0_LAYER_TYPES:
            raise ValueError(f"unknown V0 layer type {v0_type!r}")
        out["type"] = [_V0_LAYER_TYPES[v0_type]]
    for field, values in inner.items():
        if field in ("name", "type"):
            continue
        if field in ("blobs_lr", "weight_decay", "blobs"):
            out.setdefault(field, []).extend(values)
            continue
        route = _V0_ROUTES.get((field, v0_type))
        if route is None:
            raise ValueError(
                f"V0 field {field!r} has no upgrade for layer type "
                f"{v0_type!r} (upgrade_proto.cpp would mark this net "
                "not fully compatible)"
            )
        sub, new_name = route
        subdicts = out.setdefault(sub, [{}])
        subdicts[0].setdefault(new_name, []).extend(values)
    return out


def _upgrade_v0_tokens(d: Dict[str, List[Any]]) -> None:
    """Rewrite V0 connections inside a NetParameter token dict in place;
    pure-V1 ``layers`` entries pass through untouched."""
    entries = d.get("layers")
    if not entries:
        return
    _fold_v0_padding(d)
    d["layers"] = [
        _upgrade_v0_entry(e) if isinstance(e, dict) and "layer" in e else e
        for e in d.get("layers") or []
    ]


def parse(text: str, cls: Type[Message], permissive: bool = False) -> Message:
    """Parse prototxt text into an instance of ``cls``."""
    d = _parse_tokens(_tokenize(text))
    if cls is schema.NetParameter:
        _upgrade_v0_tokens(d)
    return _bind(cls, d, permissive)


def parse_file(path: str, cls: Type[Message], permissive: bool = False) -> Message:
    with open(path, "r") as f:
        return parse(f.read(), cls, permissive)


# ---------------------------------------------------------------------------
# Printer
# ---------------------------------------------------------------------------

_ENUMISH_FIELDS = {
    # fields whose string values print unquoted (proto enums)
    ("NetStateRule", "phase"),
    ("NetState", "phase"),
    ("LayerParameter", "phase"),
    ("ParamSpec", "share_mode"),
    ("FillerParameter", "variance_norm"),
    ("LossParameter", "normalization"),
    ("ConvolutionParameter", "engine"),
    ("PoolingParameter", "pool"),
    ("PoolingParameter", "engine"),
    ("EltwiseParameter", "operation"),
    ("LRNParameter", "norm_region"),
    ("LRNParameter", "engine"),
    ("ReductionParameter", "operation"),
    ("HingeLossParameter", "norm"),
    ("DataParameter", "backend"),
    ("SoftmaxParameter", "engine"),
    ("ReLUParameter", "engine"),
    ("SigmoidParameter", "engine"),
    ("TanHParameter", "engine"),
    ("SPPParameter", "pool"),
    ("SPPParameter", "engine"),
    ("SolverParameter", "snapshot_format"),
    ("SolverParameter", "solver_mode"),
    ("SolverParameter", "solver_type"),
}


def _fmt_scalar(cls_name: str, fname: str, v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if (cls_name, fname) in _ENUMISH_FIELDS:
        return str(v)
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


def dumps(msg: Message, indent: int = 0) -> str:
    """Print a message as prototxt (round-trips through :func:`parse`)."""
    cls = type(msg)
    if cls not in _TYPE_CACHE:
        _TYPE_CACHE[cls] = _field_types(cls)
    ftypes = _TYPE_CACHE[cls]
    pad = "  " * indent
    lines = []
    for f in dataclasses.fields(msg):
        v = getattr(msg, f.name)
        kind, _ = ftypes[f.name]
        default = (
            f.default_factory()
            if f.default_factory is not dataclasses.MISSING
            else f.default
        )
        if kind in ("scalar", "list") and (v == default or v is None):
            continue
        if kind in ("msg", "msglist") and not v:
            continue
        if kind == "scalar":
            lines.append(f"{pad}{f.name}: {_fmt_scalar(cls.__name__, f.name, v)}")
        elif kind == "list":
            for item in v:
                lines.append(
                    f"{pad}{f.name}: {_fmt_scalar(cls.__name__, f.name, item)}"
                )
        elif kind == "msg":
            body = dumps(v, indent + 1)
            lines.append(f"{pad}{f.name} {{\n{body}{pad}}}")
        else:
            for item in v:
                body = dumps(item, indent + 1)
                lines.append(f"{pad}{f.name} {{\n{body}{pad}}}")
    return "".join(line + "\n" for line in lines)
