"""Deterministic chaos harness: prove the fault-tolerance layer end to end.

SparkNet got fault tolerance for free from Spark's RDD lineage — a lost
partition recomputed and the averaging loop never noticed (PAPER.md §2).
The TPU rewrite has to EARN the same property, and this module is the
proof: a seeded ``FaultPlan`` injects the defining failure modes of a
real TPU pod into a small cifar10_quick run on the virtual mesh —

- **storage faults**: transient connection-resets in the data fetch,
  healed by ``utils/retry`` (the same layer ``data/object_store._get``
  sits on),
- **feed stalls**: the producer wedges past the ``Prefetcher`` stall
  watchdog; the driver tears the prefetcher down (robust ``stop()``)
  and rebuilds it,
- **preemption**: a real SIGHUP delivered mid-run — snapshot, simulated
  process death, resume,
- **snapshot corruption**: the newest snapshot's bytes are flipped, so
  resume must quarantine it and fall back to the newest VALID one
  (``io/checkpoint.restore_newest_valid``),
- **worker death**: one dp worker drops out mid-run; survivor-aware
  averaging (``ParameterAveragingTrainer.round(live_mask=...)``) keeps
  the weights healthy.
- **nan injection**: one dp worker's batch is poisoned with NaN at a
  seeded round; the numerics audit (``obs/health.py``) must flag that
  EXACT round and the in-graph sentry mask must exclude the poisoned
  replica from the parameter average before it reaches the ``psum``.
- **straggler injection**: one dp worker's batch assembly sleeps at a
  seeded round (a slow host / degraded chip stand-in); the round-
  anatomy profiler (``obs/profile.py``) must attribute the slow round
  to EXACTLY the seeded worker (per-worker timing hooks + straggler
  verdict) — the signal ROADMAP item 1's elastic membership needs to
  know *which* worker to evict.
- **cache corruption**: the chunk cache's published entry for a seeded
  round's data chunk is byte-flipped on disk (size unchanged — only
  the CRC manifest can catch it); the cache must QUARANTINE the entry
  (``*.corrupt``) and transparently refetch byte-identical data from
  the backing store (``data/chunk_cache.py``).
- **cache cold**: the whole cache is wiped at a seeded round (host
  restart / cache-volume loss stand-in); the read must miss, refetch,
  and training must not notice.
- **replica death**: one replica of a serving fleet
  (``serve/fleet.py``) is hard-killed mid-traffic; the router must
  eject it on sight, retry its in-flight requests on live siblings
  (zero client errors), and ``respawn`` must return it to rotation.
- **published snapshot corrupt**: a snapshot published for delivery
  (``serve/publish.py``) has its model bytes flipped on disk (size
  unchanged); the delivery watcher (``serve/delivery.py``) must
  REJECT it at CRC verify — it must never reach a canary — and
  quarantine the publish ``*.corrupt``.
- **decode replica kill**: a replica of a GENERATION fleet
  (``serve/generate.py`` engines under continuous batching) is
  hard-killed MID-STREAM, with a client half-way through its tokens;
  the router must eject it and RESUME the stream on a sibling via
  re-prefill of prompt + tokens-so-far — greedy decode is
  deterministic, so the full token sequence must be IDENTICAL to an
  undisturbed run — or end with a clean error event, never a hung
  connection.

Every fault is counted as injected and (when the run recovers) survived;
``bench.py --mode=chaos`` emits the ``CHAOS_r07.json`` artifact
(faults_injected, faults_survived, recovery latency, loss-band check
against the no-fault baseline) and the tier-1 chaos smoke
(``tests/test_chaos.py``) runs the same default plan.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal as _signal
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparknet_tpu import obs as _obs
from sparknet_tpu.obs import profile as _profile
from sparknet_tpu.utils import retry as _retry


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seeded, fully-deterministic schedule of faults.

    Rounds are 0-indexed and ABSOLUTE (replayed rounds after a resume
    keep their original index, so per-round faults fire exactly once).
    The default plan is the tier-1 chaos smoke: every fault class, small
    shapes, < 1 min on a CPU box."""

    seed: int = 7
    workers: int = 4
    rounds: int = 6
    tau: int = 2
    batch: int = 8
    # round -> consecutive transient storage errors before that round's
    # fetch succeeds (healed by the retry layer)
    storage_faults: Tuple[Tuple[int, int], ...] = ((1, 2), (4, 1))
    # rounds whose fetch stalls past the prefetch watchdog (fires once).
    # Round 0 by default: the consumer has no prefetch-depth lead yet,
    # so the watchdog deterministically fires and the
    # stop()-and-rebuild recovery path is what survives the fault (a
    # stall in a later round can be absorbed by the buffer instead —
    # also a survival, just a less interesting one)
    stall_rounds: Tuple[int, ...] = (0,)
    stall_s: float = 4.0
    stall_timeout_s: float = 1.0
    # SIGHUP preemption at the END of this round (None = no preemption)
    preempt_round: Optional[int] = 3
    corrupt_newest: bool = True  # corrupt newest snapshot before resume
    # this dp worker dies (drops from the average) from this round on
    dead_worker: Optional[int] = 2
    dead_from_round: int = 4
    snapshot_every: int = 2  # periodic snapshot cadence, in rounds
    # nan_injection: poison these dp workers' batches with NaN at this
    # round (fires once, by absolute round index).  The numerics audit +
    # in-graph sentry mask (obs/health.py) must catch the poisoned
    # worker(s) BEFORE the parameter average — the divergence-sentry
    # analog of the dead-worker fault.
    nan_round: Optional[int] = 2
    nan_workers: Tuple[int, ...] = (1,)
    # straggler_injection: this dp worker's batch assembly sleeps
    # straggler_s at this round (fires once, by absolute round index).
    # The round profiler's per-worker attribution must name EXACTLY
    # this worker (worst_worker + straggler verdict).  Before the
    # preemption so the resume replay cannot re-fire it; a different
    # worker than the nan/dead ones so each fault's attribution is
    # unambiguous.  Kept well under stall_timeout_s: the straggler must
    # not trip the feed watchdog (that is the stall fault's job).
    straggler_round: Optional[int] = 1
    straggler_worker: int = 3
    straggler_s: float = 0.4
    # cache_corruption: byte-flip the chunk cache's PUBLISHED entry for
    # this round's data chunk before the read (fires once, absolute
    # round).  Survived = the cache quarantined the entry (*.corrupt on
    # disk), refetched from the backing store, and served bytes
    # IDENTICAL to a direct store read.  Before the preemption so the
    # resume replay cannot re-fire it.
    cache_corrupt_round: Optional[int] = 2
    # cache_cold: wipe every published cache entry before this round's
    # read (a host restart / lost cache volume).  Survived = the read
    # misses, refetches, and the round trains normally.  AFTER the
    # preemption: the cold-cache recovery is exercised on the resumed
    # process, the realistic case.
    cache_cold_round: Optional[int] = 5
    # collector_outage: the fleet collector (obs/fleet.py) goes DOWN at
    # the end of this round and comes back collector_outage_rounds
    # rounds later (a crashed / partitioned observability plane).  The
    # per-host shipper (obs/ship.py) must keep training unblocked,
    # buffer the run-log events + metric deltas it cannot push, and
    # REPLAY them when the collector returns — survived = zero lost
    # events, zero dropped events, and the collector actually missed
    # pushes while down (the outage really bit).  Resumes before the
    # preemption so the two faults don't compound.
    collector_outage_round: Optional[int] = 1
    collector_outage_rounds: int = 2
    # replica_death: at the END of this round a 2-replica serving fleet
    # (built lazily on the chaos box, tiny toy net) loses replica 0 to
    # a hard kill mid-traffic.  Survived = every subsequent request is
    # served (router eject-and-retry, zero client errors), the dead
    # replica reads `ejected`, and a respawn returns it to rotation.
    # AFTER the preemption: the fleet is rebuilt lazily on the resumed
    # process, and the fire-once guard keeps a replay from re-killing.
    replica_death_round: Optional[int] = 4
    # decode_replica_kill: at the END of this round a 2-replica
    # GENERATION fleet (tiny TransformerLM under StreamBatcher
    # continuous batching) loses the replica serving an in-flight
    # token stream to a hard kill.  Survived = the router ejects the
    # dead replica, RESUMES the stream on the sibling by re-prefilling
    # prompt + tokens-so-far, and the client's final token sequence is
    # IDENTICAL to an undisturbed run (greedy decode is deterministic)
    # — plus respawn returns the dead replica to rotation and a fresh
    # stream serves end-to-end afterwards.  Never a hung connection.
    decode_replica_kill_round: Optional[int] = 4
    # published_snapshot_corrupt: at the END of this round the current
    # training state is PUBLISHED for delivery (passing verdict
    # attached) and its model bytes are then flipped on disk (size
    # unchanged — only the manifest CRC can catch it).  Survived = the
    # delivery watcher rejects it at verify (it never reaches a
    # canary) and quarantines the publish *.corrupt.
    publish_corrupt_round: Optional[int] = 5
    # slice_preemption: a REAL SIGTERM at the END of this round is the
    # orchestrator's preemption notice for a whole slice (the
    # membership controller's SIGTERM hook marks slice
    # slice_preempt_slice leaving; runtime/membership.py).  The
    # departed workers leave the average at the next round boundary
    # (view epoch), train masked while gone, and the relaunched slice
    # requests a rejoin slice_relaunch_delta rounds after the notice —
    # readmitted via a fresh consensus snapshot ->
    # restore_newest_valid -> broadcast_state with momentum zeroed.
    # Survived = views advanced leave -> dead -> rejoin with monotonic
    # epochs, the leave detected at EXACTLY round R+1, the average
    # renormalized over survivors every intervening round, and the
    # final roster fully live.  Before the SIGHUP preemption's round so
    # the leave lands pre-resume and the replay can't re-fire it; the
    # run also arms a two-tier HierarchySpec (membership_slices x
    # cross_slice_every), so the chaos proof covers the hierarchical
    # schedule too.
    slice_preempt_round: Optional[int] = 2
    slice_preempt_slice: int = 0
    slice_relaunch_delta: int = 1  # note_join at END of round R+delta
    membership_slices: int = 2
    cross_slice_every: int = 2
    # driver_kill: at the END of this round, one kill-point of the
    # crash-consistency sweep runs as a bounded sub-scenario
    # (runtime/recover.py): a journaled mini-driver (int8 EF residuals,
    # sentry, membership epoch all carried as job state) is crashed
    # MID-JOURNAL-APPEND — half a commit frame lands durably — and
    # resumed.  Survived = the torn tail was truncated on open, the
    # resume rewound to the last committed boundary, re-executed at
    # most ONE round, and the final state digest is BIT-IDENTICAL to
    # an uninterrupted control.  (The in-process stand-in for the
    # SIGKILL sweep; the real kill-anywhere proof is ``bench.py
    # --mode=recover`` / RECOVER_r17.)
    driver_kill_round: Optional[int] = 5
    # slow_slice: at the END of this round, a bounded A/B sub-scenario
    # (parallel/stale.py): one whole slice of a two-tier job runs
    # +slow_slice_s per round for slow_slice_rounds consecutive
    # rounds.  The synchronous control (ParameterAveragingTrainer)
    # waits for it at every boundary and pays the full tail straight
    # onto the critical path; the bounded-staleness leg
    # (BoundedStalenessTrainer, stale_bound > slow_slice_rounds) takes
    # whoever arrived, lets the slow slice go stale, and folds it in
    # after the tail clears.  Survived = the stale leg paid ZERO
    # forced waits, its wall-clock undercuts the sync control by most
    # of the injected tail, the per-worker staleness telemetry names a
    # slow-slice member as the laggiest worker every slow round, and
    # the two final losses agree within the band (the speed is not
    # bought with divergence).
    slow_slice_round: Optional[int] = 4
    slow_slice_slice: int = 1
    slow_slice_s: float = 0.5
    slow_slice_rounds: int = 3
    slow_slice_stale_bound: int = 4

    @classmethod
    def default(cls) -> "FaultPlan":
        return cls()

    def no_fault_view(self) -> "FaultPlan":
        """The same run shape with every fault removed (the baseline)."""
        return dataclasses.replace(
            self,
            storage_faults=(),
            stall_rounds=(),
            preempt_round=None,
            corrupt_newest=False,
            dead_worker=None,
            nan_round=None,
            straggler_round=None,
            cache_corrupt_round=None,
            cache_cold_round=None,
            collector_outage_round=None,
            replica_death_round=None,
            decode_replica_kill_round=None,
            publish_corrupt_round=None,
            slice_preempt_round=None,
            driver_kill_round=None,
            slow_slice_round=None,
        )


def storage_fault_hook(plan: FaultPlan, counters: Dict[str, int]):
    """A ``data/object_store.set_fault_hook`` injector: raises
    ``ConnectionResetError`` for the first N fetch attempts per planned
    round-slot, keyed round-robin by call order.  Used by tests to prove
    ``object_store._get`` heals under the SAME fault source the chaos
    run uses."""
    remaining = {r: n for r, n in plan.storage_faults}
    order = sorted(remaining)
    slot = {"i": 0}

    def hook(url: str) -> None:
        if slot["i"] >= len(order):
            return None
        r = order[slot["i"]]
        if remaining[r] > 0:
            remaining[r] -= 1
            counters["storage_injected"] = (
                counters.get("storage_injected", 0) + 1
            )
            raise ConnectionResetError(
                f"chaos: injected storage fault (slot {r}) for {url}"
            )
        # slot spent: THIS call passes (the fetch the faults were
        # aimed at succeeds) and the next slot arms for a LATER fetch —
        # slots never bleed into one call's retry loop
        slot["i"] += 1
        return None

    return hook


def chunk_name(r: int) -> str:
    """The chunk-store object name for round ``r``'s window."""
    return f"round_{r:04d}.npz"


def write_round_chunks(plan: FaultPlan, xs, ys, chunk_dir: str) -> None:
    """Serialize every round's CLEAN window arrays (the same index math
    ``_Feed`` uses) as npz chunks in a local store directory — the
    backing objects the chunk cache fronts during the chaos run.
    Idempotent; files publish atomically."""
    import io as _io

    os.makedirs(chunk_dir, exist_ok=True)
    W, tau, B, n = plan.workers, plan.tau, plan.batch, len(xs)
    for r in range(plan.rounds):
        path = os.path.join(chunk_dir, chunk_name(r))
        if os.path.exists(path):
            continue
        data = np.empty((W, tau) + xs[0].shape, np.float32)
        label = np.empty((W, tau, B), np.float32)
        for w in range(W):
            for t in range(tau):
                i = (r * W * tau + w * tau + t) % n
                data[w, t] = xs[i]
                label[w, t] = ys[i]
        from sparknet_tpu.data.chunk_cache import atomic_write_bytes

        buf = _io.BytesIO()
        np.savez(buf, data=data, label=label)
        atomic_write_bytes(path, buf.getvalue())


def corrupt_file(path: str, seed: int = 0) -> None:
    """Flip a run of bytes in the middle of ``path`` (size unchanged —
    only a checksum can catch it; truncation is the easy case)."""
    size = os.path.getsize(path)
    rng = random.Random(seed)
    with open(path, "r+b") as f:
        off = max(0, size // 2 - 8)
        f.seek(off)
        orig = f.read(16)
        f.seek(off)
        f.write(bytes((b ^ 0xA5) for b in orig) or bytes([rng.randrange(256)]))


class _CollectorOutage:
    """The collector_outage fault: a live fleet collector + this
    process's shipper, with the collector torn down for a planned span
    of rounds.  ``on_round_end`` drives pause/resume by absolute round
    (fires once — resume replays can't re-trip it); ``finalize`` stops
    the shipper (final tail flush) and judges survival: the collector
    missed pushes while down, yet ended with every enqueued event
    delivered (0 lost, 0 dropped)."""

    def __init__(self, plan: FaultPlan, counters: Dict, note):
        from sparknet_tpu.obs import trace as _trace
        from sparknet_tpu.obs.fleet import FleetCollector
        from sparknet_tpu.obs.ship import Shipper

        self.plan = plan
        self.counters = counters
        self.note = note
        self.collector = FleetCollector(port=0).start()
        self.shipper = Shipper(
            self.collector.url, host="chaos-host", interval_s=0.1
        ).start()
        # a surrounding --ship_to run's shipper is restored on close —
        # the chaos-local shipper must not permanently steal the hook
        self._prev_ship = _trace._ship
        _obs.set_ship(self.shipper)
        self._down_at: Optional[int] = plan.collector_outage_round
        self._up_at = (
            plan.collector_outage_round + plan.collector_outage_rounds
        )
        self._received_at_pause: Optional[int] = None
        self.summary: Optional[Dict] = None

    def _host_state(self) -> Dict:
        return self.collector.fleet_view()["hosts"].get("chaos-host", {})

    def on_round_end(self, r: int) -> None:
        if self._down_at is not None and r == self._down_at:
            self._down_at = None
            self._received_at_pause = self._host_state().get(
                "received_events", 0
            )
            self.collector.pause()
            self.counters["collector_outage_injected"] = 1
            _obs.fault(
                "collector_outage", round=r,
                down_rounds=self.plan.collector_outage_rounds,
            )
            self.note(
                "round %d: fleet collector DOWN for %d round(s) — "
                "shipper must buffer and replay"
                % (r, self.plan.collector_outage_rounds)
            )
        elif self._up_at is not None and r >= self._up_at:
            self._up_at = None
            self.collector.resume()
            self.note(f"round {r}: fleet collector back up")

    def finalize(self) -> Dict:
        if self._up_at is not None:  # run ended while still down
            self._up_at = None
            self.collector.resume()
        failures = self.shipper.push_failures_total
        self.shipper.stop()  # final flush ships the buffered tail
        st = self._host_state()
        received = st.get("received_events", 0)
        replayed = received - (self._received_at_pause or 0)
        lost = st.get("lost_events", 0)
        dropped = st.get("reported_dropped_total", 0)
        survived = bool(
            self.counters.get("collector_outage_injected")
            and failures > 0  # the outage really made pushes fail
            and lost == 0
            and dropped == 0
            and replayed > 0
        )
        if survived:
            self.counters["collector_outage_survived"] = 1
            self.note(
                "collector outage survived: %d push failure(s) while "
                "down, %d event(s) replayed after resume, 0 lost / 0 "
                "dropped" % (failures, replayed)
            )
            _obs.instant(
                "recovered", kind="collector_outage", replayed=replayed
            )
        self.summary = {
            "push_failures": failures,
            "events_replayed_after_resume": replayed,
            "events_received": received,
            "events_lost": lost,
            "events_dropped": dropped,
        }
        return self.summary

    def close(self) -> None:
        from sparknet_tpu.obs import trace as _trace

        if _trace._ship is self.shipper:
            _obs.set_ship(self._prev_ship)
        if self.shipper.alive:
            self.shipper.stop()
        self.collector.close()


# deploy view of the serving-fleet fault fixture: tiny net, tiny input,
# two buckets — the fleet compiles in seconds on the chaos box
_SERVE_TOY_DEPLOY = """
name: "chaos_toy"
input: "data"
input_shape { dim: 2 dim: 3 dim: 8 dim: 8 }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "logits"
  inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "logits" top: "prob" }
"""


class _ServeFaults:
    """The serving-fleet faults: ``replica_death``,
    ``published_snapshot_corrupt`` and ``decode_replica_kill``, run as
    bounded sub-scenarios at seeded round boundaries (fire once, by
    absolute round — a post-resume replay can't re-fire them).  The
    fleet is a real ``serve/fleet.py`` pool (2 replicas, toy net)
    built lazily on first use; the corrupt-publish leg publishes the
    ACTUAL training state of the chaos run through ``serve/publish.py``
    and corrupts the published model bytes; the decode-kill leg runs a
    SEPARATE 2-replica generation fleet (tiny TransformerLM under
    continuous batching) and kills the replica serving a live token
    stream."""

    def __init__(self, plan: FaultPlan, counters: Dict, note, workdir: str):
        self.plan = plan
        self.counters = counters
        self.note = note
        self.workdir = workdir
        self._death_at = plan.replica_death_round
        self._corrupt_at = plan.publish_corrupt_round
        self._decode_kill_at = plan.decode_replica_kill_round
        self._pool = None
        self._router = None
        self._gen_pool = None
        self._gen_router = None
        self._x = np.random.RandomState(plan.seed).randn(
            1, 3, 8, 8
        ).astype(np.float32)

    def _fleet(self):
        if self._pool is None:
            from sparknet_tpu import config as _cfg
            from sparknet_tpu.serve import (
                InferenceEngine, ReplicaPool, Router,
            )

            netp = _cfg.parse_net_prototxt(_SERVE_TOY_DEPLOY)

            def make_engine(weights=None):
                return InferenceEngine(
                    netp, weights=weights, buckets=(1, 2)
                )

            self._pool = ReplicaPool(make_engine, replicas=2, max_queue=32)
            self._router = Router(self._pool, max_inflight=16)
        return self._pool, self._router

    def _gen_fleet(self):
        if self._gen_pool is None:
            from sparknet_tpu.models.transformer_lm import TransformerLM
            from sparknet_tpu.serve import ReplicaPool, Router
            from sparknet_tpu.serve.generate import GenerationEngine

            def make_engine(weights=None):
                lm = TransformerLM(
                    dim=32, depth=2, heads=2, seq_len=64, vocab=64
                )
                return GenerationEngine(
                    lm, weights=weights, prefill_buckets=(16, 64),
                    max_streams=4, kv_blocks=48, kv_block_size=8,
                    seed=self.plan.seed,
                )

            self._gen_pool = ReplicaPool(
                make_engine, replicas=2, max_queue=16, stream=True
            )
            self._gen_router = Router(self._gen_pool, max_inflight=16)
        return self._gen_pool, self._gen_router

    def on_round_end(self, r: int, solver, host_state_fn) -> None:
        if self._death_at is not None and r == self._death_at:
            self._death_at = None
            self._replica_death(r)
        if self._decode_kill_at is not None and r == self._decode_kill_at:
            self._decode_kill_at = None
            self._decode_replica_kill(r)
        if self._corrupt_at is not None and r == self._corrupt_at:
            self._corrupt_at = None
            self._publish_corrupt(r, solver, host_state_fn)

    def _replica_death(self, r: int) -> None:
        pool, router = self._fleet()
        router.submit(self._x)  # fleet proven serving before the kill
        self.counters["replica_death_injected"] = 1
        _obs.fault("replica_death", round=r, replica=0)
        self.note(f"round {r}: serving replica 0 hard-killed mid-traffic")
        pool.replicas[0].kill()
        served = 0
        for _ in range(4):
            out = router.submit(self._x)  # eject-and-retry: no errors
            served += int(out.shape[0] == 1)
        ejected = pool.replicas[0].state == "ejected"
        pool.respawn(0)
        rejoined = pool.replicas[0].state == "live"
        router.submit(self._x)
        if served == 4 and ejected and rejoined:
            self.counters["replica_death_survived"] = 1
            self.note(
                f"round {r}: router ejected the dead replica, served "
                "every request on the survivor, and the respawned "
                "replica rejoined rotation"
            )
            _obs.instant("recovered", kind="replica_death", round=r)

    def _decode_replica_kill(self, r: int) -> None:
        pool, router = self._gen_fleet()
        prompt = [5, 9, 2, 7]
        max_new = 40
        # greedy decode is deterministic: an undisturbed run on either
        # replica (identical seeded weights) is the expected sequence
        expect = list(router.submit_stream(prompt, max_new))[-1]
        self.counters["decode_kill_injected"] = 1
        _obs.fault("decode_replica_kill", round=r)
        gen = router.submit_stream(prompt, max_new, timeout=30.0)
        first = next(gen)  # stream admitted + first token delivered
        victim = None
        for rep in pool.replicas:
            if rep.batcher.active_count() > 0:
                victim = rep
                break
        self.note(
            f"round {r}: generation replica "
            f"{victim.index if victim else '?'} hard-killed with a "
            "token stream in flight"
        )
        if victim is not None:
            victim.kill()
        events = [first] + list(gen)  # bounded by timeout: never hangs
        final = events[-1]
        ejected = (
            victim is not None and victim.state == "ejected"
        )
        if victim is not None and ejected:
            pool.respawn(victim.index)
        # respawn REPLACES the Replica object — re-read from the pool
        rejoined = (
            victim is not None
            and pool.replicas[victim.index].state == "live"
        )
        after = list(router.submit_stream(prompt, max_new))[-1]
        if (
            expect["event"] == "done"
            and final["event"] == "done"
            and final["tokens"] == expect["tokens"]
            and ejected
            and rejoined
            and after["event"] == "done"
            and after["tokens"] == expect["tokens"]
        ):
            self.counters["decode_kill_survived"] = 1
            self.note(
                f"round {r}: stream resumed on the sibling via "
                "re-prefill — token sequence IDENTICAL to the "
                "undisturbed run, dead replica respawned into rotation"
            )
            _obs.instant("recovered", kind="decode_replica_kill", round=r)

    def _publish_corrupt(self, r: int, solver, host_state_fn) -> None:
        from sparknet_tpu.serve import DeliveryController
        from sparknet_tpu.serve import publish as publish_mod

        pub = os.path.join(self.workdir, "publish")
        paths = publish_mod.publish_snapshot(
            solver, host_state_fn(), pub,
            {"passing": True, "reason": "chaos seeded publish"},
        )
        corrupt_file(paths[0], seed=self.plan.seed)
        self.counters["publish_corrupt_injected"] = 1
        _obs.fault(
            "published_snapshot_corrupt", round=r,
            snapshot=os.path.basename(paths[0]),
        )
        self.note(
            f"round {r}: published snapshot "
            f"{os.path.basename(paths[0])} byte-flipped on disk"
        )
        pool, router = self._fleet()
        ctl = DeliveryController(
            pool, router, pub,
            cache_dir=os.path.join(self.workdir, "delivery_cache"),
            decision_requests=2, echo=None,
        )
        act = ctl.poll_once()
        quarantined = (ctl.last_decision or {}).get("quarantined", [])
        if (
            act == "rejected"
            and ctl.rejected == 1
            and router.canary is None  # it never reached a canary
            and any(q.endswith(".corrupt") for q in quarantined)
        ):
            self.counters["publish_corrupt_survived"] = 1
            self.note(
                f"round {r}: delivery watcher REJECTED the corrupt "
                "publish at CRC verify and quarantined it "
                "(never canaried)"
            )
            _obs.instant(
                "recovered", kind="published_snapshot_corrupt", round=r
            )

    def close(self) -> None:
        if self._router is not None:
            self._router.close()
            self._router = None
            self._pool = None
        if self._gen_router is not None:
            self._gen_router.close()
            self._gen_router = None
            self._gen_pool = None


def _driver_kill_scenario(plan: FaultPlan, counters: Dict, note, workdir):
    """The driver_kill fault: crash a journaled mini-driver mid-commit
    and prove bit-identical journal-guided recovery (in-process — the
    kill hook raises instead of SIGKILLing so the chaos harness
    survives; ``run_kill_sweep`` is the real-SIGKILL version)."""
    from sparknet_tpu.runtime import recover as recover_mod

    base = os.path.join(workdir, "driver_kill")
    ctx = recover_mod.RecoverContext(
        base, workers=2, tau=1, batch=8, seed=plan.seed
    )
    kill_rounds = 3
    kill_at = ("journal_mid_append", 1)

    def boom():
        raise recover_mod.SimulatedKill("driver_kill")

    control = recover_mod.run_driver(
        ctx, kill_rounds, run_dir=os.path.join(base, "control")
    )
    counters["driver_kill_injected"] = 1
    _obs.fault(
        "driver_kill", kill_at="%s:%d" % kill_at, rounds=kill_rounds
    )
    note(
        "driver_kill: journaled driver crashed mid-commit-append at "
        "round %d (half a frame durable on disk)" % kill_at[1]
    )
    fault_dir = os.path.join(base, "fault")
    crashed = False
    try:
        recover_mod.run_driver(
            ctx, kill_rounds, kill_at=kill_at, kill=boom,
            run_dir=fault_dir,
        )
    except recover_mod.SimulatedKill:
        crashed = True
    resumed = recover_mod.run_driver(
        ctx, kill_rounds, resume=True, run_dir=fault_dir
    )
    # the crashed run executed rounds 0..kill_at[1]; anything the
    # resume re-executes in that range is a replay
    replayed = len(
        [r for r in resumed["rounds_executed"] if r <= kill_at[1]]
    )
    bit_identical = resumed["final_digest"] == control["final_digest"]
    survived = bool(
        crashed
        and resumed["journal_truncated_bytes"] > 0  # tail really torn
        and replayed <= 1
        and bit_identical
    )
    if survived:
        counters["driver_kill_survived"] = 1
        note(
            "driver_kill survived: torn tail truncated (%d bytes), "
            "resumed at round %d replaying %d round(s), final state "
            "digest BIT-IDENTICAL to the uninterrupted control"
            % (
                resumed["journal_truncated_bytes"],
                resumed["start_round"], replayed,
            )
        )
        _obs.instant(
            "recovered", kind="driver_kill", replayed=replayed,
        )
    return {
        "kill_at": "%s:%d" % kill_at,
        "crashed": crashed,
        "journal_truncated_bytes": resumed["journal_truncated_bytes"],
        "resumed_start_round": resumed["start_round"],
        "replayed_rounds": replayed,
        "bit_identical": bit_identical,
        "control_digest": control["final_digest"],
        "resumed_digest": resumed["final_digest"],
        "recovery_latency_s": resumed["restore_s"],
    }


def _slow_slice_scenario(plan: FaultPlan, counters: Dict, note, workdir):
    """The slow_slice fault: one whole slice runs ``+slow_slice_s`` per
    round for ``slow_slice_rounds`` consecutive rounds, and the
    question is what that tail COSTS.  Two bounded legs over the same
    solver/mesh (a ``runtime/recover.py`` context, two-tier hierarchy):

    - sync control (``ParameterAveragingTrainer``): every averaging
      boundary waits for the slow slice, so the job pays the full
      K x slow_s tail straight onto the critical path;
    - stale leg (``BoundedStalenessTrainer``, bound > K): the boundary
      takes whoever arrived; the slow slice goes stale (coarsened as a
      unit) and folds in after its tail clears, so the harness never
      sleeps on its behalf — the ONLY thing that can put the tail back
      on the critical path is the bound forcing a still-slow worker.

    Survived = zero forced waits in the stale leg, its measured
    wall-clock undercuts the sync control by most of the injected
    tail, the staleness ledger names a slow-slice member as the
    laggiest worker on every slow round (the fleet side can still
    point at the exact straggler), and the two final losses agree
    within the band (the speed is not bought with divergence)."""
    from sparknet_tpu.parallel import (
        BoundedStalenessTrainer,
        ParameterAveragingTrainer,
        shard_leading,
        stale_window,
    )
    from sparknet_tpu.parallel.hierarchy import HierarchySpec
    from sparknet_tpu.runtime import recover as recover_mod

    base = os.path.join(workdir, "slow_slice")
    ctx = recover_mod.RecoverContext(
        base, workers=plan.workers, tau=1, batch=plan.batch,
        seed=plan.seed, compress="none",
    )
    spec = HierarchySpec.grouped(
        plan.workers, plan.membership_slices,
        cross_slice_every=plan.cross_slice_every,
    )
    slow_members = tuple(spec.slices[plan.slow_slice_slice])
    K, slow_s = plan.slow_slice_rounds, plan.slow_slice_s
    B = plan.slow_slice_stale_bound
    rounds = max(6, K + 3)
    slow_rounds = set(range(1, 1 + K))

    counters["slow_slice_injected"] = 1
    _obs.fault(
        "slow_slice", slice=plan.slow_slice_slice,
        workers=list(slow_members), tail_s=slow_s, rounds=K,
    )
    note(
        "slow_slice: slice %d (workers %s) +%.2fs/round for rounds %s "
        "— sync control vs stale_bound=%d A/B"
        % (plan.slow_slice_slice, list(slow_members), slow_s,
           sorted(slow_rounds), B)
    )

    def leg(stale_bound: int) -> Dict:
        if stale_bound > 0:
            trainer = BoundedStalenessTrainer(
                ctx.solver, ctx.mesh, stale_bound=stale_bound,
                hierarchy=spec,
            )
        else:
            trainer = ParameterAveragingTrainer(
                ctx.solver, ctx.mesh, hierarchy=spec
            )
        state = trainer.init_state(seed=ctx.seed)
        tail_paid_s = 0.0
        forced_waits = 0
        laggiest = []
        last_losses = None
        compute_s = []  # per-round wall-clock minus this round's sleeps
        slept_s = 0.0
        for r in range(rounds):
            slow_now = r in slow_rounds
            slept_before = tail_paid_s
            t0 = time.perf_counter()
            if stale_bound > 0:
                arrived = np.ones((plan.workers,), bool)
                if slow_now:
                    arrived[list(slow_members)] = False
                    lag = trainer.lags(r)
                    if int(lag[list(slow_members)].max()) >= stale_bound:
                        # a forced arrival of a still-slow worker: the
                        # bound puts the tail back on the critical path
                        forced_waits += 1
                        tail_paid_s += slow_s
                        time.sleep(slow_s)
                state, losses, _ = trainer.round(
                    state,
                    shard_leading(
                        stale_window(ctx.batch_for, trainer.worker_rounds),
                        ctx.mesh,
                    ),
                    arrived=arrived, round_index=r,
                )
                if slow_now:
                    # post-round attribution: the ledger's laggiest
                    # worker must be a slow-slice member
                    laggiest.append(int(np.argmax(trainer.lags(r + 1))))
            else:
                if slow_now:
                    # the synchronous boundary cannot proceed without
                    # the slow slice: the whole job eats the tail
                    tail_paid_s += slow_s
                    time.sleep(slow_s)
                state, losses, _ = trainer.round(
                    state, shard_leading(ctx.batch_for(r), ctx.mesh),
                    round_index=r,
                )
            losses = np.asarray(losses)
            if r > 0:  # round 0 carries the jit compile
                dt = time.perf_counter() - t0
                round_slept = tail_paid_s - slept_before
                compute_s.append(dt - round_slept)
                slept_s += round_slept
            last_losses = losses
        # One shared CPU core and a possible mid-leg recompile or GC
        # pause can put a one-off multi-hundred-ms spike on a single
        # round and swamp the A/B; trim each leg's single worst compute
        # round (symmetric across legs) and add the sleeps back exactly.
        trimmed = sorted(compute_s)[:-1] if len(compute_s) > 1 else (
            compute_s
        )
        elapsed = sum(trimmed) + slept_s
        finite = last_losses[np.isfinite(last_losses)]
        return {
            "elapsed_s": round(elapsed, 3),
            "tail_paid_s": round(tail_paid_s, 3),
            "forced_waits": forced_waits,
            "final_loss": round(float(np.mean(finite)), 4),
            "laggiest_by_slow_round": laggiest,
        }

    sync = leg(0)
    stale = leg(B)
    tail_injected_s = K * slow_s
    saved_s = sync["elapsed_s"] - stale["elapsed_s"]
    named_ok = bool(stale["laggiest_by_slow_round"]) and all(
        w in slow_members for w in stale["laggiest_by_slow_round"]
    )
    band = max(0.5, 0.5 * abs(sync["final_loss"]))
    loss_band_ok = (
        abs(stale["final_loss"] - sync["final_loss"]) <= band
    )
    survived = bool(
        stale["forced_waits"] == 0
        and sync["tail_paid_s"] >= tail_injected_s - 1e-9
        and saved_s >= 0.6 * tail_injected_s
        and named_ok
        and loss_band_ok
    )
    if survived:
        counters["slow_slice_survived"] = 1
        note(
            "slow_slice survived: stale leg paid 0 forced waits and "
            "saved %.2fs of the %.2fs injected tail (sync control ate "
            "all of it); laggiest worker named in %s every slow round; "
            "final losses %.4f vs %.4f within band %.4f"
            % (saved_s, tail_injected_s, list(slow_members),
               stale["final_loss"], sync["final_loss"], band)
        )
        _obs.instant(
            "stale_absorbed_tail", kind="slow_slice",
            saved_s=round(saved_s, 3),
        )
    return {
        "slice": plan.slow_slice_slice,
        "workers": list(slow_members),
        "tail_s_per_round": slow_s,
        "slow_rounds": sorted(slow_rounds),
        "stale_bound": B,
        "rounds": rounds,
        "tail_injected_s": round(tail_injected_s, 3),
        "sync": sync,
        "stale": stale,
        "wallclock_saved_s": round(saved_s, 3),
        "straggler_named_ok": named_ok,
        "loss_band": round(band, 4),
        "loss_band_ok": loss_band_ok,
        "survived": survived,
    }


def run_kill_sweep(
    workdir: Optional[str] = None,
    rounds: int = 4,
    kill_round: int = 2,
    workers: int = 2,
    tau: int = 2,
    batch: int = 8,
    seed: int = 7,
    kill_points: Optional[Tuple[str, ...]] = None,
    timeout_s: float = 900.0,
    echo=None,
) -> Dict:
    """The kill-anywhere chaos sweep (``bench.py --mode=recover``):
    for every phase boundary of the journaled driver loop
    (``runtime/recover.py``), a REAL ``SIGKILL`` is delivered at that
    exact point of a subprocess run, the process is relaunched with
    ``--resume``, and the resumed trajectory is judged against an
    uninterrupted control:

    - ``bit_identical``: the full-job-state digest (params, history,
      iter, EF residuals, sentry EMA) equals the control's,
    - ``replayed_rounds``: rounds the resume re-executed that the
      killed run had already executed — must be 0 or 1 (exactly-once
      at snapshot granularity; the loop snapshots every boundary),
    - latency: the resume's restore/reconcile time.

    Plus the two controls that keep the proof honest: a ``--no_journal``
    kill+resume that must DIVERGE (the journaled state really is
    load-bearing), and a journal-off uninterrupted run whose digest
    must EQUAL the control's (the ledger itself never perturbs the
    math) — also the overhead A/B baseline."""
    import json as _json
    import subprocess
    import sys as _sys

    from sparknet_tpu.runtime import recover as recover_mod

    # stale_boundary only exists on a --stale_bound > 0 driver — the
    # dedicated stale leg below kills it under the right flags; in the
    # synchronous sweep the child would refuse the phase at argparse
    kill_points = tuple(
        kp
        for kp in (kill_points or recover_mod.KILL_POINTS)
        if kp != "stale_boundary"
    )
    workdir = workdir or tempfile.mkdtemp(prefix="recover_sweep_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_args = [
        "--rounds", str(rounds), "--workers", str(workers),
        "--tau", str(tau), "--batch", str(batch), "--seed", str(seed),
    ]

    def say(msg: str) -> None:
        if echo is not None:
            echo("recover: " + msg)

    def child(wd: str, *extra: str):
        cmd = (
            [_sys.executable, "-m", "sparknet_tpu.runtime.recover",
             "--workdir", wd]
            + base_args + list(extra)
        )
        t0 = time.perf_counter()
        proc = subprocess.run(
            cmd, capture_output=True, text=True, env=env,
            timeout=timeout_s,
        )
        rec = None
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    rec = _json.loads(line)
                    break
            if rec is None:
                raise RuntimeError(
                    "recover child printed no JSON:\n%s\n%s"
                    % (proc.stdout[-2000:], proc.stderr[-2000:])
                )
        return proc.returncode, rec, time.perf_counter() - t0

    say("control run (journal on, no kill)")
    rc, control, _ = child(os.path.join(workdir, "control"))
    if rc != 0:
        raise RuntimeError(f"recover control run failed (rc {rc})")
    say("journal-off control (overhead baseline + bit-neutrality)")
    rc, nojournal_full, _ = child(
        os.path.join(workdir, "nojournal_full"), "--no_journal"
    )
    if rc != 0:
        raise RuntimeError(f"recover no-journal run failed (rc {rc})")

    results = []
    for kp in kill_points:
        wd = os.path.join(workdir, "kill_" + kp)
        say(f"SIGKILL at {kp}:{kill_round} -> resume")
        rc1, _, _ = child(wd, "--kill_at", f"{kp}:{kill_round}")
        killed = rc1 != 0  # SIGKILL: -9 from subprocess.run
        rc2, rec, _ = child(wd, "--resume")
        # rounds the killed run had already EXECUTED: the kill fires
        # before trainer.round for assemble/h2d, after it otherwise
        executed_before = kill_round + (
            0 if kp in ("assemble", "h2d") else 1
        )
        row = {
            "kill_at": f"{kp}:{kill_round}",
            "killed": killed,
            "resumed_rc": rc2,
            "bit_identical": bool(
                rec and rec["final_digest"] == control["final_digest"]
            ),
            "replayed_rounds": (
                len([
                    r for r in rec["rounds_executed"]
                    if r < executed_before
                ])
                if rec else None
            ),
            "recovery_latency_s": rec["restore_s"] if rec else None,
            "resumed_from": rec["resumed_from"] if rec else None,
            "start_round": rec["start_round"] if rec else None,
            "journal_truncated_bytes": (
                rec["journal_truncated_bytes"] if rec else None
            ),
        }
        row["survived"] = bool(
            row["killed"]
            and rc2 == 0
            and row["bit_identical"]
            and row["replayed_rounds"] is not None
            and row["replayed_rounds"] <= 1
        )
        say(
            "%s: %s (replayed %s, latency %ss)"
            % (
                row["kill_at"],
                "SURVIVED bit-identical" if row["survived"] else
                "FAILED " + _json.dumps(row),
                row["replayed_rounds"], row["recovery_latency_s"],
            )
        )
        results.append(row)

    # the non-vacuous control: the SAME kill without the journal must
    # visibly diverge (plain newest-snapshot resume resets the EF
    # residuals and per-worker momentum)
    say(f"no-journal divergence control: SIGKILL at average:{kill_round}")
    wd = os.path.join(workdir, "nojournal_kill")
    rc1, _, _ = child(wd, "--no_journal", "--kill_at",
                      f"average:{kill_round}")
    rc2, njrec, _ = child(wd, "--no_journal", "--resume")
    no_journal_diverged = bool(
        rc1 != 0 and rc2 == 0 and njrec
        and njrec["final_digest"] != control["final_digest"]
    )
    say(
        "no-journal resume %s the control"
        % ("DIVERGED from" if no_journal_diverged else
           "unexpectedly matched")
    )

    # the bounded-staleness leg: the SAME SIGKILL discipline on an
    # async driver (--stale_bound), killed at the stale_boundary phase
    # — the arrival set has folded and the worker-round ledger advanced
    # in memory, but neither the snapshot nor the commit record landed.
    # Resume must rewind to the journaled per-worker round vector and
    # replay at most stale_bound rounds, bit-identically against an
    # uninterrupted stale control.
    stale_bound = 2
    stale_args = ("--stale_bound", str(stale_bound))
    say(f"stale control run (stale_bound={stale_bound}, no kill)")
    rc, stale_control, _ = child(
        os.path.join(workdir, "stale_control"), *stale_args
    )
    if rc != 0:
        raise RuntimeError(f"stale recover control failed (rc {rc})")
    wd = os.path.join(workdir, "kill_stale_boundary")
    say(f"SIGKILL at stale_boundary:{kill_round} -> resume")
    rc1, _, _ = child(
        wd, *stale_args, "--kill_at", f"stale_boundary:{kill_round}"
    )
    rc2, srec, _ = child(wd, *stale_args, "--resume")
    stale_replayed = (
        len([r for r in srec["rounds_executed"] if r <= kill_round])
        if srec else None
    )
    stale_row = {
        "kill_at": f"stale_boundary:{kill_round}",
        "stale_bound": stale_bound,
        "killed": rc1 != 0,
        "resumed_rc": rc2,
        "bit_identical": bool(
            srec
            and srec["final_digest"] == stale_control["final_digest"]
        ),
        "replayed_rounds": stale_replayed,
        "recovery_latency_s": srec["restore_s"] if srec else None,
        "start_round": srec["start_round"] if srec else None,
        "journal_truncated_bytes": (
            srec["journal_truncated_bytes"] if srec else None
        ),
        "resumed_worker_rounds": (
            (srec.get("resume_info") or {}).get("worker_rounds")
            if srec else None
        ),
        "final_worker_rounds": (
            srec.get("worker_rounds") if srec else None
        ),
    }
    stale_row["survived"] = bool(
        stale_row["killed"]
        and rc2 == 0
        and stale_row["bit_identical"]
        and stale_replayed is not None
        and stale_replayed <= stale_bound
    )
    say(
        "stale_boundary:%d %s (replayed %s <= bound %d, latency %ss)"
        % (
            kill_round,
            "SURVIVED bit-identical" if stale_row["survived"] else
            "FAILED " + _json.dumps(stale_row),
            stale_row["replayed_rounds"], stale_bound,
            stale_row["recovery_latency_s"],
        )
    )

    def p50(xs):
        s = sorted(xs)
        return s[len(s) // 2] if s else None

    # steady rounds only: round 0 carries the jit compile
    j_ms = p50(control["round_ms"][1:])
    nj_ms = p50(nojournal_full["round_ms"][1:])
    overhead_pct = (
        100.0 * (j_ms - nj_ms) / nj_ms if j_ms and nj_ms else None
    )
    return {
        "rounds": rounds,
        "workers": workers,
        "tau": tau,
        "batch": batch,
        "seed": seed,
        "kill_round": kill_round,
        "killpoints_total": len(results),
        "killpoints_survived": sum(
            1 for r in results if r["survived"]
        ),
        "killpoints": results,
        "bit_identical_all": all(r["bit_identical"] for r in results),
        "max_replayed_rounds": max(
            (r["replayed_rounds"] for r in results
             if r["replayed_rounds"] is not None),
            default=None,
        ),
        "control_digest": control["final_digest"],
        "stale": stale_row,
        "stale_control_digest": stale_control["final_digest"],
        "no_journal_diverged": no_journal_diverged,
        "no_journal_digest": njrec["final_digest"] if njrec else None,
        "journal_bit_neutral": bool(
            nojournal_full["final_digest"] == control["final_digest"]
        ),
        "journal_round_ms_p50": round(j_ms, 2) if j_ms else None,
        "nojournal_round_ms_p50": round(nj_ms, 2) if nj_ms else None,
        "journal_overhead_pct": (
            round(overhead_pct, 2) if overhead_pct is not None else None
        ),
        "workdir": workdir,
    }


# ----------------------------------------------------------------------
# the chaos training run


class _Feed:
    """Deterministic per-round window builder behind the pipelined
    ``RoundFeed`` executor (assembly + dp-sharded device_put on the
    producer thread — the same executor the apps and ``cli train``
    run), with storage faults (transient errors healed by retry) and
    stalls (producer wedges past the watchdog) injected per plan."""

    def __init__(self, plan: FaultPlan, xs, ys, counters, events, mesh,
                 fault_state=None, chunk_source=None):
        self.plan = plan
        self.xs, self.ys = xs, ys
        self.counters = counters
        self.events = events
        self.mesh = mesh
        # chunk_source: (store, cache) — the round windows then arrive
        # as npz chunks read THROUGH the content-addressed chunk cache
        # (data/chunk_cache.py), which is what the cache_corruption /
        # cache_cold faults attack.  None keeps the direct in-memory
        # build (unit tests).
        self._store, self._cache = chunk_source or (None, None)
        # fault state is SHARED across prefetcher/feed rebuilds (resume
        # replays rounds by absolute index; a per-round fault fires once)
        fault_state = fault_state if fault_state is not None else {}
        fault_state.setdefault("faults", {r: n for r, n in plan.storage_faults})
        fault_state.setdefault("stalls", set(plan.stall_rounds))
        fault_state.setdefault(
            "nans",
            set() if plan.nan_round is None else {plan.nan_round},
        )
        fault_state.setdefault(
            "stragglers",
            set() if plan.straggler_round is None else {plan.straggler_round},
        )
        fault_state.setdefault(
            "cache_corrupts",
            set() if plan.cache_corrupt_round is None
            else {plan.cache_corrupt_round},
        )
        fault_state.setdefault(
            "cache_colds",
            set() if plan.cache_cold_round is None
            else {plan.cache_cold_round},
        )
        self._faults = fault_state["faults"]
        self._stalls = fault_state["stalls"]
        self._nans = fault_state["nans"]
        self._stragglers = fault_state["stragglers"]
        self._cache_corrupts = fault_state["cache_corrupts"]
        self._cache_colds = fault_state["cache_colds"]
        self._rf = None
        self._policy = _retry.RetryPolicy(
            max_attempts=6, base_s=0.005, cap_s=0.02, budget_s=2.0
        )

    def _chunk_arrays(self, r: int):
        """Round ``r``'s clean window arrays read THROUGH the chunk
        cache, with the seeded cache faults applied first.  The
        corruption verdict requires all three: quarantine evidence on
        disk, a transparent refetch, and bytes identical to a direct
        store read."""
        import io as _io

        name = chunk_name(r)
        if r in self._cache_corrupts:
            self._cache_corrupts.discard(r)
            # ensure the entry is published, then flip bytes in the
            # PUBLISHED chunk (size unchanged — only the CRC32 in the
            # entry manifest can catch it)
            self._cache.get(self._store, name)
            entry = self._cache.entry_path(self._store.url, name)
            corrupt_file(entry, seed=self.plan.seed)
            self.counters["cache_corrupt_injected"] = (
                self.counters.get("cache_corrupt_injected", 0) + 1
            )
            self.events.append(
                f"round {r}: cache entry for {name} byte-flipped on disk"
            )
            _obs.fault("cache_corruption", round=r, chunk=name)
            q_before = self._cache.stats["quarantined"]
            blob = self._cache.get(self._store, name)
            direct = self._store.read(name)
            if (
                self._cache.stats["quarantined"] == q_before + 1
                and blob == direct
            ):
                self.counters["cache_corrupt_survived"] = (
                    self.counters.get("cache_corrupt_survived", 0) + 1
                )
                self.events.append(
                    f"round {r}: cache quarantined the corrupt entry "
                    "(*.corrupt) and refetched byte-identical data"
                )
                _obs.instant("recovered", kind="cache_corruption", round=r)
        elif r in self._cache_colds:
            self._cache_colds.discard(r)
            dropped = self._cache.clear()
            self.counters["cache_cold_injected"] = (
                self.counters.get("cache_cold_injected", 0) + 1
            )
            self.events.append(
                f"round {r}: cache wiped cold ({dropped} entries dropped)"
            )
            _obs.fault("cache_cold", round=r, entries_dropped=dropped)
            m_before = self._cache.stats["misses"]
            blob = self._cache.get(self._store, name)
            if self._cache.stats["misses"] == m_before + 1:
                self.counters["cache_cold_survived"] = (
                    self.counters.get("cache_cold_survived", 0) + 1
                )
                self.events.append(
                    f"round {r}: cold read missed and refetched from "
                    "the backing store"
                )
                _obs.instant("recovered", kind="cache_cold", round=r)
        else:
            blob = self._cache.get(self._store, name)
        with np.load(_io.BytesIO(blob)) as z:
            return z["data"], z["label"]

    def _build(self, r: int):
        p, W, tau, B = self.plan, self.plan.workers, self.plan.tau, self.plan.batch
        n = len(self.xs)
        src = self._chunk_arrays(r) if self._cache is not None else None
        straggle = None
        if r in self._stragglers:
            # straggler_injection: the planned worker's assembly sleeps
            # — a slow host partition / degraded chip stand-in.  The
            # per-worker timing hook below attributes it; the round
            # profiler's verdict must name exactly this worker.
            self._stragglers.discard(r)
            straggle = self.plan.straggler_worker
            self.counters["straggler_injected"] = (
                self.counters.get("straggler_injected", 0) + 1
            )
            self.events.append(
                "round %d: worker %d straggles %.2fs in assembly"
                % (r, straggle, self.plan.straggler_s)
            )
            _obs.fault(
                "straggler_injection", round=r, worker=straggle,
                straggler_s=self.plan.straggler_s,
            )
        data = np.empty((W, tau) + self.xs[0].shape, np.float32)
        label = np.empty((W, tau, B), np.float32)
        worker_s = []
        for w in range(W):
            t0 = time.perf_counter()
            if straggle == w:
                time.sleep(self.plan.straggler_s)
            if src is not None:
                # chunk path: the same arrays, via the cached chunk
                # (per-worker copy keeps the timing attribution honest)
                data[w] = src[0][w]
                label[w] = src[1][w]
            else:
                for t in range(tau):
                    i = (r * W * tau + w * tau + t) % n
                    data[w, t] = self.xs[i]
                    label[w, t] = self.ys[i]
            worker_s.append(time.perf_counter() - t0)
        # per-worker assemble attribution (no-op without a profiler)
        _profile.note_worker_phase(r, "assemble", worker_s)
        if r in self._nans:
            # poison the planned workers' batches with NaN — the
            # diverging-worker fault the numerics audit must catch
            # before the parameter average (fires once per plan)
            self._nans.discard(r)
            for w in self.plan.nan_workers:
                data[w] = np.nan
            self.counters["nan_injected"] = (
                self.counters.get("nan_injected", 0) + 1
            )
            self.events.append(
                "round %d: NaN injected into worker(s) %s batch"
                % (r, list(self.plan.nan_workers))
            )
            _obs.fault(
                "nan_injection", round=r,
                workers=list(self.plan.nan_workers),
            )
        return {"data": data, "label": label}

    def _produce_round(self, r: int):
        def attempt():
            if self._faults.get(r, 0) > 0:
                self._faults[r] -= 1
                self.counters["storage_injected"] += 1
                _obs.fault("storage", round=r)
                raise ConnectionResetError(
                    f"chaos: storage fault in round {r} fetch"
                )
            if r in self._stalls:
                self._stalls.discard(r)
                self.counters["stalls_injected"] += 1
                self.events.append(f"round {r}: producer stalled {self.plan.stall_s}s")
                _obs.fault("stall", round=r, stall_s=self.plan.stall_s)
                time.sleep(self.plan.stall_s)
            return self._build(r)

        injected_before = self.counters["storage_injected"]
        out = _retry.retry_call(
            attempt,
            policy=self._policy,
            rng=random.Random(self.plan.seed * 1000 + r),
        )
        healed = self.counters["storage_injected"] - injected_before
        if healed:
            self.counters["storage_survived"] += healed
            self.events.append(
                f"round {r}: retry layer healed {healed} storage fault(s)"
            )
            # fault -> recovery is two tagged instants on the trace
            _obs.instant("recovered", kind="storage", round=r, healed=healed)
        return out

    def _spawn(self, start_r: int):
        from sparknet_tpu.data.round_feed import RoundFeed

        # RoundFeed keeps the round cursor LOCAL to each producer
        # generation (a thread that outlives stop() — a stall longer
        # than the reap timeout — keeps bumping ITS cursor, never the
        # rebuilt generation's: no round can be silently skipped) and
        # issues the dp-sharded device_put on the producer thread
        self._rf = RoundFeed(
            lambda r, out: self._produce_round(r),
            mesh=self.mesh,
            depth=2,
            stall_timeout_s=self.plan.stall_timeout_s,
            start_round=start_r,
        )

    def next_round(self, r: int):
        """The dp-PLACED (workers, tau, ...) batches for absolute round
        ``r``, surviving producer stalls by restarting the feed.  A
        stall counts as survived once the round is DELIVERED — whether
        the watchdog fired and the feed was restarted, or the stall was
        absorbed by the prefetch depth (the producer was far enough
        ahead that training never noticed)."""
        from sparknet_tpu.data.round_feed import PrefetchStall

        if self._rf is None:
            self._spawn(r)
        while True:
            try:
                out = self._rf.next_round(r)
                break
            except PrefetchStall:
                exited = self._rf.restart(r)
                self.counters["watchdog_fires"] = (
                    self.counters.get("watchdog_fires", 0) + 1
                )
                self.events.append(
                    "round %d: watchdog fired; round feed stopped "
                    "(thread exited: %s); rebuilding" % (r, exited)
                )
        if r in self.plan.stall_rounds and r not in self._stalls:
            # this round's planned stall has been consumed and the round
            # still arrived
            if (
                self.counters["stalls_survived"]
                < self.counters["stalls_injected"]
            ):
                self.counters["stalls_survived"] += 1
        return out

    def close(self):
        if self._rf is not None:
            self._rf.stop()
            self._rf = None


def run_chaos(
    plan: Optional[FaultPlan] = None,
    workdir: Optional[str] = None,
    verbose: bool = False,
) -> Dict:
    """Run the full chaos scenario; returns the CHAOS artifact dict.

    Builds one cifar10_quick ParameterAveragingTrainer on the virtual
    mesh, runs the NO-FAULT baseline first (same data, same seed), then
    the faulted run: train -> faults -> SIGHUP preemption -> snapshot ->
    simulated death -> corrupt newest snapshot -> verified resume with
    fallback -> survivor-masked rounds -> final loss vs baseline band."""
    import jax

    from sparknet_tpu import config as cfg, models
    from sparknet_tpu.data import CifarLoader
    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.parallel import (
        ParameterAveragingTrainer,
        first_worker,
        make_mesh,
    )
    from sparknet_tpu.solver import Solver
    from sparknet_tpu.utils.signals import SignalHandler, SolverAction

    plan = plan or FaultPlan.default()
    if jax.device_count() < plan.workers:
        raise RuntimeError(
            f"chaos needs >= {plan.workers} devices (virtual CPU mesh: "
            f"utils.devices.force_virtual_cpu_devices); have "
            f"{jax.device_count()}"
        )
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_")
    os.makedirs(workdir, exist_ok=True)

    events: List[str] = []

    def note(msg: str) -> None:
        events.append(msg)
        if verbose:
            print(f"chaos: {msg}")

    # deterministic learnable data (synthetic CIFAR-format)
    data_dir = os.path.join(workdir, "data")
    if not os.path.isdir(data_dir):
        CifarLoader.write_synthetic(
            data_dir, num_train=512, num_test=64, seed=plan.seed
        )
    xs, ys = CifarLoader(data_dir).minibatches(plan.batch, train=True)

    # the data plane under test: each round's clean window is an npz
    # chunk in a local (file://) store, read THROUGH the content-
    # addressed chunk cache every round — the path the cache_corruption
    # and cache_cold faults attack (both runs use it, so the loss
    # comparison is like-for-like)
    from sparknet_tpu.data import chunk_cache as _chunk_cache
    from sparknet_tpu.data import object_store as _object_store

    chunk_dir = os.path.join(workdir, "chunk_store")
    write_round_chunks(plan, xs, ys, chunk_dir)
    chunk_source = (
        _object_store.LocalStore("file://" + chunk_dir),
        _chunk_cache.ChunkCache(os.path.join(workdir, "chunk_cache")),
    )

    netp = cfg.replace_data_layers(
        models.load_model("cifar10_quick"),
        [(plan.batch, 3, 32, 32), (plan.batch,)],
        [(plan.batch, 3, 32, 32), (plan.batch,)],
    )
    # nan_injection exercises the numerics audit + in-graph sentry mask
    # (obs/health.py): the solver computes the audit stats tree inside
    # the jitted round, and the host sentry verifies the poisoned round
    # was flagged at EXACTLY the seeded index
    audit = plan.nan_round is not None
    solver = Solver(
        models.load_model_solver("cifar10_quick"), net_param=netp,
        audit=audit,
    )
    mesh = make_mesh(
        {"dp": plan.workers}, devices=jax.devices()[: plan.workers]
    )
    # slice_preemption runs the whole scenario on the two-tier
    # hierarchical schedule (parallel/hierarchy.py): every-round psum
    # within a slice, cross-slice average every cross_slice_every
    # rounds — both legs (baseline + faulted) use the same spec so the
    # loss comparison stays like-for-like
    from sparknet_tpu.parallel.hierarchy import HierarchySpec
    from sparknet_tpu.runtime import membership as membership_mod

    spec = None
    membership_ctl = None
    if plan.slice_preempt_round is not None:
        spec = HierarchySpec.grouped(
            plan.workers, plan.membership_slices, plan.cross_slice_every
        )
        membership_ctl = membership_mod.MembershipController(
            spec, echo=note
        )
    trainer = ParameterAveragingTrainer(solver, mesh, hierarchy=spec)
    sentry = None
    if audit:
        from sparknet_tpu.obs.health import HealthSentry

        sentry = HealthSentry(policy="warn", echo=note)

    def broadcast(st):
        return trainer.broadcast_state(st)

    def final_round_loss(losses) -> float:
        return float(np.mean(np.asarray(jax.device_get(losses))))

    # ---------------- baseline: the same run shape, zero faults
    base_plan = plan.no_fault_view()
    base_counters = {
        "storage_injected": 0, "storage_survived": 0,
        "stalls_injected": 0, "stalls_survived": 0,
    }
    feed = _Feed(
        base_plan, xs, ys, base_counters, events, mesh,
        chunk_source=chunk_source,
    )
    state = trainer.init_state(seed=plan.seed)
    losses = None
    for r in range(plan.rounds):
        # round_index keeps the two-tier schedule absolute in BOTH legs
        out = trainer.round(state, feed.next_round(r), round_index=r)
        state, losses = out[0], out[1]  # audit runs drop the stats here
    feed.close()
    baseline_loss = final_round_loss(losses)
    note(f"baseline (no faults): final-round loss {baseline_loss:.4f}")
    # the artifact's cache_stats describe the FAULTED run only — the
    # shared cache also served the baseline leg, so record the offset
    cache_stats_before = dict(chunk_source[1].stats)

    # ---------------- the faulted run
    counters = {
        "storage_injected": 0, "storage_survived": 0,
        "stalls_injected": 0, "stalls_survived": 0,
    }
    fault_state: Dict = {}
    feed = _Feed(
        plan, xs, ys, counters, events, mesh, fault_state,
        chunk_source=chunk_source,
    )
    prefix = os.path.join(workdir, "chaos_ckpt")
    state = trainer.init_state(seed=plan.seed)
    losses = None
    preempted_at: Optional[int] = None
    snapshots = 0

    def take_snapshot(r: int) -> Tuple[str, str]:
        nonlocal snapshots
        if membership_ctl is not None:
            # a departed slice's slots can hold stale params between
            # cross rounds — snapshot the first LIVE worker's consensus
            st = membership_mod.consensus_state(
                state, last_mask["m"] if last_mask["m"] is not None
                else np.ones((plan.workers,), np.float32)
            )
        else:
            st = first_worker(jax.device_get(state))
        paths = checkpoint.snapshot(solver, st, prefix, fmt="BINARYPROTO")
        snapshots += 1
        note(f"round {r}: snapshot -> {os.path.basename(paths[1])}")
        return paths

    def live_mask_for(r: int):
        if plan.dead_worker is None or r < plan.dead_from_round:
            return None
        mask = np.ones((plan.workers,), np.float32)
        mask[plan.dead_worker] = 0.0
        return mask

    last_mask: Dict = {"m": None}  # the combined mask the round used

    def run_round(fd: _Feed, r: int) -> None:
        """One training round of the faulted run (shared by the
        pre-preemption loop and the post-resume replay — fault
        accounting must stay identical in both)."""
        nonlocal state, losses
        mask = live_mask_for(r)
        if mask is not None and r == plan.dead_from_round:
            counters["dead_worker_injected"] = 1
            _obs.fault("dead_worker", round=r, worker=plan.dead_worker)
            note(
                f"round {r}: dp worker {plan.dead_worker} died; "
                "averaging over survivors"
            )
        if membership_ctl is not None:
            # the membership view advances at the round BOUNDARY: the
            # preempted slice departs here, not mid-round
            mview = membership_ctl.advance(r)
            if membership_ctl.pending_joiners():
                joiners = membership_ctl.pending_joiners()
                combined = mview.live_mask()
                if mask is not None:
                    combined = combined * mask
                state, _ = membership_mod.readmit(
                    trainer, solver, state, prefix, membership_ctl,
                    r, live_mask=combined, snapshot_fmt="BINARYPROTO",
                    echo=note,
                )
                counters.setdefault("slice_rejoin_round", r)
                _obs.instant(
                    "recovered", kind="slice_preemption", round=r,
                    workers=list(joiners),
                )
                mview = membership_ctl.view
            mmask = membership_ctl.live_mask()
            mask = mmask if mask is None else mmask * mask
            if (
                counters.get("slice_preempt_injected")
                and "slice_leave_round" not in counters
                and any(s != membership_mod.LIVE for s in mview.states)
            ):
                counters["slice_leave_round"] = r
            sw = spec.slices[plan.slice_preempt_slice]
            if all(mask[w] == 0.0 for w in sw):
                # a set: post-resume replays revisit rounds by absolute
                # index and must not double-count them
                counters.setdefault("slice_masked_rounds", set()).add(r)
        last_mask["m"] = mask
        batches = fd.next_round(r)  # placed by the pipelined feed
        out = trainer.round(state, batches, live_mask=mask, round_index=r)
        state, losses = out[0], out[1]
        if sentry is not None:
            verdict = sentry.observe(r, losses, out[2])
            if verdict.nonfinite_total > 0:
                counters.setdefault("nan_detected_round", r)
            if r == plan.nan_round and counters.get("nan_injected"):
                # survived = flagged at EXACTLY the seeded round, the
                # poisoned worker(s) masked out of the average in-graph,
                # and the surviving weights stayed finite
                exact = (
                    verdict.nonfinite_total > 0
                    and verdict.masked_workers
                    == sorted(plan.nan_workers)
                    and sentry.last_anomaly_round == plan.nan_round
                )
                if exact:
                    counters["nan_survived"] = 1
                    note(
                        f"round {r}: sentry flagged + masked poisoned "
                        f"worker(s) {verdict.masked_workers}; average "
                        "stayed healthy"
                    )
        if (
            profiler is not None
            and r == plan.straggler_round
            and counters.get("straggler_injected")
            # a post-resume REPLAY of this round has no injected sleep
            # (the fault already discharged) — the first visit's verdict
            # must not be overwritten by the healthy replay's
            and "straggler_detected_worker" not in counters
        ):
            # survived = the round profiler's verdict names EXACTLY the
            # seeded worker (per-worker attribution, not just "slow")
            rec = profiler.last()
            w = (rec or {}).get("worker")
            counters["straggler_detected_worker"] = (
                w["worst_worker"] if w else None
            )
            if (
                rec is not None
                and rec["round"] == r
                and w is not None
                and w["straggler"]
                and w["worst_worker"] == plan.straggler_worker
            ):
                counters["straggler_survived"] = 1
                note(
                    "round %d: profiler attributed the slow round to "
                    "worker %d (skew %.2f) — straggler verdict exact"
                    % (r, w["worst_worker"], w["skew"])
                )
        if outage is not None:
            outage.on_round_end(r)
        if serve_faults is not None:
            serve_faults.on_round_end(
                r, solver,
                lambda: first_worker(jax.device_get(state)),
            )
        if (
            plan.driver_kill_round is not None
            and r == plan.driver_kill_round
            and not counters.get("driver_kill_injected")
        ):
            # crash-consistency fault: a journaled driver killed
            # mid-commit, recovered bit-identically (fires once; runs
            # as a bounded sub-scenario like the serve faults)
            counters["driver_kill_summary"] = _driver_kill_scenario(
                plan, counters, note, workdir
            )
        if (
            plan.slow_slice_round is not None
            and r == plan.slow_slice_round
            and not counters.get("slow_slice_injected")
        ):
            # bounded-staleness fault: a whole slice +X s/round — the
            # sync control pays the full tail, the stale leg doesn't,
            # and the ledger still names the straggler (fires once;
            # bounded A/B sub-scenario like driver_kill)
            counters["slow_slice_summary"] = _slow_slice_scenario(
                plan, counters, note, workdir
            )
        if membership_ctl is not None:
            if (
                r == plan.slice_preempt_round
                and not counters.get("slice_preempt_injected")
            ):
                # a REAL SIGTERM: the orchestrator's preemption notice
                # for slice slice_preempt_slice — the membership
                # controller's hook marks it leaving; the process (and
                # the job) keeps running
                counters["slice_preempt_injected"] = 1
                sw = list(spec.slices[plan.slice_preempt_slice])
                _obs.fault(
                    "slice_preemption", round=r,
                    slice=plan.slice_preempt_slice, workers=sw,
                )
                note(
                    f"round {r}: SIGTERM preemption notice for slice "
                    f"{plan.slice_preempt_slice} (workers {sw})"
                )
                os.kill(os.getpid(), _signal.SIGTERM)
            if (
                counters.get("slice_preempt_injected")
                and r == plan.slice_preempt_round
                + plan.slice_relaunch_delta
                and not counters.get("slice_relaunched")
            ):
                counters["slice_relaunched"] = 1
                sw = spec.slices[plan.slice_preempt_slice]
                membership_ctl.note_join(sw)
                note(
                    f"round {r}: slice {plan.slice_preempt_slice} "
                    "relaunched — rejoin requested"
                )

    # the round profiler attributes the seeded straggler (installed for
    # the faulted run only; the baseline above ran unprofiled)
    profiler = None
    if plan.straggler_round is not None:
        profiler = _profile.install(_profile.RoundProfiler())
    # collector_outage: fleet collector + shipper live for the faulted
    # run only (the baseline ran unshipped)
    outage = None
    if plan.collector_outage_round is not None:
        outage = _CollectorOutage(plan, counters, note)
    # the serving-fleet faults (replica_death, decode_replica_kill,
    # published_snapshot_corrupt)
    serve_faults = None
    if (
        plan.replica_death_round is not None
        or plan.decode_replica_kill_round is not None
        or plan.publish_corrupt_round is not None
    ):
        serve_faults = _ServeFaults(plan, counters, note, workdir)
    t_preempt = None
    if membership_ctl is not None:
        # SIGTERM -> "slice slice_preempt_slice is being preempted"
        # (utils/signals.py hook; the handler itself is installed by
        # the SignalHandler below via sigterm_hooks=True)
        membership_ctl.sigterm_marks(plan.slice_preempt_slice)
    try:
        with SignalHandler(
            sigint_effect=SolverAction.NONE,
            sighup_effect=SolverAction.SNAPSHOT,
            sigterm_hooks=membership_ctl is not None,
        ) as handler:
            for r in range(plan.rounds):
                run_round(feed, r)
                snapped = (r + 1) % plan.snapshot_every == 0
                if snapped:
                    take_snapshot(r)
                if plan.preempt_round is not None and r == plan.preempt_round:
                    # a REAL signal, not a flag: the orchestrator's
                    # preemption notice arrives as SIGHUP
                    os.kill(os.getpid(), _signal.SIGHUP)
                    # the driver's poll sees SNAPSHOT (reference SIGHUP
                    # semantics), saves — unless the periodic snapshot
                    # already covered this exact iteration — and "dies"
                    if (
                        handler.get_action() == SolverAction.SNAPSHOT
                        and not snapped
                    ):
                        take_snapshot(r)
                    counters["preempt_injected"] = 1
                    t_preempt = time.perf_counter()
                    preempted_at = r
                    _obs.fault("preemption", round=r)
                    note(
                        f"round {r}: SIGHUP preemption — simulated "
                        "process death"
                    )
                    break
        feed.close()

        resumed_from_iter = None
        quarantined: List[str] = []
        recovery_latency_s = None
        if preempted_at is not None:
            # simulated restart: live state is GONE; only files survive
            state = None
            if plan.corrupt_newest:
                newest = checkpoint.find_snapshots(prefix)[-1]
                corrupt_file(newest, seed=plan.seed)
                counters["corruption_injected"] = 1
                _obs.fault(
                    "snapshot_corruption", snapshot=os.path.basename(newest)
                )
                note(f"corrupted newest snapshot {os.path.basename(newest)}")
            st, used = checkpoint.restore_newest_valid(solver, prefix)
            resumed_from_iter = int(np.asarray(st.iter))
            quarantined = [
                os.path.basename(p)
                for p in sorted(os.listdir(workdir))
                if p.endswith(".corrupt")
            ]
            if plan.corrupt_newest:
                if quarantined and used != newest:
                    counters["corruption_survived"] = 1
                note(
                    f"resume fell back to {os.path.basename(used)} "
                    f"(quarantined: {quarantined})"
                )
            state = broadcast(st)
            recovery_latency_s = time.perf_counter() - t_preempt
            counters["preempt_survived"] = 1
            _obs.instant(
                "recovered", kind="preemption",
                latency_s=round(recovery_latency_s, 3),
                resumed_iter=resumed_from_iter,
            )
            start_round = resumed_from_iter // plan.tau
            note(
                "resumed at round %d (iter %d) in %.2fs; replaying %d "
                "round(s)"
                % (
                    start_round,
                    resumed_from_iter,
                    recovery_latency_s,
                    preempted_at + 1 - start_round,
                )
            )
            feed = _Feed(
                plan, xs, ys, counters, events, mesh, fault_state,
                chunk_source=chunk_source,
            )
            for r in range(start_round, plan.rounds):
                run_round(feed, r)
            feed.close()
    finally:
        if membership_ctl is not None:
            membership_ctl.detach()
        if profiler is not None:
            _profile.uninstall(profiler)
        if serve_faults is not None:
            serve_faults.close()
        if outage is not None:
            try:
                outage.finalize()
            finally:
                outage.close()

    final_loss = final_round_loss(losses)
    if counters.get("dead_worker_injected") and np.isfinite(final_loss):
        counters["dead_worker_survived"] = 1
    if counters.get("slice_preempt_injected") and membership_ctl is not None:
        # survived = the departure took effect at EXACTLY the round
        # boundary after the notice, every intervening round's average
        # excluded the departed slice (renormalized over survivors),
        # the views advanced with monotonic epochs, and the rejoin
        # completed (whole roster live again)
        leave_r = counters.get("slice_leave_round")
        rejoin_r = counters.get("slice_rejoin_round")
        masked = set(counters.get("slice_masked_rounds", []))
        gone = (
            set(range(leave_r, rejoin_r))
            if leave_r is not None and rejoin_r is not None
            else None
        )
        if (
            leave_r == plan.slice_preempt_round + 1
            and gone is not None
            and gone <= masked
            and membership_ctl.epochs_monotonic()
            and all(
                s == membership_mod.LIVE
                for s in membership_ctl.view.states
            )
            and np.isfinite(final_loss)
        ):
            counters["slice_preempt_survived"] = 1
            note(
                "slice preemption survived: left at round %d, masked "
                "rounds %s, rejoined at round %d, final epoch %d"
                % (leave_r, sorted(masked), rejoin_r,
                   membership_ctl.epoch)
            )

    loss_band = max(0.25, 0.25 * abs(baseline_loss))
    loss_band_ok = bool(abs(final_loss - baseline_loss) <= loss_band)
    note(
        f"final-round loss {final_loss:.4f} vs baseline "
        f"{baseline_loss:.4f} (band +/-{loss_band:.3f}: "
        f"{'OK' if loss_band_ok else 'OUT OF BAND'})"
    )

    fault_kinds = {
        "storage": ("storage_injected", "storage_survived"),
        "stall": ("stalls_injected", "stalls_survived"),
        "preemption": ("preempt_injected", "preempt_survived"),
        "snapshot_corruption": (
            "corruption_injected", "corruption_survived",
        ),
        "dead_worker": ("dead_worker_injected", "dead_worker_survived"),
        "nan_injection": ("nan_injected", "nan_survived"),
        "straggler_injection": (
            "straggler_injected", "straggler_survived",
        ),
        "cache_corruption": (
            "cache_corrupt_injected", "cache_corrupt_survived",
        ),
        "cache_cold": ("cache_cold_injected", "cache_cold_survived"),
        "collector_outage": (
            "collector_outage_injected", "collector_outage_survived",
        ),
        "replica_death": (
            "replica_death_injected", "replica_death_survived",
        ),
        "decode_replica_kill": (
            "decode_kill_injected", "decode_kill_survived",
        ),
        "published_snapshot_corrupt": (
            "publish_corrupt_injected", "publish_corrupt_survived",
        ),
        "slice_preemption": (
            "slice_preempt_injected", "slice_preempt_survived",
        ),
        "driver_kill": (
            "driver_kill_injected", "driver_kill_survived",
        ),
        "slow_slice": (
            "slow_slice_injected", "slow_slice_survived",
        ),
    }
    faults = {
        kind: {
            "injected": int(counters.get(ik, 0)),
            "survived": int(counters.get(sk, 0)),
        }
        for kind, (ik, sk) in fault_kinds.items()
    }
    injected = sum(v["injected"] for v in faults.values())
    survived = sum(v["survived"] for v in faults.values())
    return {
        "seed": plan.seed,
        "workers": plan.workers,
        "rounds": plan.rounds,
        "tau": plan.tau,
        "batch": plan.batch,
        "faults_injected": injected,
        "faults_survived": survived,
        "faults": faults,
        "watchdog_fires": int(counters.get("watchdog_fires", 0)),
        "nan_round": plan.nan_round,
        "nan_detected_round": counters.get("nan_detected_round"),
        "straggler_round": plan.straggler_round,
        "straggler_worker": plan.straggler_worker,
        "straggler_detected_worker": counters.get(
            "straggler_detected_worker"
        ),
        "cache_corrupt_round": plan.cache_corrupt_round,
        "cache_cold_round": plan.cache_cold_round,
        "collector_outage_round": plan.collector_outage_round,
        "collector_outage": outage.summary if outage is not None else None,
        "replica_death_round": plan.replica_death_round,
        "decode_replica_kill_round": plan.decode_replica_kill_round,
        "publish_corrupt_round": plan.publish_corrupt_round,
        "driver_kill_round": plan.driver_kill_round,
        "driver_kill": counters.get("driver_kill_summary"),
        "slow_slice_round": plan.slow_slice_round,
        "slow_slice": counters.get("slow_slice_summary"),
        "slice_preempt_round": plan.slice_preempt_round,
        "slice_preempt_slice": plan.slice_preempt_slice,
        "slice_leave_round": counters.get("slice_leave_round"),
        "slice_rejoin_round": counters.get("slice_rejoin_round"),
        "slice_masked_rounds": sorted(
            counters.get("slice_masked_rounds", [])
        ),
        "membership": (
            membership_ctl.state_dict()
            if membership_ctl is not None else None
        ),
        # the faulted run's own cache traffic (baseline-leg reads on the
        # shared cache subtracted out)
        "cache_stats": {
            k: v - cache_stats_before.get(k, 0)
            for k, v in chunk_source[1].stats.items()
        },
        "recovery_latency_s": (
            round(recovery_latency_s, 3)
            if recovery_latency_s is not None
            else None
        ),
        "resumed_from_iter": resumed_from_iter,
        "quarantined": quarantined,
        "final_loss": round(final_loss, 4),
        "baseline_final_loss": round(baseline_loss, 4),
        "loss_band": round(loss_band, 4),
        "loss_band_ok": loss_band_ok,
        "final_iter": plan.rounds * plan.tau,
        "events": events,
    }
