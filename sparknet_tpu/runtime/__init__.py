"""ctypes bindings for the native runtime (record DB + data pipeline).

The native side (``native/sparknet_runtime/runtime.cpp``) replaces the
reference's C++ data plane: db::DB over LevelDB/LMDB, BlockingQueue,
DataReader's reader thread and DataTransformer.  A pure-Python fallback
keeps everything working when the .so hasn't been built (``make -C
native``); ``native_available()`` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import queue as _queue
from typing import Dict, Iterator, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libsparknet_runtime.so")
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")

_lib = None
_lib_error: Optional[str] = None


def _load():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        _lib_error = str(e)
        return None
    if not hasattr(lib, "snpipe_create2"):
        # a stale pre-rework .so: fall back to Python (rebuildable with
        # `make -C native` / runtime.build(force=True))
        _lib_error = (
            "libsparknet_runtime.so is outdated (missing snpipe_create2); "
            "rebuild with `make -C native`"
        )
        return None
    lib.sn_last_error.restype = ctypes.c_char_p
    lib.sndb_open.restype = ctypes.c_void_p
    lib.sndb_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.sndb_put.restype = ctypes.c_int
    lib.sndb_put.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.sndb_commit.argtypes = [ctypes.c_void_p]
    lib.sndb_num_records.restype = ctypes.c_long
    lib.sndb_num_records.argtypes = [ctypes.c_void_p]
    lib.sndb_read.restype = ctypes.c_long
    lib.sndb_read.argtypes = [
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    lib.sndb_close.argtypes = [ctypes.c_void_p]
    lib.snpipe_create2.restype = ctypes.c_void_p
    lib.snpipe_create2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_float,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
        ctypes.c_uint,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.snpipe_next2.restype = ctypes.c_int
    lib.snpipe_next2.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.snpipe_out_h.restype = ctypes.c_int
    lib.snpipe_out_h.argtypes = [ctypes.c_void_p]
    lib.snpipe_out_w.restype = ctypes.c_int
    lib.snpipe_out_w.argtypes = [ctypes.c_void_p]
    lib.snpipe_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def build(force: bool = False) -> bool:
    """Build the native library with make (returns True on success)."""
    global _lib, _lib_error
    if os.path.exists(_LIB_PATH) and not force:
        _lib_error = None
        if _load() is not None:
            return True
        # present but unloadable/stale: fall through and rebuild
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR], check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        _lib_error = getattr(e, "stderr", b"") or str(e)
        return False
    _lib, _lib_error = None, None
    return _load() is not None


def native_available() -> bool:
    return _load() is not None


def _err(lib) -> str:
    return lib.sn_last_error().decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# RecordDB
# ---------------------------------------------------------------------------


class RecordDB:
    """Record store with transaction-style commits (the ``db::DB`` role;
    the CreateDB path commits explicitly like CreateDB.scala:13-51)."""

    MAGIC = b"SNDB1\x00\x00\x00"

    def __init__(self, path: str, mode: str = "r"):
        self.path = path
        self.mode = mode
        self._lib = _load()
        self._handle = None
        self._py_records = None
        self._py_pending = []
        self._py_out = None
        if self._lib is not None:
            self._handle = self._lib.sndb_open(
                path.encode(), 1 if mode == "w" else 0
            )
            if not self._handle:
                raise IOError(f"sndb_open failed: {_err(self._lib)}")
        elif mode == "w":
            self._py_out = open(path, "wb")
            self._py_out.write(self.MAGIC)
        else:
            self._py_records = self._py_scan(path)

    @classmethod
    def _py_scan(cls, path):
        records = []
        with open(path, "rb") as f:
            if f.read(8) != cls.MAGIC:
                raise IOError(f"bad magic in {path}")
            while True:
                head = f.read(4)
                if not head:
                    break
                klen = int.from_bytes(head, "little")
                key = f.read(klen)
                vlen = int.from_bytes(f.read(4), "little")
                value = f.read(vlen)
                if len(value) != vlen:
                    raise IOError(f"truncated record in {path}")
                records.append((key, value))
        return records

    def put(self, key: bytes, value: bytes):
        if self._handle is not None:
            rc = self._lib.sndb_put(self._handle, key, len(key), value, len(value))
            if rc:
                raise IOError(_err(self._lib))
        else:
            self._py_pending.append((key, value))

    def commit(self):
        if self._handle is not None:
            if self._lib.sndb_commit(self._handle):
                raise IOError(_err(self._lib))
        else:
            for key, value in self._py_pending:
                self._py_out.write(len(key).to_bytes(4, "little"))
                self._py_out.write(key)
                self._py_out.write(len(value).to_bytes(4, "little"))
                self._py_out.write(value)
            self._py_pending.clear()
            self._py_out.flush()

    def __len__(self) -> int:
        if self._handle is not None:
            return int(self._lib.sndb_num_records(self._handle))
        if self._py_records is None:
            return 0
        return len(self._py_records)

    def read(self, idx: int):
        if self._handle is not None:
            size = self._lib.sndb_read(self._handle, idx, None, 0, None, 0)
            if size < 0:
                raise IndexError(_err(self._lib))
            keybuf = ctypes.create_string_buffer(4096)
            buf = ctypes.create_string_buffer(int(size))
            self._lib.sndb_read(self._handle, idx, keybuf, 4096, buf, size)
            return keybuf.value, buf.raw
        return self._py_records[idx]

    def close(self):
        if self._handle is not None:
            self._lib.sndb_close(self._handle)
            self._handle = None
        if self._py_out is not None:
            self._py_out.close()
            self._py_out = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_datum_db(
    path: str,
    images: np.ndarray,
    labels: np.ndarray,
    commit_every: int = 1000,
) -> None:
    """Write (N, C, H, W) uint8 images + labels as Datum-style records
    (label + pixel bytes), committing every ``commit_every`` puts like
    the reference's CreateDB.  The label is 1 byte when every label fits
    (CIFAR-scale) or 2 little-endian bytes otherwise (1000-class
    ImageNet); readers infer the width from record length vs the known
    image size."""
    images = np.ascontiguousarray(images, dtype=np.uint8)
    labels = np.asarray(labels)
    if len(labels) and not (
        0 <= int(labels.min()) and int(labels.max()) <= 0xFFFF
    ):
        raise ValueError(
            f"labels outside [0, 65535]: min {labels.min()}, "
            f"max {labels.max()}"
        )
    width = 1 if (len(labels) == 0 or int(labels.max()) <= 0xFF) else 2
    with RecordDB(path, "w") as db:
        for i in range(len(labels)):
            value = int(labels[i]).to_bytes(width, "little") + images[i].tobytes()
            db.put(b"%08d" % i, value)
            if (i + 1) % commit_every == 0:
                db.commit()
        db.commit()


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------

_U64 = (1 << 64) - 1


def _record_rng_stream(seed: int, seq: int):
    """The counter-based splitmix64 stream the native pipeline draws
    per-record crop/mirror randomness from (runtime.cpp splitmix64):
    keyed on (seed, global record sequence number), so output is
    identical for any worker count and both implementations."""
    s = ((seed * 0x9E3779B97F4A7C15) ^ (seq * 0xBF58476D1CE4E5B9)) & _U64

    def next_u64():
        nonlocal s
        s = (s + 0x9E3779B97F4A7C15) & _U64
        z = s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
        return z ^ (z >> 31)

    return next_u64


class DataPipeline:
    """Threaded DB -> transformed batches (one reader + N transform
    workers + ordered delivery in native code; Python thread fallback
    otherwise).

    Two output modes:

    - float (default): full DataTransformer semantics on the host —
      ``next()`` returns ``(data f32 (B,C,oh,ow), labels f32 (B,))``.
    - ``u8_output=True``: the host applies only crop *geometry* (uint8
      row copies — the cheap part) and ships the arithmetic to the
      device where it fuses into the training step; ``next()`` returns
      ``(data u8, labels, h_offs i32, w_offs i32, flips u8)``.  Finish
      on device with ``data.transforms.finish_host_crops``.  This is
      the low-byte path for weak host->device links (5x fewer bytes
      than float full-frames).
    """

    def __init__(
        self,
        db_path: str,
        batch_size: int,
        shape: Sequence[int],  # (C, H, W) of stored records
        crop: int = 0,
        mirror: bool = False,
        train: bool = True,
        scale: float = 1.0,
        mean: Optional[np.ndarray] = None,
        seed: int = 0,
        prefetch: int = 3,
        workers: int = 0,  # 0 = cores-1 (native); fallback always 1
        u8_output: bool = False,
    ):
        self.batch_size = batch_size
        c, h, w = (int(x) for x in shape)
        self.c, self.h, self.w = c, h, w
        self.out_h = crop if crop else h
        self.out_w = crop if crop else w
        self.u8_output = bool(u8_output)
        self._lib = _load()
        mean_arr = (
            np.ascontiguousarray(mean, dtype=np.float32).reshape(-1)
            if mean is not None
            else None
        )
        if self._lib is not None:
            mean_ptr = (
                mean_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                if mean_arr is not None
                else None
            )
            self._handle = self._lib.snpipe_create2(
                db_path.encode(),
                batch_size,
                c,
                h,
                w,
                crop,
                int(mirror),
                int(train),
                scale,
                mean_ptr,
                0 if mean_arr is None else mean_arr.size,
                seed,
                prefetch,
                workers,
                int(u8_output),
            )
            if not self._handle:
                raise IOError(f"snpipe_create failed: {_err(self._lib)}")
        else:
            self._handle = None
            self._py_init(db_path, crop, mirror, train, scale, mean_arr, seed, prefetch)

    # -- python fallback ------------------------------------------------
    def _py_init(self, db_path, crop, mirror, train, scale, mean, seed, prefetch):
        db = RecordDB(db_path, "r")
        if len(db) == 0:
            raise IOError("empty db")
        record_bytes = 1 + self.c * self.h * self.w
        self._py_q: "_queue.Queue" = _queue.Queue(maxsize=prefetch)
        self._py_stop = threading.Event()
        u8 = self.u8_output

        def run():
            idx = 0
            seq = 0
            n = len(db)
            while not self._py_stop.is_set():
                dtype = np.uint8 if u8 else np.float32
                data = np.empty(
                    (self.batch_size, self.c, self.out_h, self.out_w), dtype
                )
                labels = np.empty(self.batch_size, np.float32)
                h_offs = np.zeros(self.batch_size, np.int32)
                w_offs = np.zeros(self.batch_size, np.int32)
                flips = np.zeros(self.batch_size, np.uint8)
                for i in range(self.batch_size):
                    _, value = db.read(idx)
                    idx = (idx + 1) % n
                    if len(value) not in (record_bytes, record_bytes + 1):
                        self._py_q.put(
                            IOError(
                                f"record size mismatch: got {len(value)}, "
                                f"want {record_bytes} or {record_bytes + 1}"
                            )
                        )
                        return
                    # label width (1 or 2 bytes) inferred from length
                    lw = len(value) - (record_bytes - 1)
                    labels[i] = int.from_bytes(value[:lw], "little")
                    img = np.frombuffer(value, np.uint8, offset=lw).reshape(
                        self.c, self.h, self.w
                    )
                    draw = _record_rng_stream(seed, seq)
                    seq += 1
                    ho = wo = 0
                    if crop:
                        if train:
                            ho = draw() % (self.h - crop + 1)
                            wo = draw() % (self.w - crop + 1)
                        else:
                            ho = (self.h - crop) // 2
                            wo = (self.w - crop) // 2
                    flip = bool(mirror and train and (draw() & 1))
                    window = (
                        img[:, ho : ho + crop, wo : wo + crop] if crop else img
                    )
                    if u8:
                        data[i] = window
                        h_offs[i], w_offs[i], flips[i] = ho, wo, flip
                        continue
                    out = window.astype(np.float32)
                    if mean is not None and mean.size == self.c * self.h * self.w:
                        m = mean.reshape(self.c, self.h, self.w)
                        out = out - m[:, ho : ho + self.out_h, wo : wo + self.out_w]
                    elif mean is not None and mean.size == self.c:
                        out = out - mean.reshape(self.c, 1, 1)
                    if flip:
                        out = out[:, :, ::-1]
                    data[i] = out * scale
                item = (
                    (data, labels, h_offs, w_offs, flips)
                    if u8
                    else (data, labels)
                )
                while not self._py_stop.is_set():
                    try:
                        self._py_q.put(item, timeout=0.1)
                        break
                    except _queue.Full:
                        continue

        self._py_thread = threading.Thread(
            target=run, name="snpipe-producer", daemon=True
        )
        self._py_thread.start()

    def next(self):
        """float mode: ``(data f32, labels)``; u8 mode: ``(data u8,
        labels, h_offs, w_offs, flips)``."""
        if self._handle is not None:
            dtype = np.uint8 if self.u8_output else np.float32
            data = np.empty(
                (self.batch_size, self.c, self.out_h, self.out_w), dtype
            )
            labels = np.empty(self.batch_size, np.float32)
            if self.u8_output:
                h_offs = np.empty(self.batch_size, np.int32)
                w_offs = np.empty(self.batch_size, np.int32)
                flips = np.empty(self.batch_size, np.uint8)
                rc = self._lib.snpipe_next2(
                    self._handle,
                    data.ctypes.data_as(ctypes.c_void_p),
                    labels.ctypes.data_as(ctypes.c_void_p),
                    h_offs.ctypes.data_as(ctypes.c_void_p),
                    w_offs.ctypes.data_as(ctypes.c_void_p),
                    flips.ctypes.data_as(ctypes.c_void_p),
                )
                if rc:
                    raise IOError(_err(self._lib))
                return data, labels, h_offs, w_offs, flips
            rc = self._lib.snpipe_next2(
                self._handle,
                data.ctypes.data_as(ctypes.c_void_p),
                labels.ctypes.data_as(ctypes.c_void_p),
                None,
                None,
                None,
            )
            if rc:
                raise IOError(_err(self._lib))
            return data, labels
        item = self._py_q.get()
        if isinstance(item, Exception):
            raise item
        return item

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        return self.next()

    def close(self):
        if self._handle is not None:
            self._lib.snpipe_destroy(self._handle)
            self._handle = None
        elif hasattr(self, "_py_stop"):
            self._py_stop.set()
            try:
                while True:
                    self._py_q.get_nowait()
            except _queue.Empty:
                pass
            self._py_thread.join(timeout=5)
