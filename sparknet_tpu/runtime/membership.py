"""Elastic worker membership: train through a preempted slice.

The dp mesh is fixed at launch, but the WORKERS behind it are not: a
preemptible TPU slice can be taken away mid-run and handed back minutes
later.  ``live_mask`` (PR 2) and the in-graph sentry mask (PR 5)
already renormalize the average over survivors; this module adds the
missing control plane — an epoch-numbered **membership view** of the
worker roster that decides WHAT the mask is each round, and a
readmission path that brings a departed slice back without stopping
the job.

One ``MembershipController`` per driver process:

- **views are epoch-numbered and advance only at round boundaries.**
  Signals (a SIGTERM preemption notice, a fleet-collector liveness
  verdict, a chaos fault, an explicit join request) enqueue *events*;
  ``advance(round)`` applies them all at once, bumps the epoch exactly
  once per changed view, and returns the new ``MembershipView``.  The
  trainer never sees a mid-round roster change — departures take
  effect at the next boundary with no collective hang (the mesh shape
  never changes; only the mask does).
- **worker states**: ``live -> leaving -> dead -> joining -> live``.
  A preemption notice or a LATE heartbeat demotes to ``leaving`` (the
  worker may still come back — late is not dead); a missed deadline or
  an explicit death, or ``leave_grace_rounds`` boundaries spent
  leaving, completes the departure to ``dead``.  A join request on a
  ``dead`` worker makes it ``joining``; a join requested while the
  worker is still ``leaving`` is DEFERRED until the leave completes
  (the rejoin-before-leave-completes ordering).  Only ``live`` workers
  carry mask weight.
- **readmission**: a ``joining`` worker is admitted at a view epoch by
  the driver — catch up through ``io/checkpoint.restore_newest_valid``
  (the snapshot is how weights travel to a relaunched process), place
  via ``ParameterAveragingTrainer.broadcast_state``, merge ONLY the
  rejoining rows into the live stacked state, and zero the rejoiners'
  momentum history (the PR-5 rejoin contract).  ``readmit`` below is
  that whole dance; ``admit()`` then flips joining -> live at the next
  epoch.
- **fleet feed** (PR 10): ``ingest_fleet_view`` translates the
  collector's per-host ``live|late|dead`` verdicts + ``boot_id``
  restart detection into membership events, given a host -> workers
  mapping — the 2-process e2e proof kills and relaunches a real
  shipper process and watches the views walk leave -> rejoin.

Telemetry: every epoch bump sets ``sparknet_membership_epoch`` and the
per-state ``sparknet_membership_workers`` gauges, counts
``sparknet_membership_transitions_total{kind}`` and emits a
``membership_view`` instant on the run log; ``obs.set_membership``
exports the controller's ``state_dict()`` on ``/healthz``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from sparknet_tpu import obs as _obs
from sparknet_tpu.parallel.hierarchy import HierarchySpec

LIVE = "live"
LEAVING = "leaving"
DEAD = "dead"
JOINING = "joining"
STATES = (LIVE, LEAVING, DEAD, JOINING)


class MembershipView:
    """One immutable epoch-numbered snapshot of the roster."""

    __slots__ = ("epoch", "round", "states", "spec")

    def __init__(
        self,
        epoch: int,
        round: int,
        states: Tuple[str, ...],
        spec: HierarchySpec,
    ):
        self.epoch = epoch
        self.round = round
        self.states = states
        self.spec = spec

    def live_mask(self) -> np.ndarray:
        """The (num_workers,) 0/1 mask the trainer consumes: only LIVE
        workers carry weight — leaving/dead/joining are all excluded
        from the average until (re)admitted."""
        return np.asarray(
            [1.0 if s == LIVE else 0.0 for s in self.states], np.float32
        )

    def workers_in(self, state: str) -> Tuple[int, ...]:
        return tuple(
            w for w, s in enumerate(self.states) if s == state
        )

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in STATES}
        for s in self.states:
            out[s] += 1
        return out

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"MembershipView(epoch={self.epoch}, round={self.round}, "
            f"states={self.states})"
        )


class MembershipController:
    """Maintains the roster; thread-safe on the event side (signal
    handlers, heartbeat threads), single-driver on ``advance``."""

    def __init__(
        self,
        spec: HierarchySpec,
        leave_grace_rounds: int = 1,
        echo: Optional[Callable[[str], None]] = None,
    ):
        self.spec = spec
        self.num_workers = spec.num_workers
        self.leave_grace_rounds = max(0, int(leave_grace_rounds))
        self._echo = echo
        self._lock = threading.Lock()
        self._states: List[str] = [LIVE] * self.num_workers
        self._epoch = 0
        self._round = -1
        self._leaving_since: Dict[int, int] = {}
        # events queued from any thread, applied at the next advance():
        # (kind, workers) with kind in preempt|late|dead|join
        self._events: List[Tuple[str, Tuple[int, ...]]] = []
        # joins that arrived while the worker had not finished leaving
        self._deferred_joins: set = set()
        self._view = MembershipView(
            0, -1, tuple(self._states), spec
        )
        # transition log: (epoch, round, kind, workers) — the proof the
        # chaos/bench verdicts read ("views advanced leave -> rejoin")
        self.transitions: List[Tuple[int, int, str, Tuple[int, ...]]] = []
        self._sigterm_hook = None
        self._host_boot_ids: Dict[str, Optional[str]] = {}
        self._publish_metrics()

    # ------------------------------------------------------------------
    # event side — safe from signal handlers / heartbeat threads
    def _queue(self, kind: str, workers: Iterable[int]) -> None:
        ws = tuple(int(w) for w in workers)
        if not ws:
            return
        # DELIBERATELY lock-free: the SIGTERM preemption hook runs in
        # signal-handler context ON the driver thread — taking
        # self._lock there deadlocks if the signal lands while
        # advance()/admit() hold it (a non-reentrant Lock on the same
        # thread).  A CPython list.append is atomic, and advance()'s
        # swap-drain never loses a concurrent append, so the queue
        # needs no lock (the signals.py hook contract: no locks).
        self._events.append((kind, ws))

    def note_preempt(
        self,
        workers: Optional[Sequence[int]] = None,
        slice_index: Optional[int] = None,
    ) -> None:
        """A preemption notice (SIGTERM / chaos fault): the named
        workers — or a whole slice — start LEAVING at the next round
        boundary."""
        if workers is None:
            if slice_index is None:
                raise ValueError("pass workers or slice_index")
            workers = self.spec.slices[slice_index]
        self._queue("preempt", workers)

    def note_late(self, workers: Sequence[int]) -> None:
        """A late heartbeat demotes to LEAVING, never straight to dead
        — a slow host may catch up (the fleet plane's late-vs-dead
        distinction, preserved here)."""
        self._queue("late", workers)

    def note_dead(self, workers: Sequence[int]) -> None:
        """A hard death (missed push deadline, process gone)."""
        self._queue("dead", workers)

    def note_join(self, workers: Sequence[int]) -> None:
        """A (re)join request — honored once the worker's leave has
        completed (dead), at a later view epoch."""
        self._queue("join", workers)

    # --- SIGTERM preemption wiring (utils/signals.py hook) ---
    def sigterm_marks(self, slice_index: int):
        """Register a SIGTERM hook marking ``slice_index`` preempted
        (the orchestrator's notice names this process's slice).  Use
        with a ``SignalHandler(sigterm_hooks=True)`` scope; returns the
        hook so callers can detach early."""
        from sparknet_tpu.utils import signals as _signals

        workers = self.spec.slices[slice_index]

        def hook():
            self.note_preempt(workers=workers)

        self._sigterm_hook = _signals.add_sigterm_hook(hook)
        return hook

    def detach(self) -> None:
        if self._sigterm_hook is not None:
            from sparknet_tpu.utils import signals as _signals

            _signals.remove_sigterm_hook(self._sigterm_hook)
            self._sigterm_hook = None

    # --- fleet-plane feed (obs/fleet.py views) ---
    def ingest_fleet_view(
        self, view: Dict, host_workers: Dict[str, Sequence[int]]
    ) -> None:
        """Translate a collector ``fleet_view()`` into membership
        events: a ``late`` host's workers start leaving, a ``dead``
        host's workers die, and a host seen LIVE again after its
        workers departed — or whose ``boot_id`` changed (process
        restart) — requests a rejoin for its workers."""
        hosts = view.get("hosts", {})
        for host, workers in host_workers.items():
            st = hosts.get(host)
            if st is None:
                continue
            hstate = st.get("state")
            boot = st.get("boot_id")
            prev_boot = self._host_boot_ids.get(host)
            restarted = (
                prev_boot is not None
                and boot is not None
                and boot != prev_boot
            )
            self._host_boot_ids[host] = boot
            with self._lock:
                cur = {self._states[w] for w in workers}
            if hstate == "dead":
                self.note_dead(workers)
            elif hstate == "late":
                if LIVE in cur:
                    self.note_late(workers)
            elif hstate == "live":
                if restarted and LIVE in cur:
                    # the host restarted BETWEEN polls (boot_id flipped
                    # while its workers were still marked live): the
                    # old incarnation's training state is GONE, so the
                    # fresh process must walk the full leave -> rejoin
                    # path — dead now, readmitted with catch-up weights
                    # and zeroed momentum at a later epoch — never
                    # averaged in raw under the stale live mask
                    self.note_dead(workers)
                    self.note_join(workers)
                elif restarted or cur <= {DEAD, LEAVING, JOINING}:
                    if cur != {JOINING} and cur != {LIVE}:
                        self.note_join(workers)

    # ------------------------------------------------------------------
    # driver side — round boundaries
    @property
    def view(self) -> MembershipView:
        return self._view

    @property
    def epoch(self) -> int:
        return self._view.epoch

    def live_mask(self) -> np.ndarray:
        return self._view.live_mask()

    def pending_joiners(self) -> Tuple[int, ...]:
        return self._view.workers_in(JOINING)

    def advance(self, round_index: int) -> MembershipView:
        """Apply every queued event at this round boundary; bump the
        epoch exactly once if anything changed.  Ordering within one
        boundary: demotions (preempt/late), deaths, leave-completions
        — and only THEN join requests, restricted to workers that were
        already dead BEFORE this boundary (a join racing its own leave
        waits for the next boundary: leave completes first)."""
        with self._lock:
            # swap-drain of the lock-free queue: an append racing the
            # swap lands on one of the two lists and is processed this
            # boundary or the next — never lost
            events, self._events = self._events, []
            dead_before = {
                w for w, s in enumerate(self._states) if s == DEAD
            }
            changed: List[Tuple[str, Tuple[int, ...]]] = []

            def move(w: int, to: str) -> bool:
                if self._states[w] == to:
                    return False
                self._states[w] = to
                return True

            for kind, ws in events:
                if kind in ("preempt", "late"):
                    moved = tuple(
                        w for w in ws
                        if self._states[w] == LIVE and move(w, LEAVING)
                    )
                    for w in moved:
                        self._leaving_since[w] = round_index
                    if moved:
                        changed.append(("leave" if kind == "preempt"
                                        else "late", moved))
                elif kind == "dead":
                    moved = tuple(
                        w for w in ws
                        if self._states[w] in (LIVE, LEAVING)
                        and move(w, DEAD)
                    )
                    for w in moved:
                        self._leaving_since.pop(w, None)
                    if moved:
                        changed.append(("death", moved))
                elif kind == "join":
                    self._deferred_joins.update(int(w) for w in ws)

            # leave-completion: a worker that has sat out
            # leave_grace_rounds boundaries finishes departing
            expired = tuple(
                w for w, since in list(self._leaving_since.items())
                if self._states[w] == LEAVING
                and round_index - since >= self.leave_grace_rounds
            )
            for w in expired:
                move(w, DEAD)
                self._leaving_since.pop(w, None)
            if expired:
                changed.append(("death", expired))

            # joins only for workers whose leave completed BEFORE this
            # boundary — the rejoin-before-leave-completes ordering
            ready = tuple(
                w for w in sorted(self._deferred_joins)
                if w in dead_before and self._states[w] == DEAD
            )
            for w in ready:
                move(w, JOINING)
                self._deferred_joins.discard(w)
            if ready:
                changed.append(("join_request", ready))
            # drop deferred joins for workers that are live again
            self._deferred_joins = {
                w for w in self._deferred_joins
                if self._states[w] in (LEAVING, DEAD)
            }

            self._round = int(round_index)
            if changed:
                self._epoch += 1
                self._view = MembershipView(
                    self._epoch, self._round, tuple(self._states),
                    self.spec,
                )
                for kind, ws in changed:
                    self.transitions.append(
                        (self._epoch, self._round, kind, ws)
                    )
            else:
                self._view = MembershipView(
                    self._view.epoch, self._round, tuple(self._states),
                    self.spec,
                )
        if changed:
            self._note_changes(changed)
        return self._view

    def admit(
        self, round_index: int, workers: Optional[Sequence[int]] = None
    ) -> MembershipView:
        """Flip ``joining`` workers to ``live`` (the driver just
        readmitted their state): a new view epoch."""
        with self._lock:
            ws = tuple(
                int(w) for w in (
                    workers if workers is not None
                    else [w for w, s in enumerate(self._states)
                          if s == JOINING]
                )
                if self._states[int(w)] == JOINING
            )
            if not ws:
                return self._view
            for w in ws:
                self._states[w] = LIVE
            self._epoch += 1
            self._round = int(round_index)
            self._view = MembershipView(
                self._epoch, self._round, tuple(self._states), self.spec
            )
            self.transitions.append(
                (self._epoch, self._round, "rejoin", ws)
            )
        self._note_changes([("rejoin", ws)])
        return self._view

    # ------------------------------------------------------------------
    def _note_changes(
        self, changed: List[Tuple[str, Tuple[int, ...]]]
    ) -> None:
        view = self._view
        if self._echo is not None:
            for kind, ws in changed:
                self._echo(
                    "membership: epoch %d (round %d): %s %s -> %s"
                    % (view.epoch, view.round, kind, list(ws),
                       dict(view.counts()))
                )
        _obs.instant(
            "membership_view", cat="membership",
            epoch=view.epoch, round=view.round,
            changes=[[k, list(ws)] for k, ws in changed],
            counts=view.counts(),
        )
        tm = _obs.training_metrics()
        if tm is not None:
            for kind, ws in changed:
                tm.membership_transitions.labels(kind).inc(len(ws))
        self._publish_metrics()

    def _publish_metrics(self) -> None:
        tm = _obs.training_metrics()
        if tm is None:
            return
        tm.membership_epoch.set(self._view.epoch)
        for s, n in self._view.counts().items():
            tm.membership_workers.labels(s).set(n)

    def state_dict(self) -> Dict:
        """The /healthz membership block (obs.set_membership)."""
        view = self._view
        return {
            "epoch": view.epoch,
            "round": view.round,
            "workers": view.counts(),
            "states": list(view.states),
            "slices": [list(s) for s in self.spec.slices],
            "cross_slice_every": self.spec.cross_slice_every,
            "pending_joiners": list(view.workers_in(JOINING)),
            "transitions": len(self.transitions),
        }

    # --- full job state (crash consistency, io/checkpoint extra_state)
    def export_state(self) -> Dict:
        """The roster scalars a restarted driver needs to continue the
        SAME view history: epoch, round, per-worker states, and
        leave-grace bookkeeping.  Queued-but-unapplied events are
        deliberately NOT exported — an event that never reached a
        round boundary is not yet part of the job's state (the source
        re-delivers: SIGTERM re-fires, fleet views re-ingest)."""
        with self._lock:
            return {
                "epoch": int(self._epoch),
                "round": int(self._round),
                "states": list(self._states),
                "leaving_since": {
                    str(w): int(r)
                    for w, r in self._leaving_since.items()
                },
            }

    def load_state(self, d: Dict) -> None:
        """Restore a view exported by ``export_state`` — the resumed
        epoch numbering continues where the crashed driver's stopped
        (monotonic across the restart, so downstream consumers never
        see the epoch clock rewind)."""
        states = [str(s) for s in d["states"]]
        if len(states) != self.num_workers:
            raise ValueError(
                f"jobstate roster has {len(states)} workers, spec has "
                f"{self.num_workers}"
            )
        with self._lock:
            self._epoch = int(d["epoch"])
            self._round = int(d["round"])
            self._states = states
            self._leaving_since = {
                int(w): int(r)
                for w, r in (d.get("leaving_since") or {}).items()
            }
            self._view = MembershipView(
                self._epoch, self._round, tuple(self._states), self.spec
            )
        self._publish_metrics()

    def epochs_monotonic(self) -> bool:
        """True iff the logged transition epochs strictly increase per
        bump (the chaos/bench verdict helper)."""
        es = [e for e, _, _, _ in self.transitions]
        return all(b >= a for a, b in zip(es, es[1:]))


class AutoRejoin:
    """Driver-side rejoin policy for single-process runs: request a
    departed worker's rejoin once its leave has COMPLETED (dead) and
    ``after`` round boundaries have passed since it first left — the
    stand-in for the orchestrator's relaunch notice (``cifar_app
    --elastic --rejoin_after=N``).  Call ``on_round`` right after
    ``advance``; ``after <= 0`` disables it (rejoins then come only
    from external events: fleet views, chaos, note_join)."""

    def __init__(self, controller: MembershipController, after: int):
        self.controller = controller
        self.after = int(after)
        self._gone_since: Dict[int, int] = {}

    def on_round(self, round_index: int) -> None:
        if self.after <= 0:
            return
        view = self.controller.view
        ready = []
        for w, s in enumerate(view.states):
            if s == LIVE:
                self._gone_since.pop(w, None)
                continue
            self._gone_since.setdefault(w, round_index)
            if (
                s == DEAD
                and round_index - self._gone_since[w] >= self.after
            ):
                ready.append(w)
        if ready:
            self.controller.note_join(ready)


# ----------------------------------------------------------------------
# readmission: catch up through a snapshot, broadcast, merge, zero
# momentum — the rejoin contract


def consensus_state(state, live_mask):
    """A single-replica host TrainState read from the FIRST LIVE worker
    slot of a stacked state (dead slots may hold stale params under the
    intra-slice tier, so "worker 0" is not always safe)."""
    import jax

    mask = np.asarray(live_mask, np.float32).reshape(-1)
    live = np.flatnonzero(mask > 0)
    w = int(live[0]) if live.size else 0
    host = jax.device_get(state)
    import jax.tree_util as tu

    return tu.tree_map(lambda x: x[w], host)


def readmit_state(trainer, state, restored, workers):
    """Merge a catch-up state into the stacked live state for the
    ``workers`` being readmitted: their params/stats rows come from
    ``restored`` (placed via ``trainer.broadcast_state`` — the
    restore-on-every-executor semantics), their momentum HISTORY is
    zeroed (the PR-5 rejoin contract: stale momentum must not replay),
    and every OTHER row — the survivors — is untouched."""
    import jax
    import jax.numpy as jnp

    tree_map = jax.tree_util.tree_map
    full = trainer.broadcast_state(restored)
    n = trainer.num_workers
    row = np.zeros((n,), bool)
    row[list(workers)] = True
    rowj = jnp.asarray(row)

    def pick(cur, new):
        m = rowj.reshape((n,) + (1,) * (cur.ndim - 1))
        return jnp.where(m, new, cur)

    def zero_row(cur):
        m = rowj.reshape((n,) + (1,) * (cur.ndim - 1))
        return jnp.where(m, jnp.zeros_like(cur), cur)

    return type(state)(
        tree_map(pick, state.params, full.params),
        tree_map(pick, state.stats, full.stats),
        tree_map(zero_row, state.history),
        state.iter,
    )


def readmit(
    trainer,
    solver,
    state,
    prefix: str,
    controller: MembershipController,
    round_index: int,
    snapshot: bool = True,
    live_mask=None,
    snapshot_fmt: Optional[str] = None,
    echo: Optional[Callable[[str], None]] = None,
):
    """The full readmission dance for every pending joiner: publish a
    fresh consensus snapshot (so the catch-up source is current —
    skipped when ``snapshot=False``), restore through
    ``restore_newest_valid`` (quarantining corrupt snapshots exactly
    like any other resume), merge the rejoiners in via
    ``broadcast_state`` + ``readmit_state``, and ``admit()`` the new
    epoch.  ``live_mask`` names which slots hold live consensus for the
    snapshot (defaults to the controller's own view — pass the combined
    mask when other fault channels also exclude workers).  Returns
    ``(state, view_or_None)``."""
    workers = controller.pending_joiners()
    if not workers:
        return state, None
    from sparknet_tpu.io import checkpoint

    if snapshot:
        mask = (
            controller.live_mask() if live_mask is None else live_mask
        )
        checkpoint.snapshot(
            solver, consensus_state(state, mask), prefix,
            fmt=snapshot_fmt,
        )
    restored, used = checkpoint.restore_newest_valid(solver, prefix)
    state = readmit_state(trainer, state, restored, workers)
    view = controller.admit(round_index)
    if echo is not None:
        import os

        echo(
            "membership: readmitted worker(s) %s from %s at epoch %d "
            "(momentum zeroed)"
            % (list(workers), os.path.basename(used), view.epoch)
        )
    return state, view


def readmit_from_survivors(trainer, state, controller, round_index,
                           echo=None):
    """Snapshot-less readmission (drivers with no checkpoint
    machinery): rejoiners take the live consensus state directly —
    same merge + momentum-zeroing contract, the catch-up source is the
    survivors' current weights instead of a restored snapshot."""
    workers = controller.pending_joiners()
    if not workers:
        return state, None
    restored = consensus_state(state, controller.live_mask())
    state = readmit_state(trainer, state, restored, workers)
    view = controller.admit(round_index)
    if echo is not None:
        echo(
            "membership: readmitted worker(s) %s from the survivor "
            "consensus at epoch %d (momentum zeroed)"
            % (list(workers), view.epoch)
        )
    return state, view
