"""The journaled driver loop: crash-consistent training, provable.

``run_driver`` is a small cifar10_quick parameter-averaging loop wired
the way a crash-consistent production driver must be:

- every round is bracketed by a **write-ahead intent** and a
  **durable commit** in the run journal (``io/journal.RunJournal``),
- every committed boundary snapshots the FULL job state: params +
  history (the classic snapshot) plus the CommPlane error-feedback
  residuals, the sentry EMA/cooldown, the membership view epoch and
  the data cursor (``io/checkpoint.snapshot(extra_state=...)``),
- resume reconciles ledger vs snapshots
  (``checkpoint.restore_newest_valid_journaled``): rewind to the last
  committed boundary, re-execute the one in-flight round, never
  re-execute a committed one.

The loop doubles as the kill-anywhere chaos child: ``--kill_at
PHASE:ROUND`` SIGKILLs the process at a named phase boundary —

    assemble            after the round's host batch is built
    h2d                 after the dp-sharded device placement
    execute             after the fused local-steps+average returns
    average             after the sentry consumed the round's stats
    snapshot_mid_write  mid-write of the solverstate file (the tmp is
                        written, the publish rename never happens)
    journal_mid_append  mid-append of the commit record (half a frame
                        lands durably — the torn tail truncation case)

— and ``runtime/chaos.run_kill_sweep`` drives the full sweep: each
kill-point's resumed trajectory must be BIT-IDENTICAL to an
uninterrupted control (the digest covers params, history, iter, EF
residuals and sentry EMA), with at most one replayed round.  The
``--no_journal`` leg proves the zero is not vacuous: resuming from the
plain newest snapshot resets the EF residuals and measurably diverges.

Subprocess entry::

    python -m sparknet_tpu.runtime.recover --workdir DIR --rounds 4 \
        [--kill_at execute:2] [--resume] [--no_journal]

prints one JSON line (rounds executed, final state digest, per-round
wall times, restore latency).  Importable pieces (``RecoverContext``,
``run_driver`` with ``kill=<raise>``) power the in-process tier-1
tests and the chaos harness's ``driver_kill`` fault.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal as _signal
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

KILL_POINTS = (
    "assemble",
    "h2d",
    "execute",
    "average",
    "snapshot_mid_write",
    "journal_mid_append",
    # bounded-staleness runs only (--stale_bound > 0): fires right
    # after the stale averaging boundary folded its arrival set, before
    # the worker-round vector is committed — resume must replay the
    # boundary from the journaled vector, <= stale_bound rounds
    "stale_boundary",
)


class SimulatedKill(BaseException):
    """The in-process stand-in for SIGKILL (tests / chaos driver_kill):
    raised by a kill hook, caught by the harness — deliberately a
    BaseException so no library ``except Exception`` can absorb it."""


def sigkill_self() -> None:
    os.kill(os.getpid(), _signal.SIGKILL)


def parse_kill_at(value: Optional[str]) -> Tuple[Optional[str], int]:
    if not value:
        return None, -1
    phase, _, r = value.partition(":")
    if phase not in KILL_POINTS:
        raise ValueError(
            f"kill_at phase {phase!r}: expected one of {KILL_POINTS}"
        )
    return phase, int(r or 0)


class RecoverContext:
    """Everything expensive, built once: data, solver (audit on),
    mesh, trainer (int8 delta averaging so real EF-residual state is
    carried).  Reusable across in-process control/crash/resume runs —
    the jitted programs compile once."""

    def __init__(
        self,
        workdir: str,
        workers: int = 2,
        tau: int = 2,
        batch: int = 8,
        seed: int = 7,
        compress: str = "int8",
        stale_bound: int = 0,
    ):
        import jax

        from sparknet_tpu import config as cfg, models
        from sparknet_tpu.data import CifarLoader
        from sparknet_tpu.parallel import (
            BoundedStalenessTrainer,
            ParameterAveragingTrainer,
            make_mesh,
        )
        from sparknet_tpu.solver import Solver

        self.workdir = workdir
        self.workers = workers
        self.tau = tau
        self.batch = batch
        self.seed = seed
        self.stale_bound = int(stale_bound)
        if self.stale_bound > 0:
            # stale boundaries don't compose with the comm plane's
            # EF-residual collectives; the stale recovery leg carries
            # the worker-round ledger + per-worker replicas instead
            compress = "none"
        self.compress = compress
        os.makedirs(workdir, exist_ok=True)
        data_dir = os.path.join(workdir, "data")
        if not os.path.isdir(data_dir):
            CifarLoader.write_synthetic(
                data_dir, num_train=256, num_test=32, seed=seed
            )
        self.xs, self.ys = CifarLoader(data_dir).minibatches(
            batch, train=True
        )
        netp = cfg.replace_data_layers(
            models.load_model("cifar10_quick"),
            [(batch, 3, 32, 32), (batch,)],
            [(batch, 3, 32, 32), (batch,)],
        )
        # audit=True: the sentry's stats ride the jitted round, so the
        # journaled sentry EMA is real state, not a stub
        self.solver = Solver(
            models.load_model_solver("cifar10_quick"), net_param=netp,
            audit=True,
        )
        if jax.device_count() < workers:
            raise RuntimeError(
                f"recover needs >= {workers} devices (virtual CPU mesh)"
            )
        self.mesh = make_mesh(
            {"dp": workers}, devices=jax.devices()[:workers]
        )
        if self.stale_bound > 0:
            self.trainer = BoundedStalenessTrainer(
                self.solver, self.mesh, stale_bound=self.stale_bound
            )
            # the deterministic straggler: the last worker never
            # self-arrives, so every boundary's arrival set is a pure
            # function of the (journaled) worker-round vector — the
            # bound forces it in every stale_bound-th boundary
            self.straggler = workers - 1
        else:
            self.trainer = ParameterAveragingTrainer(
                self.solver, self.mesh, compress=compress
            )
            self.straggler = None
        self.prefix = os.path.join(workdir, "recover_ckpt")

    def batch_for(self, r: int) -> Dict[str, np.ndarray]:
        """Round ``r``'s host batch, a pure function of the absolute
        round index (the shuffle-cursor discipline: resume re-derives
        the same draw from the journaled cursor, no stateful sampler to
        lose)."""
        W, tau, B, n = self.workers, self.tau, self.batch, len(self.xs)
        data = np.empty((W, tau) + self.xs[0].shape, np.float32)
        label = np.empty((W, tau, B), np.float32)
        for w in range(W):
            for t in range(tau):
                i = (r * W * tau + w * tau + t) % n
                data[w, t] = self.xs[i]
                label[w, t] = self.ys[i]
        return {"data": data, "label": label}

    def make_sentry(self):
        from sparknet_tpu.obs.health import HealthSentry

        return HealthSentry(policy="warn")

    def arrival_for(self) -> np.ndarray:
        """The boundary's self-arrival set (pure: same every round —
        the straggler's fold-ins come from the bound forcing it)."""
        arr = np.ones((self.workers,), bool)
        if self.straggler is not None:
            arr[self.straggler] = False
        return arr


def state_digest(
    state, comm_state=None, sentry_state=None, stale_state=None
) -> str:
    """Deterministic digest of the FULL job state: every TrainState
    leaf (params, stats, history, iter), the comm plane's EF residuals,
    the sentry's EMA scalars, and (stale runs) the worker-round
    ledger.  Bit-identity of two runs == equal digests."""
    import jax

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves(jax.device_get(state))
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    if comm_state is not None:
        resid = comm_state["resid"]
        for i in range(len(resid)):
            h.update(np.asarray(resid[str(i)]).tobytes())
    if sentry_state is not None:
        h.update(
            json.dumps(
                {
                    k: sentry_state.get(k)
                    for k in ("ema", "emvar", "seen", "cooldown")
                },
                sort_keys=True,
            ).encode()
        )
    if stale_state is not None:
        h.update(
            json.dumps(
                {
                    "boundary": int(np.asarray(stale_state["boundary"])),
                    "worker_rounds": [
                        int(v)
                        for v in np.asarray(
                            stale_state["worker_rounds"]
                        ).reshape(-1)
                    ],
                },
                sort_keys=True,
            ).encode()
        )
    return h.hexdigest()


def run_driver(
    ctx: RecoverContext,
    rounds: int,
    *,
    journal: bool = True,
    resume: bool = False,
    kill_at: Optional[Tuple[Optional[str], int]] = None,
    kill: Optional[Callable[[], None]] = None,
    fsync: str = "commit",
    run_dir: Optional[str] = None,
) -> Dict:
    """One driver invocation (fresh or ``resume``); returns the run
    record.  ``kill_at=(phase, round)`` arms the kill at that phase
    boundary; ``kill`` defaults to a real SIGKILL (pass a raiser for
    in-process harnesses).  ``run_dir`` overrides where the snapshots
    + ledger live (in-process harnesses run control/crash/resume legs
    in separate dirs off ONE compiled context)."""
    import jax

    from sparknet_tpu import obs as _obs
    from sparknet_tpu.io import checkpoint
    from sparknet_tpu.io.journal import RunJournal, default_journal_path
    from sparknet_tpu.parallel import (
        export_worker_history,
        export_worker_replicas,
        first_worker,
        restore_worker_history,
        restore_worker_replicas,
        shard_leading,
        stale_window,
    )
    from sparknet_tpu.parallel.hierarchy import HierarchySpec
    from sparknet_tpu.runtime import membership as membership_mod

    kill = kill or sigkill_self
    kp, kr = kill_at or (None, -1)
    stale = ctx.stale_bound > 0
    if kp == "stale_boundary" and not stale:
        raise ValueError(
            "kill_at stale_boundary needs a --stale_bound > 0 context"
        )

    def maybe_kill(phase: str, r: int) -> None:
        if kp == phase and r == kr:
            kill()

    trainer = ctx.trainer
    prefix = ctx.prefix
    if run_dir is not None:
        os.makedirs(run_dir, exist_ok=True)
        prefix = os.path.join(run_dir, "recover_ckpt")
    jr = (
        RunJournal(default_journal_path(prefix), fsync=fsync)
        if journal
        else None
    )
    sentry = ctx.make_sentry()
    # a (flat) membership controller rides along so the view epoch is
    # real journaled state: the resumed epoch clock must continue, not
    # rewind (flat spec + all-live mask => no effect on the math)
    membership = membership_mod.MembershipController(
        HierarchySpec.flat(ctx.workers)
    )

    start_round = 0
    restore_s = None
    resumed_from = None
    info = None
    try:
        if resume:
            t0 = time.perf_counter()
            st = js = None
            if jr is not None:
                try:
                    st, used, js, info = (
                        checkpoint.restore_newest_valid_journaled(
                            ctx.solver, prefix, jr
                        )
                    )
                except FileNotFoundError:
                    info = jr.reconcile()  # round 0 never committed
                start_round = info["resume_round"]
                if info["in_flight_round"] is not None:
                    tm = _obs.training_metrics()
                    if tm is not None:
                        tm.recover_replayed.inc()
            else:
                try:
                    st, used = checkpoint.restore_newest_valid(
                        ctx.solver, prefix
                    )
                    start_round = int(np.asarray(st.iter)) // ctx.tau
                except FileNotFoundError:
                    pass
            if st is not None:
                resumed_from = os.path.basename(used)
                state = trainer.broadcast_state(st)  # resets the plane
                if js:
                    if "comm" in js:
                        trainer.restore_comm_state(js["comm"])
                    if "sentry" in js:
                        sentry.load_state(js["sentry"])
                    if "membership" in js:
                        membership.load_state(js["membership"])
                    if "workers" in js:
                        # PER-WORKER momentum history: the consensus
                        # snapshot carries worker 0's only (broadcast
                        # replicated it), but each worker's local-SGD
                        # momentum differs — put the true stacks back
                        state = restore_worker_history(
                            state, js["workers"], ctx.mesh
                        )
                    if stale and "stale" in js:
                        # bounded staleness: worker replicas DIVERGE
                        # between boundaries (absent workers keep their
                        # own params), so the full per-worker stacks
                        # replace the broadcast consensus, and the
                        # worker-round ledger resumes where it was
                        state = restore_worker_replicas(
                            state, js["stale"]["replicas"], ctx.mesh
                        )
                        ctx.trainer.load_stale_state(
                            js["stale"]["ledger"]
                        )
            else:
                trainer.reset_comm_state()
                if stale:
                    trainer.reset_stale_state()
                state = trainer.init_state(seed=ctx.seed)
            restore_s = time.perf_counter() - t0
        else:
            trainer.reset_comm_state()
            if stale:
                trainer.reset_stale_state()
            state = trainer.init_state(seed=ctx.seed)

        rounds_executed: List[int] = []
        round_ms: List[float] = []
        losses = None
        for r in range(start_round, rounds):
            t_r = time.perf_counter()
            view = membership.advance(r)
            meta = {}
            if stale:
                # the journal VERSIONS every worker's round vector: the
                # intent records what each worker was about to fold,
                # the commit (below) what it folded — resume replays
                # <= stale_bound rounds from exactly this vector
                meta = {
                    "worker_rounds": [
                        int(v) for v in trainer.worker_rounds
                    ],
                    "stale_bound": ctx.stale_bound,
                }
            if jr is not None:
                # the WRITE-AHEAD intent: everything restart needs to
                # know what round ``r`` was (the exactly-once bracket)
                jr.begin_round(
                    r,
                    iter=r * ctx.tau,
                    view_epoch=view.epoch,
                    cursor=r,
                    rng="default_train_key(0)",
                    **meta,
                )
            if stale:
                # each worker consumes the window of its OWN next
                # round — a pure function of the journaled ledger
                host = stale_window(ctx.batch_for, trainer.worker_rounds)
            else:
                host = ctx.batch_for(r)
            maybe_kill("assemble", r)
            placed = shard_leading(host, ctx.mesh)
            maybe_kill("h2d", r)
            if stale:
                state, losses, stats = trainer.round(
                    state, placed, arrived=ctx.arrival_for(),
                    round_index=r,
                )
            else:
                state, losses, stats = trainer.round(
                    state, placed, round_index=r
                )
            rounds_executed.append(r)
            maybe_kill("execute", r)
            if stale:
                # the mid-async-boundary preemption: the arrival set
                # folded and the ledger advanced in memory, but neither
                # the snapshot nor the commit record landed
                maybe_kill("stale_boundary", r)
                lb = trainer.last_boundary
                sentry.observe(
                    r, losses, stats,
                    arrived=lb["arrived"],
                    worker_rounds=[
                        lb["boundary"] - l for l in lb["lag"]
                    ],
                )
            else:
                sentry.observe(r, losses, stats)
            maybe_kill("average", r)
            # the durable boundary: full job state beside params, then
            # the commit record referencing it
            host_state = jax.device_get(state)
            consensus = first_worker(host_state)
            extra = {
                "sentry": sentry.export_state(),
                "membership": membership.export_state(),
                "cursor": {"next_round": r + 1},
                # per-worker momentum stacks (the consensus model/state
                # files keep worker 0's view only)
                "workers": export_worker_history(host_state),
            }
            comm_state = trainer.export_comm_state()
            if comm_state is not None:
                extra["comm"] = comm_state
            if stale:
                # full per-worker replicas + the ledger: stale worker
                # states diverge by design, so the consensus snapshot
                # under-determines the fleet
                extra["stale"] = {
                    "ledger": trainer.export_stale_state(),
                    "replicas": export_worker_replicas(host_state),
                }
            if kp == "snapshot_mid_write" and r == kr:
                # the preemption lands while the solverstate tmp is
                # written but unpublished — restore must never see it
                checkpoint.set_crash_hook(
                    lambda path: (
                        kill()
                        if path.endswith(".solverstate.npz")
                        else None
                    )
                )
            try:
                _, state_path = checkpoint.snapshot(
                    ctx.solver, consensus, prefix,
                    fmt="BINARYPROTO", extra_state=extra,
                )
            finally:
                checkpoint.set_crash_hook(None)
            if jr is not None:
                if kp == "journal_mid_append" and r == kr:
                    jr.crash_hook = kill
                commit_meta = dict(meta)
                if stale:
                    # post-fold vector: what the boundary durably owns
                    commit_meta["worker_rounds"] = [
                        int(v) for v in trainer.worker_rounds
                    ]
                jr.commit_round(
                    r,
                    iter=(r + 1) * ctx.tau,
                    snapshot=os.path.basename(state_path),
                    **commit_meta,
                )
            round_ms.append((time.perf_counter() - t_r) * 1e3)

        final_comm = trainer.export_comm_state()
        final_sentry = sentry.export_state()
        final_stale = trainer.export_stale_state() if stale else None
        return {
            "rounds": rounds,
            "start_round": start_round,
            "rounds_executed": rounds_executed,
            "final_iter": int(
                np.asarray(jax.device_get(state.iter)).reshape(-1)[0]
            ),
            "final_digest": state_digest(
                state, final_comm, final_sentry, final_stale
            ),
            "final_loss": (
                float(np.mean(np.asarray(jax.device_get(losses))))
                if losses is not None
                else None
            ),
            "sentry_ema": final_sentry["ema"],
            "view_epoch": membership.view.epoch,
            "journal": journal,
            "journal_truncated_bytes": (
                jr.truncated_bytes if jr is not None else 0
            ),
            "resumed_from": resumed_from,
            "resume_info": info,
            "stale_bound": ctx.stale_bound,
            "worker_rounds": (
                [int(v) for v in trainer.worker_rounds]
                if stale
                else None
            ),
            "restore_s": (
                round(restore_s, 4) if restore_s is not None else None
            ),
            "round_ms": [round(m, 2) for m in round_ms],
        }
    finally:
        if jr is not None:
            jr.close()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", required=True)
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--tau", type=int, default=2)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--compress", default="int8")
    p.add_argument(
        "--stale_bound", type=int, default=0,
        help="run the bounded-staleness driver leg: the last worker "
        "straggles (never self-arrives; the bound forces it), the "
        "journal versions the worker-round vector, snapshots carry "
        "full per-worker replicas.  0 = the synchronous driver",
    )
    p.add_argument(
        "--kill_at", default=None, metavar="PHASE:ROUND",
        help="SIGKILL self at this phase boundary of this round "
        f"(phases: {', '.join(KILL_POINTS)})",
    )
    p.add_argument("--resume", action="store_true")
    p.add_argument(
        "--no_journal", dest="journal", action="store_false",
        default=True,
        help="run without the ledger (the divergence control: resume "
        "resets EF residuals / sentry state)",
    )
    p.add_argument("--fsync", default="commit")
    args = p.parse_args(argv)

    # the virtual mesh must exist before any backend use (same rule as
    # bench.py's multi-device modes)
    from sparknet_tpu.utils.devices import force_virtual_cpu_devices

    force_virtual_cpu_devices(max(args.workers, 2))

    ctx = RecoverContext(
        args.workdir,
        workers=args.workers,
        tau=args.tau,
        batch=args.batch,
        seed=args.seed,
        compress=args.compress,
        stale_bound=args.stale_bound,
    )
    rec = run_driver(
        ctx,
        args.rounds,
        journal=args.journal,
        resume=args.resume,
        kill_at=parse_kill_at(args.kill_at),
        fsync=args.fsync,
    )
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
