"""JaxNet — the net compiler: NetParameter -> pure jitted functions.

This is the TPU-native replacement for the whole reference engine stack
``Net<Dtype>`` + ``Solver``'s forward path (``caffe/src/caffe/net.cpp``) and
for the Scala-side ``CaffeNet`` facade (``src/main/scala/libs/Net.scala``):

- ``Net::Init`` (DAG build, phase filter, param sharing by name at
  ``net.cpp:470``)  ->  ``JaxNet.__init__`` (static shape walk + blob init)
- ``Net::ForwardFromTo`` layer loop  ->  ``JaxNet.apply`` — a pure function
  of (params, stats, batch, rng) traced once under ``jit``; XLA fuses the
  layer chain, so there is no per-layer dispatch at run time
- data/diff twin blobs + ``Net::Update``  ->  gradients are values from
  ``jax.grad``; no mutable state anywhere
- ``getData``/``getWeights``/``setWeights`` float-copy loops
  (``Net.scala:131-191``)  ->  zero-copy pytrees of device arrays

Params layout parity: ``params[layer_name] == [weight, bias, ...]`` ordered
exactly like the reference layer's ``blobs_`` vector, with shared params
stored once under the owning layer (ParamSpec.name sharing).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sparknet_tpu.config.schema import NetParameter, NetState
from sparknet_tpu.graph import filter_net, toposort_check
from sparknet_tpu.ops import fillers  # noqa: F401  (registry population)
from sparknet_tpu.ops import attention, common, data_layers, losses, vision  # noqa: F401
from sparknet_tpu.ops.base import BlobDef, Layer, create_layer

Params = Dict[str, List[jax.Array]]
Stats = Dict[str, List[jax.Array]]


@dataclasses.dataclass
class _BlobRef:
    """Where one layer blob lives: (collection, owner layer, index)."""

    collection: str  # "params" | "stats"
    owner: str
    index: int


@dataclasses.dataclass
class NetOutputs:
    blobs: Dict[str, jax.Array]
    loss: jax.Array
    stats: Stats


class JaxNet:
    """A compiled net for one phase.

    Parameters
    ----------
    net_param:
        The (unfiltered) NetParameter; phase filtering happens here.
    phase:
        "TRAIN" or "TEST".
    feed_shapes:
        Extra {top_name: shape} for host-fed data layers that don't declare
        shapes inline.
    """

    def __init__(
        self,
        net_param: NetParameter,
        phase: str = "TRAIN",
        feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
        stages: Sequence[str] = (),
        level: int = 0,
        compute_dtype: Optional[str] = None,
    ):
        # compute_dtype="bfloat16" runs layer compute in bf16 (params stay
        # f32 master copies; loss layers upcast to f32) — the TPU-native
        # mixed-precision recipe. None keeps full f32 (reference numerics).
        self.compute_dtype = (
            jnp.dtype(compute_dtype) if compute_dtype else None
        )
        self.phase = phase.upper()
        state = NetState(phase=self.phase, level=level, stage=list(stages))
        self.net_param = filter_net(net_param, state)
        self.name = self.net_param.name
        feed_shapes = {k: tuple(map(int, v)) for k, v in (feed_shapes or {}).items()}

        # net-level `input:` declarations are host-fed blobs too
        for i, blob in enumerate(self.net_param.input):
            if blob not in feed_shapes:
                if i < len(self.net_param.input_shape):
                    feed_shapes[blob] = tuple(
                        int(d) for d in self.net_param.input_shape[i].dim
                    )
                elif len(self.net_param.input_dim) >= 4 * (i + 1):
                    feed_shapes[blob] = tuple(
                        self.net_param.input_dim[4 * i : 4 * i + 4]
                    )
        toposort_check(self.net_param, external_tops=list(feed_shapes))

        self.layers: List[Layer] = []
        self.blob_shapes: Dict[str, Tuple[int, ...]] = dict(feed_shapes)
        self.feed_blobs: List[str] = list(feed_shapes)
        self._blob_defs: Dict[str, List[BlobDef]] = {}
        self._blob_refs: Dict[str, List[_BlobRef]] = {}
        self._loss_weights: Dict[str, List[float]] = {}
        param_owners: Dict[str, _BlobRef] = {}  # ParamSpec.name -> ref

        counts: Dict[str, int] = {}
        for lp in self.net_param.layer:
            layer = create_layer(lp, self.phase)
            if layer.name in counts:
                raise ValueError(f"duplicate layer name {layer.name!r}")
            counts[layer.name] = 1
            bshapes = [self.blob_shapes[b] for b in lp.bottom]

            if isinstance(layer, data_layers._HostFed):
                declared = layer.declared_shapes()
                tshapes = []
                for i, top in enumerate(lp.top):
                    if declared is not None and i < len(declared):
                        shape = declared[i]
                    elif top in feed_shapes:
                        shape = feed_shapes[top]
                    else:
                        raise ValueError(
                            f"data layer {layer.name!r}: no shape for top "
                            f"{top!r}; pass feed_shapes"
                        )
                    tshapes.append(tuple(shape))
                    self.feed_blobs.append(top)
            else:
                tshapes = layer.out_shapes(bshapes)

            defs = layer.blob_defs(bshapes)
            refs: List[_BlobRef] = []
            pi = si = 0
            for bi, d in enumerate(defs):
                spec = lp.param[bi] if bi < len(lp.param) else None
                shared_name = spec.name if spec and spec.name else None
                if shared_name and shared_name in param_owners:
                    owner_ref = param_owners[shared_name]
                    owner_defs = self._blob_defs[owner_ref.owner]
                    mode = (spec.share_mode or "STRICT").upper()
                    if mode == "STRICT" and tuple(
                        owner_defs[owner_ref.index].shape
                    ) != tuple(d.shape):
                        raise ValueError(
                            f"shared param {shared_name!r}: shape mismatch "
                            f"{owner_defs[owner_ref.index].shape} vs {d.shape}"
                        )
                    refs.append(owner_ref)
                else:
                    coll = "params" if d.learnable else "stats"
                    ref = _BlobRef(coll, layer.name, pi if d.learnable else si)
                    if d.learnable:
                        pi += 1
                    else:
                        si += 1
                    refs.append(ref)
                    if shared_name:
                        param_owners[shared_name] = ref

            self._blob_defs[layer.name] = defs
            self._blob_refs[layer.name] = refs
            self._loss_weights[layer.name] = layer.loss_weights()
            for top, shape in zip(lp.top, tshapes):
                self.blob_shapes[top] = tuple(int(x) for x in shape)
            self.layers.append(layer)

        # dedupe feed blobs, preserve order
        seen = set()
        self.feed_blobs = [
            b for b in self.feed_blobs if not (b in seen or seen.add(b))
        ]

        self._plan_fusion()
        self._plan_hconv()

    # ------------------------------------------------------------------
    # Layer fusion (TPU-first: the LRN+MaxPool sandwich never
    # materializes the LRN output in HBM — see ops/pallas_plp.py)
    # ------------------------------------------------------------------
    def _plan_fusion(self) -> None:
        import os

        self._plp_fused: Dict[int, Tuple[str, object]] = {}
        self._plp_skip: set = set()
        # Opt-in (SPARKNET_FUSION=1): on the current virtualized v5e the
        # Mosaic kernel's per-band overheads outweigh its HBM savings
        # (measured 2-5x slower than the XLA lowering — see
        # ops/pallas_plp.py and PERF.md); the kernel is kept correct and
        # tested as the template for environments where the tradeoff
        # flips.
        if os.environ.get("SPARKNET_FUSION", "") != "1":
            return
        if self.phase != "TRAIN":
            # keep the full named-blob map (getData parity) outside the
            # training hot path
            return
        from sparknet_tpu.config.schema import LRNParameter
        from sparknet_tpu.ops import pallas_plp
        from sparknet_tpu.ops.vision import _pool_geometry

        consumers: Dict[str, int] = {}
        for layer in self.layers:
            for b in layer.lp.bottom:
                consumers[b] = consumers.get(b, 0) + 1
        for i in range(len(self.layers) - 1):
            lrn, pool = self.layers[i], self.layers[i + 1]
            if lrn.lp.type != "LRN" or pool.lp.type != "Pooling":
                continue
            mid = lrn.lp.top[0]
            if list(pool.lp.bottom) != [mid] or consumers.get(mid, 0) != 1:
                continue
            if any(self._loss_weights[lrn.name]) or any(
                self._loss_weights[pool.name]
            ):
                continue
            np_ = lrn.lp.lrn_param or LRNParameter()
            shape = self.blob_shapes[lrn.lp.bottom[0]]
            if len(shape) != 4:
                continue
            h, w = shape[2], shape[3]
            pp = pool.lp.pooling_param
            if pp.global_pooling:
                continue
            try:
                kernel, stride, pad, _ = _pool_geometry(pp, h, w)
            except ValueError:
                continue
            if not pallas_plp.fusable(
                np_.norm_region, np_.local_size, pp.pool, kernel, stride,
                pad, h, w,
            ):
                continue
            n, alpha, beta, k = (
                int(np_.local_size),
                float(np_.alpha),
                float(np_.beta),
                float(np_.k),
            )

            def fn(x, n=n, alpha=alpha, beta=beta, k=k):
                return pallas_plp.lrn_maxpool(x, n, alpha, beta, k)

            self._plp_fused[i] = (pool.lp.top[0], fn)
            self._plp_skip.add(i + 1)

    def _plan_hconv(self) -> None:
        """Horizontal convolution fusion (default on; SPARKNET_HFUSE=0
        opts out): sibling Convolution layers reading the *same* bottom
        with identical geometry (the Inception pattern — 1x1, 3x3-reduce
        and 5x5-reduce branches all read the block input; ResNet's
        stage-entry projection + first bottleneck conv) execute as ONE
        convolution whose output channels are the members' concatenated,
        then split back to the named tops.  Each small conv tiles the
        128x128 MXU poorly and re-reads the input from HBM; the fused
        conv does one read and one large contraction — measured +6%
        GoogLeNet throughput on v5e (PERF.md).  Parameters stay
        per-layer (concat happens inside the step), so checkpoints,
        weight import and the blob map are unchanged."""
        import os

        self._hconv_groups: Dict[int, dict] = {}
        self._hconv_skip: set = set()
        if os.environ.get("SPARKNET_HFUSE", "1") == "0":
            return
        # measured on v5e (PERF.md): 3+-way groups (Inception branches)
        # win +6%; 2-way groups (ResNet stage-entry projection pairs)
        # LOSE ~4% — the concat/slice overhead beats the tiling gain.
        min_members = int(os.environ.get("SPARKNET_HFUSE_MIN", "3"))
        groups: Dict[tuple, List[int]] = {}
        for li, layer in enumerate(self.layers):
            lp = layer.lp
            if lp.type != "Convolution" or len(lp.bottom) != 1:
                continue
            cp = lp.convolution_param
            if max(1, cp.group) != 1:
                continue
            if any(self._loss_weights[layer.name]):
                continue
            try:
                geom = layer._geometry(self.blob_shapes[lp.bottom[0]])
            except Exception:
                continue
            key = (lp.bottom[0], geom, bool(cp.bias_term))
            groups.setdefault(key, []).append(li)
        for key, lis in groups.items():
            if len(lis) < min_members:
                continue
            bottom = key[0]
            # executing every member at the leader's slot must not change
            # what anything reads.  Two hazards: (a) a layer in the fused
            # span rewrites the shared bottom in place — members would
            # read different versions of it; (b) a member's top name is
            # produced or read by some layer between the leader and that
            # member's original slot (legal top-name rebinding,
            # graph.py toposort) — early production would change what
            # that layer sees.  Layers at/after a member's original slot
            # are unaffected (production only moves earlier).
            if any(
                bottom in self.layers[mid].lp.top
                for mid in range(lis[0], lis[-1] + 1)
            ):
                continue
            hazard = False
            for li in lis[1:]:
                t = self.layers[li].lp.top[0]
                for mid in range(lis[0], li):
                    lm = self.layers[mid].lp
                    if t in lm.top or t in lm.bottom:
                        hazard = True
                        break
                if hazard:
                    break
            if hazard:
                continue
            leader = lis[0]
            self._hconv_groups[leader] = {
                "lis": lis,
                "geom": key[1],
                "bias": key[2],
                # each member's own num_output — NOT blob_shapes[top],
                # which holds the final binding of a possibly-rebound name
                "sizes": [
                    self.layers[li].lp.convolution_param.num_output
                    for li in lis
                ],
            }
            self._hconv_skip.update(lis[1:])

    def _apply_hconv(self, group, x, params, perturb, blobs) -> None:
        """Run one fused sibling-conv group and write every member top."""
        (kh, kw), (sh, sw), (ph, pw), (dh, dw) = group["geom"]
        members = [self.layers[li] for li in group["lis"]]
        gathered = [self._gather_blobs(m.name, params, {}) for m in members]
        cd = self.compute_dtype
        if cd is not None and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(cd)
            gathered = [[b.astype(cd) for b in g] for g in gathered]
        w = jnp.concatenate([g[0] for g in gathered], axis=0)
        y = jax.lax.conv_general_dilated(
            x,
            w,
            window_strides=(sh, sw),
            padding=[(ph, ph), (pw, pw)],
            rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if group["bias"]:
            b = jnp.concatenate([g[1] for g in gathered])
            y = y + b.reshape(1, -1, 1, 1)
        off = 0
        for m, size in zip(members, group["sizes"]):
            top = m.lp.top[0]
            out = jax.lax.slice_in_dim(y, off, off + size, axis=1)
            off += size
            if perturb is not None and top in perturb:
                out = out + perturb[top]
            blobs[top] = out

    # ------------------------------------------------------------------
    # Introspection (the `num_layers`/`layer_names`/blob enumeration side
    # of the engine API, ccaffe.h:30-45)
    # ------------------------------------------------------------------
    @property
    def layer_names(self) -> List[str]:
        return [l.name for l in self.layers]

    def param_multipliers(self) -> Tuple[Params, Params]:
        """Per-blob (lr_mult, decay_mult) pytrees matching init() params
        structure (reference: ParamSpec handling in ``net.cpp
        AppendParam``)."""
        lr: Dict[str, List[float]] = {}
        decay: Dict[str, List[float]] = {}
        for layer in self.layers:
            for d, ref in zip(
                self._blob_defs[layer.name], self._blob_refs[layer.name]
            ):
                if ref.collection == "params" and ref.owner == layer.name:
                    lr.setdefault(layer.name, []).append(d.lr_mult)
                    decay.setdefault(layer.name, []).append(d.decay_mult)
        return lr, decay

    # ------------------------------------------------------------------
    # Init
    # ------------------------------------------------------------------
    def init(self, seed: int = 0) -> Tuple[Params, Stats]:
        """Initialize all blobs with their fillers (Net::Init's filler pass)."""
        key = jax.random.PRNGKey(seed)
        params: Params = {}
        stats: Stats = {}
        for li, layer in enumerate(self.layers):
            defs = self._blob_defs[layer.name]
            refs = self._blob_refs[layer.name]
            if not defs:
                continue
            lkey = jax.random.fold_in(key, li)
            keys = jax.random.split(lkey, len(defs))
            for d, ref, k in zip(defs, refs, keys):
                if ref.owner != layer.name:
                    continue  # shared: owner already initialized it
                arr = fillers.fill(k, d.shape, d.filler)
                if ref.collection == "params":
                    params.setdefault(layer.name, []).append(arr)
                else:
                    stats.setdefault(layer.name, []).append(arr)
        return params, stats

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def _gather_blobs(self, layer_name: str, params: Params, stats: Stats):
        out = []
        for ref in self._blob_refs[layer_name]:
            coll = params if ref.collection == "params" else stats
            out.append(coll[ref.owner][ref.index])
        return out

    def apply(
        self,
        params: Params,
        stats: Stats,
        batch: Dict[str, jax.Array],
        rng: Optional[jax.Array] = None,
        train: Optional[bool] = None,
        perturb: Optional[Dict[str, jax.Array]] = None,
    ) -> NetOutputs:
        """Run the net. Returns every named blob (the ``getData`` analog,
        Net.scala:173-191), the weighted total loss, and updated stats.

        ``perturb`` adds a zero-valued tensor to each named top as it is
        produced — differentiating w.r.t. those taps yields every
        activation gradient in one backward pass (the diff side of the
        reference's data/diff twin blobs; used by ``Solver.debug_info_pass``,
        net.cpp:648-735)."""
        train = (self.phase == "TRAIN") if train is None else train
        blobs: Dict[str, jax.Array] = {}
        for b in self.feed_blobs:
            if b not in batch:
                raise ValueError(f"batch missing feed blob {b!r}")
            blobs[b] = jnp.asarray(batch[b])
        new_stats: Stats = {k: list(v) for k, v in stats.items()}
        loss = jnp.asarray(0.0, jnp.float32)

        cd = self.compute_dtype
        for li, layer in enumerate(self.layers):
            lp = layer.lp
            if li in self._hconv_skip:
                continue
            if li in self._hconv_groups:
                self._apply_hconv(
                    self._hconv_groups[li], blobs[lp.bottom[0]], params,
                    perturb, blobs,
                )
                continue
            if li in self._plp_skip:
                continue
            if li in self._plp_fused:
                pool_top, fn = self._plp_fused[li]
                x = blobs[lp.bottom[0]]
                if cd is not None and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(cd)
                y = fn(x)
                if perturb is not None and pool_top in perturb:
                    y = y + perturb[pool_top]
                blobs[pool_top] = y
                continue
            if isinstance(layer, data_layers._HostFed):
                # host blobs keep their dtype: index-valued blobs (labels)
                # must never round through bf16; consumers cast as needed
                tops = [blobs[t] for t in lp.top]
            else:
                lblobs = self._gather_blobs(layer.name, params, new_stats)
                bottoms = [blobs[b] for b in lp.bottom]
                if cd is not None:
                    if layer.IS_LOSS:
                        # losses compute in f32 for stable log/exp; the
                        # label bottom is f32 already (exact indices)
                        bottoms = [b.astype(jnp.float32) for b in bottoms]
                    elif not layer.MIXED_PRECISION_EXEMPT:
                        lblobs = [b.astype(cd) for b in lblobs]
                        bottoms = [
                            b.astype(cd)
                            if jnp.issubdtype(b.dtype, jnp.floating)
                            else b
                            for b in bottoms
                        ]
                lrng = jax.random.fold_in(rng, li) if rng is not None else None
                tops, updated = layer.apply(lblobs, bottoms, lrng, train)
                if updated is not None:
                    refs = self._blob_refs[layer.name]
                    for d, ref, arr in zip(
                        self._blob_defs[layer.name], refs, updated
                    ):
                        if ref.collection == "stats":
                            # keep stat blobs at their master dtype even
                            # under bf16 compute
                            cur = new_stats[ref.owner][ref.index]
                            new_stats[ref.owner][ref.index] = arr.astype(
                                cur.dtype
                            )
            if perturb is not None:
                tops = [
                    top + perturb[name] if name in perturb else top
                    for name, top in zip(lp.top, tops)
                ]
            for w, top, name in zip(
                self._loss_weights[layer.name], tops, lp.top
            ):
                if w:
                    loss = loss + w * jnp.sum(top)
            for name, top in zip(lp.top, tops):
                blobs[name] = top
        return NetOutputs(blobs=blobs, loss=loss, stats=new_stats)

    def forward(
        self,
        params: Params,
        stats: Stats,
        batch: Dict[str, jax.Array],
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, jax.Array]:
        """Inference forward returning all blobs (FeaturizerApp's
        forward+getData path, FeaturizerApp.scala:88-103)."""
        return self.apply(params, stats, batch, rng=rng, train=False).blobs

    def loss_fn(self, params, stats, batch, rng=None, train=True):
        """(loss, (blobs, stats)) — the function handed to ``jax.grad``."""
        out = self.apply(params, stats, batch, rng=rng, train=train)
        return out.loss, (out.blobs, out.stats)
