"""Graph passes over NetParameter.

The reference runs FilterNet (phase/stage/level rules, ``net.cpp:287-366``)
then InsertSplits (``insert_splits.cpp``) before building.  Here only the
filter pass survives: split insertion existed to give hand-written backward
passes explicit gradient-accumulation points, and ``jax.grad`` accumulates
fan-out gradients natively, so that pass is a no-op by construction.
"""

from __future__ import annotations

from typing import List

from sparknet_tpu.config.schema import (
    LayerParameter,
    NetParameter,
    NetState,
    NetStateRule,
)

__all__ = ["filter_net", "state_meets_rule", "toposort_check"]


def state_meets_rule(state: NetState, rule: NetStateRule) -> bool:
    """NetState vs NetStateRule matching (reference: ``net.cpp
    StateMeetsRule``)."""
    if rule.phase is not None and rule.phase != state.phase:
        return False
    if rule.min_level is not None and state.level < rule.min_level:
        return False
    if rule.max_level is not None and state.level > rule.max_level:
        return False
    for s in rule.stage:
        if s not in state.stage:
            return False
    for s in rule.not_stage:
        if s in state.stage:
            return False
    return True


def _layer_included(layer: LayerParameter, state: NetState) -> bool:
    # legacy per-layer phase field acts like an include rule
    if layer.phase is not None and not layer.include and layer.phase != state.phase:
        return False
    if layer.include:
        return any(state_meets_rule(state, r) for r in layer.include)
    return not any(state_meets_rule(state, r) for r in layer.exclude)


def filter_net(net: NetParameter, state: NetState) -> NetParameter:
    """Return a copy of ``net`` keeping only layers whose rules admit
    ``state``."""
    out = net.copy()
    out.state = NetState(
        phase=state.phase, level=state.level, stage=list(state.stage)
    )
    out.layer = [l for l in net.layer if _layer_included(l, state)]
    return out


def toposort_check(net: NetParameter, external_tops: List[str] = ()) -> None:
    """Validate the reference's execution contract: layers run in listed
    order and every bottom must already be produced (``net.cpp
    AppendBottom`` errors otherwise).  In-place tops rebind the same name."""
    available = set(external_tops) | set(net.input)
    for layer in net.layer:
        for b in layer.bottom:
            if b not in available:
                raise ValueError(
                    f"layer {layer.name!r}: unknown bottom blob {b!r} "
                    f"(blob order follows listed layer order)"
                )
        available.update(layer.top)
