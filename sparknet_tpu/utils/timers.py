"""Timers (reference: ``include/caffe/util/benchmark.hpp:10-46``).

``Timer`` syncs the device (block_until_ready on a token) the way the
reference's cudaEvent timer syncs the stream; ``CPUTimer`` is wall clock.
"""

from __future__ import annotations

import time
from typing import Optional

import jax


class CPUTimer:
    def __init__(self):
        self._start: Optional[float] = None
        self._elapsed = 0.0
        self.has_run_at_least_once = False

    def start(self):
        self._start = time.perf_counter()
        return self

    def stop(self):
        if self._start is not None:
            self._elapsed = time.perf_counter() - self._start
            self.has_run_at_least_once = True
            self._start = None
        return self

    def milli_seconds(self) -> float:
        return self._elapsed * 1e3

    def micro_seconds(self) -> float:
        return self._elapsed * 1e6

    def seconds(self) -> float:
        return self._elapsed


class Timer(CPUTimer):
    """Device-synchronized timer: stop() waits for the given arrays (or
    all pending work) before reading the clock."""

    def __init__(self, sync_on=None):
        super().__init__()
        self._sync_on = sync_on

    def stop(self):
        if self._sync_on is not None:
            # sparknet: sync-ok(device-synchronized timer: the sync IS the contract, cudaEvent-style)
            jax.block_until_ready(self._sync_on)
        return super().stop()
