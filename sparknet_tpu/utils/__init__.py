"""Aux subsystems: timers, signal handling, retry/backoff, profiling,
experiment logs."""

from sparknet_tpu.utils.retry import (  # noqa: F401
    RetryBudgetExceeded,
    RetryPolicy,
    retry_call,
)
from sparknet_tpu.utils.signals import SignalHandler, SolverAction  # noqa: F401
from sparknet_tpu.utils.timers import CPUTimer, Timer  # noqa: F401
from sparknet_tpu.utils.trainlog import TrainingLog  # noqa: F401
