"""Aux subsystems: timers, signal handling, profiling, experiment logs."""

from sparknet_tpu.utils.signals import SignalHandler, SolverAction  # noqa: F401
from sparknet_tpu.utils.timers import CPUTimer, Timer  # noqa: F401
from sparknet_tpu.utils.trainlog import TrainingLog  # noqa: F401
