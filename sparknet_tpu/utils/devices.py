"""Virtual device-platform control for tests and dryruns.

Multi-chip shardings are validated without multi-chip hardware the way
SURVEY.md §4 prescribes: force ``n`` virtual CPU devices via
``--xla_force_host_platform_device_count`` and run the real pjit/shard_map
paths on that mesh. The axon TPU tunnel registers itself via sitecustomize
at interpreter start and pins ``JAX_PLATFORMS=axon``, so plain env vars are
not enough — the live jax config must be flipped back to cpu before (or in
spite of) any backend use.
"""

import os
import re

_COUNT_FLAG = "xla_force_host_platform_device_count"


def force_virtual_cpu_devices(n_devices: int) -> None:
    """Force jax onto at least ``n_devices`` virtual CPU devices.

    Safe to call before or after ``import jax``; must be called before the
    first *use* of a backend in this process for the flag to take effect (XLA
    parses ``XLA_FLAGS`` once per process — if a backend already initialised
    with a smaller count, the best we can do is reset it and re-check).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --{_COUNT_FLAG}={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = (
            flags[: m.start()] + f"--{_COUNT_FLAG}={n_devices}" + flags[m.end() :]
        )

    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        # A backend initialised before the env was set (e.g. the axon
        # sitecustomize probed devices). Resetting makes jax rebuild the CPU
        # client; this recovers platform pinning, but XLA_FLAGS is parsed
        # only once per process, so a stale smaller device count cannot be
        # fixed here — re-check and fail loudly rather than let callers hit
        # confusing downstream mesh errors.
        try:
            jax.clear_backends()
        except Exception:
            try:
                from jax.extend import backend as _backend

                _backend.clear_backends()
            except Exception:
                pass  # fall through to the loud re-check below
        if len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"need {n_devices} virtual CPU devices but jax sees "
                f"{len(jax.devices())}; a backend initialised before "
                f"XLA_FLAGS could take effect — set XLA_FLAGS="
                f"--{_COUNT_FLAG}={n_devices} JAX_PLATFORMS=cpu in the "
                f"environment before starting Python"
            )
