"""Retry with exponential backoff and full jitter — the I/O resilience
layer.

SparkNet inherited fault tolerance from Spark's RDD lineage: a lost
partition was recomputed and the driver loop never noticed
(SparkNet §3; the reference's own restart-from-snapshot is SURVEY §5).
The TPU rewrite talks to object stores and record DBs directly, so
transient I/O failure has to be absorbed here instead: every network
fetch goes through ``retry_call`` with

- **exponential backoff + full jitter**: attempt ``k`` sleeps
  ``uniform(0, min(cap, base * 2**k))`` (the AWS-recommended full-jitter
  schedule — decorrelates a fleet of workers hammering a recovering
  endpoint),
- **a per-call retry budget**: total sleep across attempts is bounded by
  ``budget_s`` so a stuck endpoint fails the call in bounded time
  instead of retrying forever,
- **retryable-error classification**: 5xx/429/timeouts/connection-resets
  retry; other 4xx (permanent: bad key, no auth) fail immediately,
- **Retry-After honoring**: a 429/503 carrying ``Retry-After: N`` floors
  the computed backoff at ``min(N, cap)`` (the serving front-end emits
  exactly this header — ``serve/server.py``).

Deterministic injection/testing: pass ``rng=random.Random(seed)`` and/or
``sleep=`` to make schedules reproducible without real waiting.
"""

from __future__ import annotations

import errno
import http.client
import os
import random
import socket
import time
import urllib.error
from dataclasses import dataclass
from typing import Callable, Optional, TypeVar

from sparknet_tpu import obs

T = TypeVar("T")

# OS-level errno values that mean "the far side hiccuped", not "you asked
# for something that does not exist"
_RETRYABLE_ERRNOS = frozenset(
    {
        errno.ECONNRESET,
        errno.ECONNREFUSED,
        errno.ECONNABORTED,
        errno.ETIMEDOUT,
        errno.EPIPE,
        errno.ENETUNREACH,
        errno.EHOSTUNREACH,
        errno.EAGAIN,
    }
)

# HTTP statuses worth retrying: throttling + anything server-side
_RETRYABLE_HTTP = frozenset({408, 429, 500, 502, 503, 504})


class RetryBudgetExceeded(OSError):
    """All attempts (or the sleep budget) exhausted; ``__cause__`` is the
    last underlying error.  Subclasses ``OSError`` so callers with
    ordinary I/O-error handling (``except OSError``) treat exhaustion as
    the I/O failure it is — e.g. ``HTTPStore.list``'s index.txt ->
    auto-index fallback keeps working when the index fetch exhausts its
    budget rather than failing on the first attempt."""


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule knobs.  ``SPARKNET_RETRY_ATTEMPTS`` /
    ``SPARKNET_RETRY_BUDGET_S`` override the defaults process-wide (ops
    escape hatch; tests pass explicit policies)."""

    max_attempts: int = 5
    base_s: float = 0.05
    cap_s: float = 5.0
    budget_s: float = 30.0

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            max_attempts=int(os.environ.get("SPARKNET_RETRY_ATTEMPTS", "5")),
            budget_s=float(os.environ.get("SPARKNET_RETRY_BUDGET_S", "30")),
        )


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """Seconds from a ``Retry-After`` header, if the error carries one
    (numeric form only; HTTP-date is rare and not worth stdlib date
    parsing here)."""
    headers = getattr(exc, "headers", None)
    if headers is None:
        return None
    try:
        val = headers.get("Retry-After")
    except AttributeError:
        return None
    if val is None:
        return None
    try:
        return max(0.0, float(val))
    except ValueError:
        return None


def is_retryable(exc: BaseException) -> bool:
    """Transient vs permanent classification.

    Retryable: 5xx/429/408 HTTP statuses, socket timeouts, connection
    resets/refusals, remote disconnects, truncated reads, and URLErrors
    whose underlying reason is one of those.  NOT retryable: other 4xx
    (permanent client errors — retrying a 404 just burns the budget) and
    non-network OSErrors (ENOENT and friends)."""
    # HTTPError first: it subclasses URLError AND OSError
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in _RETRYABLE_HTTP or exc.code >= 500
    if isinstance(exc, urllib.error.URLError):
        reason = exc.reason
        if isinstance(reason, BaseException):
            return is_retryable(reason)
        return True  # bare-string reason: DNS hiccups etc — assume transient
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return True
    if isinstance(exc, socket.gaierror):
        # DNS: EAI_AGAIN ("temporary failure in name resolution") is the
        # transient one; NXDOMAIN and friends are permanent
        return exc.errno in (
            socket.EAI_AGAIN,
            getattr(socket, "EAI_NODATA", socket.EAI_AGAIN),
        )
    if isinstance(exc, ConnectionError):  # reset/refused/aborted
        return True
    if isinstance(
        exc,
        (
            http.client.RemoteDisconnected,
            http.client.IncompleteRead,
            http.client.BadStatusLine,
        ),
    ):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _RETRYABLE_ERRNOS
    return False


def backoff_s(
    attempt: int, policy: RetryPolicy, rng: random.Random
) -> float:
    """Full-jitter delay before retry number ``attempt`` (0-based)."""
    return rng.uniform(0.0, min(policy.cap_s, policy.base_s * (2.0 ** attempt)))


def retry_call(
    fn: Callable[[], T],
    policy: Optional[RetryPolicy] = None,
    retryable: Callable[[BaseException], bool] = is_retryable,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn()`` with the policy's backoff schedule.

    Non-retryable errors propagate immediately.  Retryable errors retry
    until success, ``max_attempts`` calls, or the cumulative sleep budget
    runs out — then raise ``RetryBudgetExceeded`` from the last error.
    ``on_retry(exc, attempt, delay_s)`` observes each scheduled retry
    (logging / chaos-harness bookkeeping)."""
    policy = policy or RetryPolicy.from_env()
    rng = rng or random.Random()
    slept = 0.0
    attempts = 0
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.max_attempts)):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            attempts += 1
            if not retryable(e):
                raise
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            delay = backoff_s(attempt, policy, rng)
            hint = retry_after_hint(e)
            if hint is not None:
                delay = max(delay, min(hint, policy.cap_s))
            if slept + delay > policy.budget_s:
                break
            # telemetry: every scheduled retry ticks the counter and
            # tags the trace (no-ops when obs is off); the caller's
            # on_retry still observes afterwards, unchanged
            tm = obs.training_metrics()
            if tm is not None:
                tm.retries.inc()
            obs.instant(
                "retry", cat="io", attempt=attempt,
                delay_ms=round(delay * 1e3, 2), error=type(e).__name__,
            )
            if on_retry is not None:
                on_retry(e, attempt, delay)
            slept += delay
            sleep(delay)
    raise RetryBudgetExceeded(
        f"gave up after {attempts} of {policy.max_attempts} allowed "
        f"attempts ({slept:.2f}s of {policy.budget_s:.0f}s budget slept)"
    ) from last
