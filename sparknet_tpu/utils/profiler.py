"""Per-layer forward/backward profiler — the ``caffe time`` analog.

Reference: ``tools/caffe.cpp:290-376`` warms up, then averages per-layer
forward/backward microseconds over N iterations plus whole-net times.  On
TPU the fused whole-net jit is the honest end-to-end number; the per-layer
numbers here time each layer's computation jitted in isolation against the
real intermediate activations — indicative of relative cost, not additive
to the fused total (XLA fuses across layers; that's the point of the
design).  For deep profiles, ``jax.profiler.trace`` output is the real
tool; ``profile_trace`` wraps it.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import numpy as np

from sparknet_tpu.net import JaxNet
from sparknet_tpu.ops import data_layers


def _time_fn(fn, args, iters: int, warmup: int = 1) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def profile_net(
    net: JaxNet,
    params,
    stats,
    batch,
    iterations: int = 10,
    rng=None,
) -> Dict[str, object]:
    """Returns {layer: {forward_ms, backward_ms}, total_forward_ms,
    total_fwdbwd_ms} like `caffe time`'s table."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    # whole-net numbers (the fused-program truth)
    fwd = jax.jit(lambda p, s, b: net.apply(p, s, b, rng=rng, train=True).loss)
    total_fwd = _time_fn(fwd, (params, stats, batch), iterations)
    grad = jax.jit(jax.grad(lambda p: net.loss_fn(p, stats, batch, rng, True)[0]))
    total_fwdbwd = _time_fn(grad, (params,), iterations)

    # per-layer isolated timings against real activations
    out = net.apply(params, stats, batch, rng=rng, train=True)
    blobs = {k: jax.device_get(v) for k, v in out.blobs.items()}
    per_layer: Dict[str, Dict[str, float]] = {}
    for li, layer in enumerate(net.layers):
        if isinstance(layer, data_layers._HostFed):
            continue
        lblobs = net._gather_blobs(layer.name, params, stats)
        bottoms = [jax.device_put(blobs[b]) for b in layer.lp.bottom]
        cd = net.compute_dtype
        if cd is not None:
            if layer.IS_LOSS:
                bottoms = [b.astype("float32") for b in bottoms]
            else:
                lblobs = [b.astype(cd) for b in lblobs]
                bottoms = [
                    b.astype(cd) if jax.numpy.issubdtype(b.dtype, jax.numpy.floating)
                    else b
                    for b in bottoms
                ]
        lrng = jax.random.fold_in(rng, li)

        def run(lb, bt):
            tops, _ = layer.apply(list(lb), list(bt), lrng, True)
            return tops

        jrun = jax.jit(run)
        f_ms = _time_fn(jrun, (lblobs, bottoms), iterations) * 1e3

        b_ms = 0.0
        if bottoms or lblobs:

            def run_sum(lb, bt):
                tops, _ = layer.apply(list(lb), list(bt), lrng, True)
                return sum(jax.numpy.sum(t) for t in tops) if tops else 0.0

            try:
                jgrad = jax.jit(jax.grad(run_sum, argnums=(0, 1)))
                b_ms = _time_fn(jgrad, (lblobs, bottoms), iterations) * 1e3
            except Exception:
                b_ms = float("nan")  # non-differentiable layer (e.g. Accuracy)
        per_layer[layer.name] = {"forward_ms": f_ms, "backward_ms": b_ms}

    return {
        "layers": per_layer,
        "total_forward_ms": total_fwd * 1e3,
        "total_fwdbwd_ms": total_fwdbwd * 1e3,
    }


def format_profile(result: Dict[str, object]) -> str:
    """`caffe time`-style report."""
    lines = ["%-20s %14s %14s" % ("layer", "forward (ms)", "backward (ms)")]
    for name, t in result["layers"].items():
        lines.append(
            "%-20s %14.3f %14.3f" % (name, t["forward_ms"], t["backward_ms"])
        )
    lines.append(
        "fused whole-net: forward %.3f ms, forward+backward %.3f ms"
        % (result["total_forward_ms"], result["total_fwdbwd_ms"])
    )
    return "\n".join(lines)


def profile_trace(path: str):
    """Context manager writing a jax.profiler trace viewable in
    TensorBoard/Perfetto (the deep-profiling path)."""
    return jax.profiler.trace(path)
