"""Signal-driven stop/snapshot.

Reference: ``caffe/src/caffe/util/signal_handler.cpp:9-60`` + the solver's
per-iteration action poll (``solver.cpp:267-280``) and the CLI flags
``--sigint_effect/--sighup_effect`` (tools/caffe.cpp:43-46).  SIGINT
defaults to STOP, SIGHUP to SNAPSHOT; handlers only set flags — the driver
polls between rounds (never mid-jit).  The serving front-end
(``serve/server.py``) reuses the same poll-a-flag discipline with
``sigterm_effect=STOP`` for graceful drain (SIGTERM is the orchestrator's
shutdown signal; training ignores it by default, preserving the
reference CLI's surface).
"""

from __future__ import annotations

import enum
import signal
from typing import Callable, List, Optional


class SolverAction(enum.Enum):
    NONE = 0
    STOP = 1
    SNAPSHOT = 2


# SIGTERM preemption hooks: the orchestrator's preemption notice
# arrives as SIGTERM, and subscribers (the elastic membership
# controller, runtime/membership.py) want to KNOW without the process
# acting on it — a preempted slice marks itself `leaving` and the job
# trains on.  Hooks fire from any installed SignalHandler's SIGTERM
# path; they must be signal-safe (set a flag, append to a list — no
# locks, no I/O).  A SignalHandler built with ``sigterm_hooks=True``
# installs the SIGTERM handler even when its effect is NONE, purely to
# deliver these callbacks.
_sigterm_hooks: List[Callable[[], None]] = []


def add_sigterm_hook(fn: Callable[[], None]) -> Callable[[], None]:
    """Subscribe ``fn`` to SIGTERM deliveries; returns ``fn`` so the
    caller can hand it back to ``remove_sigterm_hook``."""
    _sigterm_hooks.append(fn)
    return fn


def remove_sigterm_hook(fn: Callable[[], None]) -> None:
    """Unsubscribe (idempotent — a hook already removed is a no-op)."""
    try:
        _sigterm_hooks.remove(fn)
    except ValueError:
        pass


class SignalHandler:
    def __init__(
        self,
        sigint_effect: SolverAction = SolverAction.STOP,
        sighup_effect: SolverAction = SolverAction.SNAPSHOT,
        sigterm_effect: SolverAction = SolverAction.NONE,
        sigterm_hooks: bool = False,
    ):
        self._effects = {}
        self._flags = {SolverAction.STOP: False, SolverAction.SNAPSHOT: False}
        self._prev = {}
        for sig, effect in (
            (signal.SIGINT, sigint_effect),
            (signal.SIGHUP, sighup_effect),
            (signal.SIGTERM, sigterm_effect),
        ):
            want = effect != SolverAction.NONE or (
                sig == signal.SIGTERM and sigterm_hooks
            )
            if want:
                if effect != SolverAction.NONE:
                    self._effects[sig] = effect
                self._prev[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame):
        effect = self._effects.get(signum)
        if effect is not None:
            self._flags[effect] = True
        if signum == signal.SIGTERM:
            # orchestrator shutdown: dump the flight-recorder ring (a
            # no-op unless --flight_recorder armed one).  SIGTERM only:
            # SIGINT/SIGHUP are routine stop/snapshot requests, not
            # postmortem moments.
            from sparknet_tpu.obs import flight as _flight

            _flight.dump_if_active("signal_SIGTERM")
            # preemption-notice subscribers (elastic membership): each
            # hook guarded — a bad subscriber must not break the
            # stop/snapshot contract of the handler itself
            for fn in list(_sigterm_hooks):
                try:
                    fn()
                except Exception:  # noqa: BLE001 — signal context
                    pass

    def get_action(self) -> SolverAction:
        """Poll-and-clear, highest priority first (STOP beats SNAPSHOT)."""
        if self._flags[SolverAction.STOP]:
            self._flags[SolverAction.STOP] = False
            return SolverAction.STOP
        if self._flags[SolverAction.SNAPSHOT]:
            self._flags[SolverAction.SNAPSHOT] = False
            return SolverAction.SNAPSHOT
        return SolverAction.NONE

    def restore(self):
        """Reinstall the previous handlers.  Idempotent — a second call
        (e.g. ``__exit__`` after an explicit ``restore()``) is a no-op,
        so it can never clobber handlers installed after this one."""
        prev, self._prev = self._prev, {}
        for sig, handler in prev.items():
            signal.signal(sig, handler)

    # context-manager form: driver loops can't leak handlers on an
    # exception path (``with SignalHandler(...) as h: ...`` restores the
    # previous handler chain on ANY exit; nested handlers unwind LIFO)
    def __enter__(self) -> "SignalHandler":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.restore()
