"""Two-process jax.distributed harness, shared by the CI test
(``tests/test_multihost.py``) and the driver dryrun
(``__graft_entry__.dryrun_multichip`` mode 4) so the bring-up scaffolding
— port probe, forced-CPU env, spawn/reap/cleanup — and the toy averaging
worker itself have exactly one copy.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

# The canonical 2-process averaging worker: joins via jax.distributed,
# builds one global dp=4 mesh, runs a real ParameterAveragingTrainer
# round, asserts finite per-worker losses and post-averaging parameter
# agreement across this process's local shards, prints "<marker> p<pid>".
_TOY_AVERAGING_WORKER = r"""
import sys
import numpy as np

pid, port = int(sys.argv[1]), sys.argv[2]

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from sparknet_tpu import config
from sparknet_tpu.parallel import ParameterAveragingTrainer
from sparknet_tpu.parallel.mesh import initialize_distributed, make_mesh
from sparknet_tpu.solver import Solver

initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2

# fleet-plane wiring: with SPARKNET_SHIP_TO set (the dryrun's fleet
# leg) each process ships metric deltas + round spans to one collector
import os as _os

_run_obs = None
if _os.environ.get("SPARKNET_SHIP_TO"):
    from sparknet_tpu import obs as _obs

    _run_obs = _obs.start(
        ship_to=_os.environ["SPARKNET_SHIP_TO"],
        host_id=_os.environ.get("SPARKNET_HOST_ID", f"proc{pid}"),
        echo=None,
    )

NET = '''
name: "toy"
layer { name: "data" type: "HostData" top: "x" top: "label"
  java_data_param { shape { dim: 4 dim: 6 } shape { dim: 4 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "logits"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
  bottom: "label" top: "loss" }
'''
sp = config.parse_solver_prototxt(
    'base_lr: 0.05 lr_policy: "fixed" momentum: 0.9'
)
solver = Solver(sp, net_param=config.parse_net_prototxt(NET))
mesh = make_mesh({"dp": 4})
trainer = ParameterAveragingTrainer(solver, mesh)
state = trainer.init_state(seed=0)

rng = np.random.RandomState(0)  # same data on both processes
full = {
    "x": rng.randn(4, 2, 4, 6).astype(np.float32),
    "label": rng.randint(0, 3, (4, 2, 4)).astype(np.float32),
}
sharding = NamedSharding(mesh, P("dp"))
batches = {
    k: jax.make_array_from_callback(
        v.shape, sharding, lambda idx, v=v: v[idx]
    )
    for k, v in full.items()
}
state, losses = trainer.round(state, batches)
local = np.concatenate(
    [np.asarray(s.data) for s in losses.addressable_shards], axis=0
)
assert np.isfinite(local).all(), local
# post-averaging: this process's local shards of every param must agree
for key, blobs in state.params.items():
    for blob in blobs:
        shards = [np.asarray(s.data) for s in blob.addressable_shards]
        np.testing.assert_allclose(shards[0], shards[1], rtol=1e-6)
if _run_obs is not None:
    _run_obs.close()  # final flush ships the run's tail
print(f"@MARKER@ p{pid} smoothed={solver.smoothed_loss:.4f}")
"""


def toy_averaging_worker(marker: str) -> str:
    return _TOY_AVERAGING_WORKER.replace("@MARKER@", marker)


# Timed variant: measures the pmean(θ) collective's wall-clock share of
# an averaging round ACROSS A REAL PROCESS BOUNDARY (jax.distributed over
# loopback TCP) via the same average_params=True/False A/B bench_scaling
# uses on the virtual mesh.  Model sized so θ is ~0.5 MB — big enough
# for the collective to be measurable, small enough for CPU workers.
_TIMED_AVERAGING_WORKER = r"""
import sys
import time
import numpy as np

pid, port = int(sys.argv[1]), sys.argv[2]

import jax

from sparknet_tpu import config
from sparknet_tpu.parallel import ParameterAveragingTrainer
from sparknet_tpu.parallel.mesh import initialize_distributed, make_mesh
from sparknet_tpu.solver import Solver

initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)

# fleet-plane wiring: with SPARKNET_SHIP_TO set (tools/launch.py
# --fleet_collector, or the e2e fleet test) each worker ships its
# metric deltas + round spans to the one collector
import os as _os

_run_obs = None
if _os.environ.get("SPARKNET_SHIP_TO"):
    from sparknet_tpu import obs as _obs

    _run_obs = _obs.start(
        ship_to=_os.environ["SPARKNET_SHIP_TO"],
        host_id=_os.environ.get("SPARKNET_HOST_ID", f"proc{pid}"),
        echo=None,
    )

NET = '''
name: "timed"
layer { name: "data" type: "HostData" top: "x" top: "label"
  java_data_param { shape { dim: 16 dim: 256 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
  inner_product_param { num_output: 256 weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "logits"
  inner_product_param { num_output: 128 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
  bottom: "label" top: "loss" }
'''
sp = config.parse_solver_prototxt(
    'base_lr: 0.01 lr_policy: "fixed" momentum: 0.9'
)
mesh = make_mesh({"dp": 4})
TAU, ROUNDS = 10, 10

rng = np.random.RandomState(0)
from jax.sharding import NamedSharding, PartitionSpec as P
sharding = NamedSharding(mesh, P("dp"))
full = {
    "x": rng.randn(4, TAU, 16, 256).astype(np.float32),
    "label": rng.randint(0, 128, (4, TAU, 16)).astype(np.float32),
}
# the round DONATES its batch argument (the consumed buffers are
# recycled on device), so a placed batch is single-use: re-place per
# round.  The placement cost is identical in both A/B legs, so the
# avg-minus-local subtraction still isolates the collective.
def make_batches():
    return {
        k: jax.make_array_from_callback(
            v.shape, sharding, lambda idx, v=v: v[idx]
        )
        for k, v in full.items()
    }


def timed(average_params):
    solver = Solver(sp, net_param=config.parse_net_prototxt(NET))
    trainer = ParameterAveragingTrainer(
        solver, mesh, average_params=average_params
    )
    state = trainer.init_state(seed=0)
    state, losses = trainer.round(state, make_batches())  # compile + warm
    # sparknet: sync-ok(A/B timing harness: the sync closes the clock, identical in both legs)
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        state, losses = trainer.round(state, make_batches())
    # sparknet: sync-ok(A/B timing harness: the sync closes the clock, identical in both legs)
    jax.block_until_ready(losses)
    return (time.perf_counter() - t0) / ROUNDS


avg = timed(True)
local = timed(False)
coll_ms = max(0.0, (avg - local) * 1e3)
if _run_obs is not None:
    _run_obs.close()  # final flush ships the run's tail
print(
    f"@MARKER@ p{pid} avg_ms={avg * 1e3:.3f} local_ms={local * 1e3:.3f} "
    f"collective_ms={coll_ms:.3f} tau={TAU}"
)
"""


def timed_averaging_worker(marker: str) -> str:
    return _TIMED_AVERAGING_WORKER.replace("@MARKER@", marker)


# Fleet-shipping worker: a real single-device training loop (tiny
# InnerProduct net, per-round ``execute`` spans carrying the absolute
# round) that ships its metric deltas + run-log events to the collector
# named by SPARKNET_SHIP_TO — the per-process half of the fleet e2e
# proof (tests/test_fleet.py) and of ``bench.py --mode=fleet``.  Env
# knobs (all optional) shape the fleet scenario WITHOUT touching the
# harness: SPARKNET_FLEET_ROUNDS / _ROUND_S (clock-paced rounds),
# _STRAGGLE_FROM + _STRAGGLE_S (a slow host: extra per-round sleep from
# an absolute round on), _LINGER_S (keep the shipper heartbeating after
# the loop so a peer's lag verdict can be observed against a live
# fleet), SPARKNET_SHIP_CLOCK_SKEW_S (a skewed host clock the
# collector's alignment must recover).  Needs no cross-process
# collectives, so it runs on any CPU jax build.
_FLEET_SHIP_WORKER = r"""
import os
import sys
import time

import numpy as np

pid = int(sys.argv[1])

from sparknet_tpu import config, obs
from sparknet_tpu.solver import Solver

NET = '''
name: "fleet_toy"
layer { name: "data" type: "HostData" top: "x" top: "label"
  java_data_param { shape { dim: 4 dim: 6 } shape { dim: 4 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "logits"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
  bottom: "label" top: "loss" }
'''

rounds = int(os.environ.get("SPARKNET_FLEET_ROUNDS", "5"))
round_s = float(os.environ.get("SPARKNET_FLEET_ROUND_S", "0.02"))
straggle_from = int(os.environ.get("SPARKNET_FLEET_STRAGGLE_FROM", "-1"))
straggle_s = float(os.environ.get("SPARKNET_FLEET_STRAGGLE_S", "0"))
linger_s = float(os.environ.get("SPARKNET_FLEET_LINGER_S", "0"))

run = obs.start(
    ship_to=os.environ["SPARKNET_SHIP_TO"],
    host_id=os.environ.get("SPARKNET_HOST_ID", f"host{pid}"),
    echo=None,
)
sp = config.parse_solver_prototxt(
    'base_lr: 0.05 lr_policy: "fixed" momentum: 0.9'
)
solver = Solver(sp, net_param=config.parse_net_prototxt(NET))
state = solver.init_state(seed=pid)
rng = np.random.RandomState(pid)


def window():
    return {
        "x": rng.randn(1, 4, 6).astype(np.float32),
        "label": rng.randint(0, 3, (1, 4)).astype(np.float32),
    }


for r in range(rounds):
    with obs.span("execute", round=r):
        state, losses = solver.step(state, window())
    run.shipper.note_round(r)
    time.sleep(round_s + (straggle_s if 0 <= straggle_from <= r else 0.0))
print(f"@MARKER@ p{pid} rounds={rounds} loss={solver.smoothed_loss:.4f}")
sys.stdout.flush()
if linger_s:
    # loop done; keep the shipper heartbeating (a finished-but-alive
    # host) until the harness kills us or the linger expires
    time.sleep(linger_s)
run.close()
"""


def fleet_ship_worker(marker: str) -> str:
    return _FLEET_SHIP_WORKER.replace("@MARKER@", marker)


def run_two_process_round(
    worker_src: str,
    marker: str,
    repo_root: str,
    devices_per_process: int = 2,
    timeout: int = 600,
    env_extra: Optional[Dict[str, str]] = None,
) -> List[str]:
    """Spawn two workers running ``worker_src`` (argv: pid, port) on
    forced-CPU virtual devices; assert both exit 0 and print
    ``<marker> p<pid>``; return the outputs.  ``env_extra`` merges into
    each worker's environment (e.g. ``SPARKNET_SHIP_TO`` pointing both
    at one fleet collector).

    Each worker is reaped on its own thread (so a fast-failing peer's
    output surfaces immediately and pipes never fill); on timeout the
    survivors are killed and the error carries every output collected.
    """
    with tempfile.TemporaryDirectory(prefix="mp_round_") as d:
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write(worker_src)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = {
            **os.environ,
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get(
                "PYTHONPATH", ""
            ),
            "PALLAS_AXON_POOL_IPS": "",  # never route workers via a tunnel
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (
                f"--xla_force_host_platform_device_count="
                f"{devices_per_process}"
            ),
            **(env_extra or {}),
        }
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(pid), str(port)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            for pid in range(2)
        ]
        results: Dict[int, Tuple[int, str]] = {}

        def reap(pid: int, p: subprocess.Popen) -> None:
            out, _ = p.communicate()
            results[pid] = (p.returncode, out)

        threads = [
            threading.Thread(
                target=reap, args=(pid, p), name=f"procs-reap-p{pid}",
                daemon=True,
            )
            for pid, p in enumerate(procs)
        ]
        for t in threads:
            t.start()
        deadline = time.time() + timeout
        try:
            while time.time() < deadline:
                if all(not t.is_alive() for t in threads):
                    break
                if any(rc != 0 for rc, _ in results.values()):
                    # a worker already failed: don't wait out the peer
                    # stuck on the coordinator — kill it and report
                    break
                time.sleep(0.2)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for t in threads:
                t.join(timeout=30)
        if len(results) < 2:
            raise TimeoutError(
                f"worker(s) did not finish within {timeout}s; collected: "
                + "".join(
                    f"\n-- worker {pid} rc={rc}:\n{out}"
                    for pid, (rc, out) in sorted(results.items())
                )
            )
        if any(rc != 0 for rc, _ in results.values()):
            # show every worker's output — the killed survivor's rc=-9 is
            # noise next to the real traceback
            raise AssertionError(
                "worker failure:" + "".join(
                    f"\n-- worker {pid} rc={rc}:\n{out}"
                    for pid, (rc, out) in sorted(results.items())
                )
            )
        for pid in range(2):
            assert f"{marker} p{pid}" in results[pid][1], results[pid][1]
        return [results[pid][1] for pid in range(2)]
