"""Analytic model-FLOP counting for MFU reporting.

XLA's ``compiled.cost_analysis()`` under-reports on some backends, so the
benchmark cross-checks it against this shape walk.  Convention matches the
standard MFU accounting: count the MXU work (convolutions and matmuls; a
multiply-accumulate is 2 FLOPs), ignore elementwise/normalization tails,
and charge the backward pass at 2x forward (grad-wrt-input + grad-wrt-
weights each cost one forward).  Reference cost ground truth: AlexNet
forward is ~1.4 GFLOPs/image at batch-size-independent shapes
(``caffe/models/bvlc_alexnet``), so train ~4.3 GFLOPs/image.
"""

from __future__ import annotations

from sparknet_tpu.net import JaxNet


def _conv_flops(net: JaxNet, layer) -> float:
    lp = layer.lp
    cp = lp.convolution_param
    (n, c, _, _) = net.blob_shapes[lp.bottom[0]]
    out = net.blob_shapes[lp.top[0]]
    if lp.type == "Deconvolution":
        # the GEMM runs over the *input* spatial extent
        _, k, _, _ = out
        _, _, oh, ow = net.blob_shapes[lp.bottom[0]]
    else:
        _, k, oh, ow = out
    g = max(1, cp.group)
    (kh, kw), _, _, _ = layer._geometry(net.blob_shapes[lp.bottom[0]])
    macs = n * oh * ow * k * (c // g) * kh * kw
    if cp.bias_term:
        macs += n * k * oh * ow
    return 2.0 * macs


def _ip_flops(net: JaxNet, layer) -> float:
    lp = layer.lp
    bshape = net.blob_shapes[lp.bottom[0]]
    p = lp.inner_product_param
    axis = p.axis if p.axis >= 0 else len(bshape) + p.axis
    n = 1
    for d in bshape[:axis]:
        n *= d
    fan_in = 1
    for d in bshape[axis:]:
        fan_in *= d
    macs = n * fan_in * p.num_output
    if p.bias_term:
        macs += n * p.num_output
    return 2.0 * macs


def forward_flops(net: JaxNet) -> float:
    """MXU FLOPs for one forward pass at the net's static shapes."""
    total = 0.0
    for layer in net.layers:
        t = layer.lp.type
        if t in ("Convolution", "Deconvolution"):
            total += _conv_flops(net, layer)
        elif t == "InnerProduct":
            total += _ip_flops(net, layer)
        elif t == "Embed":
            # gather, not matmul — negligible
            continue
    return total


def train_flops(net: JaxNet) -> float:
    """Forward + backward (2x forward) per training iteration."""
    return 3.0 * forward_flops(net)
