"""Step-time PRNG policy.

The reference engine draws dropout/transform randomness from a per-thread
Mersenne generator (``caffe/src/caffe/common.cpp`` RNG) — cheap on CPU.
JAX's default threefry2x32 is counter-based and reproducible but costs
real VPU time per mask on TPU; the hardware RBG generator is the
TPU-native equivalent of "a fast local generator" with the same
functional-key API.  Training-step keys (dropout masks, crop/mirror
draws, stochastic pooling) use RBG on TPU; *initialization* keys stay
threefry everywhere so filler golden tests are backend-independent.

``SPARKNET_PRNG=threefry2x32|rbg`` overrides.
"""

from __future__ import annotations

import os

import jax


def train_key(seed: int = 0) -> jax.Array:
    """A typed PRNG key for training-step randomness (see module doc)."""
    impl = os.environ.get("SPARKNET_PRNG")
    if impl is None:
        impl = "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
    return jax.random.key(seed, impl=impl)
