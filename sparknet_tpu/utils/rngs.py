"""Step-time PRNG policy.

The reference engine draws dropout/transform randomness from a per-thread
Mersenne generator (``caffe/src/caffe/common.cpp`` RNG) — cheap on CPU.
JAX's default threefry2x32 is counter-based and reproducible but costs
real VPU time per mask on TPU; the hardware RBG generator is the
TPU-native equivalent of "a fast local generator" with the same
functional-key API.  Training-step keys (dropout masks, crop/mirror
draws, stochastic pooling) use RBG on TPU; *initialization* keys stay
threefry everywhere so filler golden tests are backend-independent.

``SPARKNET_PRNG=threefry2x32|rbg`` overrides.
"""

from __future__ import annotations

import functools
import os

import jax


def _default_impl() -> str:
    impl = os.environ.get("SPARKNET_PRNG")
    if impl is None:
        impl = "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
    return impl


def train_key(seed: int = 0) -> jax.Array:
    """A typed PRNG key for training-step randomness (see module doc)."""
    return jax.random.key(seed, impl=_default_impl())


@functools.lru_cache(maxsize=16)
def _cached_train_key(seed: int, impl: str) -> jax.Array:
    return jax.random.key(seed, impl=impl)


def default_train_key(seed: int = 0) -> jax.Array:
    """``train_key`` for the hot-loop *default-rng* paths
    (``trainer.round(..., rng=None)`` every round): the key is cached
    per (seed, impl), so the per-round scalar host->device transfer a
    fresh ``jax.random.key`` pays disappears — ``bench.py
    --mode=sanitize`` runs the round loop under
    ``jax.transfer_guard("disallow")`` and a fresh key per round is
    exactly the class of silent implicit transfer it exists to catch.
    (Keys are never consumed in place — reusing the cached array is
    semantically identical to rebuilding it.)"""
    return _cached_train_key(int(seed), _default_impl())
