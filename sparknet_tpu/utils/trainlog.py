"""Timestamped phase log — the driver's experiment record.

Reference: ``CifarApp.scala:36-46`` writes elapsed-seconds structured lines
per phase per iteration to ``training_log_<timestamp>.txt``; that file is
the primary experiment record (SURVEY §5).  Format preserved.
"""

from __future__ import annotations

import os
import time
from typing import Optional, TextIO


class TrainingLog:
    def __init__(self, directory: str = ".", tag: str = "", echo: bool = True):
        os.makedirs(directory, exist_ok=True)
        ts = int(time.time() * 1000)
        suffix = f"_{tag}" if tag else ""
        self.path = os.path.join(directory, f"training_log_{ts}{suffix}.txt")
        self._f: TextIO = open(self.path, "a")
        self._t0 = time.time()
        self._echo = echo

    def log(self, message: str, i: int = -1):
        """Reference line formats (ImageNetApp.scala:47-53): with a round
        index, ``<elapsed>, i = <i>: <message>``; else ``<elapsed>: <msg>``."""
        elapsed = time.time() - self._t0
        if i >= 0:
            line = f"{elapsed:.3f}, i = {i}: {message}"
        else:
            line = f"{elapsed:.3f}: {message}"
        self._f.write(line + "\n")
        self._f.flush()
        if self._echo:
            print(line)

    def close(self):
        self._f.close()
