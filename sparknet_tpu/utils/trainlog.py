"""Timestamped phase log — the driver's experiment record.

Reference: ``CifarApp.scala:36-46`` writes elapsed-seconds structured lines
per phase per iteration to ``training_log_<timestamp>.txt``; that file is
the primary experiment record (SURVEY §5).  Format preserved.

Lifecycle: ``TrainingLog`` is a context manager with an idempotent
``close()`` (no leaked file handles; every line is flushed as written,
so a crash loses nothing).  Destination, most specific wins: an
explicit ``path``, else ``directory``, else ``$SPARKNET_LOG_DIR``, else
the CWD — tests and apps point logs at tmpdirs instead of littering the
repo root.

When round-span tracing is on (``obs/trace.py``), every line is
mirrored as a structured instant event into the JSONL run log, which
``tools/parse_log.py`` parses with the same recognizers as the flat
format.
"""

from __future__ import annotations

import os
import time
from typing import Optional, TextIO

from sparknet_tpu import obs


class TrainingLog:
    def __init__(
        self,
        directory: Optional[str] = None,
        tag: str = "",
        echo: bool = True,
        path: Optional[str] = None,
    ):
        if path is None:
            directory = directory or os.environ.get(
                "SPARKNET_LOG_DIR", "."
            )
            os.makedirs(directory, exist_ok=True)
            ts = int(time.time() * 1000)
            suffix = f"_{tag}" if tag else ""
            path = os.path.join(
                directory, f"training_log_{ts}{suffix}.txt"
            )
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self.path = path
        self._f: Optional[TextIO] = open(self.path, "a")
        self._t0 = time.time()
        self._echo = echo

    def log(self, message: str, i: int = -1):
        """Reference line formats (ImageNetApp.scala:47-53): with a round
        index, ``<elapsed>, i = <i>: <message>``; else ``<elapsed>: <msg>``."""
        elapsed = time.time() - self._t0
        if i >= 0:
            line = f"{elapsed:.3f}, i = {i}: {message}"
        else:
            line = f"{elapsed:.3f}: {message}"
        if self._f is None:
            raise ValueError(f"TrainingLog {self.path} is closed")
        self._f.write(line + "\n")
        self._f.flush()  # crash-durable per line
        # structured mirror: rides the JSONL run log when tracing is on
        obs.instant("log", cat="log", msg=message, i=i,
                    elapsed=round(elapsed, 3))
        if self._echo:
            print(line)

    def close(self):
        """Idempotent: safe to call from both a ``with`` exit and an
        explicit app ``finally``."""
        if self._f is not None:
            self._f.close()
            self._f = None

    @property
    def closed(self) -> bool:
        return self._f is None

    def __enter__(self) -> "TrainingLog":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
