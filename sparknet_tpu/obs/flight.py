"""Crash flight recorder: a bounded ring of recent telemetry, dumped as
one postmortem JSON bundle when a run dies.

The run log answers "what happened over the whole run"; the flight
recorder answers "what were the last N things that happened before it
went wrong" — cheaply enough to leave on for every run.  While
installed it receives:

- every span/instant the trace layer emits (``obs.span``/``obs.instant``
  feed the ring even when no ``Tracer`` is installed — the ring is
  independent of ``--trace_out``), which includes ``TrainingLog`` lines
  (mirrored as ``log`` instants) and chaos fault tags,
- every ``HealthSentry`` verdict and its key metric samples
  (loss / grad norm per round).

``dump(reason)`` writes the bundle atomically; it fires on:

- **crash** — an uncaught exception (chained ``sys.excepthook``),
- **SIGTERM** — chained signal handler (and any signal a
  ``utils.signals.SignalHandler`` fields),
- **PrefetchStall** — the feed watchdog (``data/prefetch.py``),
- **sentry halt / rollback** (``obs/health.py``),
- **chaos faults** (``obs.fault``).

Repeated dumps overwrite the same path (newest wins; ``dump_index``
records how many fired).  ``tools/health_report.py`` folds a bundle
into the round-by-round health table.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_BUNDLE_PATH = "flight_postmortem.json"

_active: Optional["FlightRecorder"] = None


class FlightRecorder:
    """Bounded in-memory rings + the atomic postmortem dump."""

    def __init__(
        self,
        path: str = DEFAULT_BUNDLE_PATH,
        capacity: int = 4096,
    ):
        self.path = path
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        self._verdicts: deque = deque(maxlen=512)
        self._samples: deque = deque(maxlen=1024)
        self._dumps = 0
        self._t0 = time.time()
        self._prev_excepthook = None
        self._prev_sigterm = None

    # ------------------------------------------------------------------
    def record_event(self, rec: Dict) -> None:
        """A span/instant record (the trace layer's JSONL shape)."""
        with self._lock:
            self._events.append(rec)

    def record_verdict(self, verdict: Dict) -> None:
        """Record (or refresh) a round's health verdict.  The sentry
        records once at observe time and again after the policy acted
        (the ``action`` field changes) — same-round re-records REPLACE
        the earlier snapshot so the bundle shows what was actually
        done, without duplicate rows."""
        with self._lock:
            if (
                self._verdicts
                and self._verdicts[-1].get("round") == verdict.get("round")
            ):
                self._verdicts[-1] = verdict
            else:
                self._verdicts.append(verdict)

    def record_sample(self, name: str, value, **labels) -> None:
        rec = {"name": name, "value": value, "t_s": round(
            time.time() - self._t0, 3)}
        rec.update(labels)
        with self._lock:
            self._samples.append(rec)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {
                "events": len(self._events),
                "verdicts": len(self._verdicts),
                "samples": len(self._samples),
            }

    # ------------------------------------------------------------------
    def dump(self, reason: str, extra: Optional[Dict] = None) -> str:
        """Write the postmortem bundle (atomic: tmp + rename).  Never
        raises — a failing dump must not mask the crash it documents."""
        from sparknet_tpu import obs as _obs

        with self._lock:
            self._dumps += 1
            bundle = {
                "kind": "sparknet_flight_bundle",
                "version": 1,
                "reason": reason,
                "wall_time_unix_s": time.time(),
                "uptime_s": round(time.time() - self._t0, 3),
                "pid": os.getpid(),
                "dump_index": self._dumps,
                "events": list(self._events),
                "verdicts": list(self._verdicts),
                "samples": list(self._samples),
            }
        if extra:
            bundle["extra"] = extra
        try:
            bundle["sentry"] = _obs.sentry_state()
            tm = _obs.training_metrics()
            bundle["metrics_text"] = (
                tm.registry.render() if tm is not None else None
            )
        except Exception:  # noqa: BLE001 — postmortem must not die
            pass
        try:
            tmp = f"{self.path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                # default=str: a ring entry holding a non-JSON value (a
                # stray numpy/jax scalar in span args) degrades to its
                # repr instead of losing the whole postmortem
                json.dump(bundle, f, default=str)
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 — dump runs inside the crash
            # excepthook / SIGTERM handler; it must never mask the
            # failure it documents
            return self.path
        return self.path

    # ------------------------------------------------------------------
    # crash + SIGTERM chaining (installed by install())
    def _excepthook(self, etype, exc, tb):
        self.dump(
            f"crash:{etype.__name__}", extra={"exception": repr(exc)[:500]}
        )
        hook = self._prev_excepthook or sys.__excepthook__
        hook(etype, exc, tb)

    def _sigterm(self, signum, frame):
        self.dump("signal_SIGTERM")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == _signal.SIG_DFL:
            # preserve default terminate semantics after the dump
            _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
            os.kill(os.getpid(), _signal.SIGTERM)


def install(recorder: FlightRecorder) -> FlightRecorder:
    """Make ``recorder`` the process's active flight recorder: trace
    events feed its ring, and crash/SIGTERM dumps are chained.  One
    recorder at a time (a second install replaces the first)."""
    global _active
    if _active is not None:
        uninstall(_active)
    _active = recorder
    from sparknet_tpu.obs import trace as _trace

    _trace.set_flight(recorder)
    recorder._prev_excepthook = sys.excepthook
    sys.excepthook = recorder._excepthook
    try:  # signals only bind on the main thread
        recorder._prev_sigterm = _signal.getsignal(_signal.SIGTERM)
        _signal.signal(_signal.SIGTERM, recorder._sigterm)
    except ValueError:
        recorder._prev_sigterm = None
    return recorder


def uninstall(recorder: Optional[FlightRecorder] = None) -> None:
    """Detach the active recorder (its dumped bundles stay on disk)."""
    global _active
    rec = recorder if recorder is not None else _active
    if rec is None or rec is not _active:
        return
    _active = None
    from sparknet_tpu.obs import trace as _trace

    _trace.set_flight(None)
    if sys.excepthook == rec._excepthook:
        sys.excepthook = rec._prev_excepthook or sys.__excepthook__
    try:
        if _signal.getsignal(_signal.SIGTERM) == rec._sigterm:
            _signal.signal(
                _signal.SIGTERM, rec._prev_sigterm or _signal.SIG_DFL
            )
    except ValueError:
        pass


def active() -> Optional[FlightRecorder]:
    return _active


def record_verdict(verdict: Dict) -> None:
    rec = _active
    if rec is not None:
        rec.record_verdict(verdict)


def record_sample(name: str, value, **labels) -> None:
    rec = _active
    if rec is not None:
        rec.record_sample(name, value, **labels)


def dump_if_active(reason: str, extra: Optional[Dict] = None) -> Optional[str]:
    """Dump the bundle if a recorder is installed (the hook every
    trigger site calls — a no-op, not an error, when flight recording
    is off)."""
    rec = _active
    if rec is None:
        return None
    return rec.dump(reason, extra=extra)


def load_bundle(path: str) -> Dict:
    """Read + sanity-check a dumped bundle (tools/health_report.py)."""
    with open(path) as f:
        bundle = json.load(f)
    if bundle.get("kind") != "sparknet_flight_bundle":
        raise ValueError(f"{path}: not a sparknet flight bundle")
    return bundle
