"""Unified telemetry layer — tracing, metrics, and the /metrics sidecar.

One observability surface shared by training, the data plane, and
serving (ARCHITECTURE.md "Observability"):

- ``obs.metrics``  — Counter/Gauge/Histogram (+ label families) and the
  Prometheus-text ``MetricsRegistry``; ``serve.metrics`` re-exports it.
- ``obs.trace``    — low-overhead ``span()``/``instant()`` emitting
  Chrome trace-event JSON (Perfetto-loadable, thread-correct) plus a
  structured JSONL run log.
- ``obs.exporter`` — the opt-in ``/metrics`` + ``/healthz`` HTTP
  sidecar every ``cli train``/app run gets via ``--obs`` (also exports
  the divergence sentry's state: ``last_anomaly_round``, policy, 503
  while halted).
- ``obs.health``   — the training-health sentry: in-graph numerics
  audit (grad norm, update/param ratios, non-finite counts, fused into
  the jitted step), in-graph poisoned-worker masking, and the
  warn/halt/rollback divergence policy (``--health``).
- ``obs.flight``   — the crash flight recorder: a bounded ring of
  recent spans/verdicts/samples dumped as one postmortem JSON bundle
  on crash/SIGTERM/stall/halt/chaos fault (``--flight_recorder``;
  folded by ``tools/health_report.py``).
- ``obs.profile``  — the round-anatomy profiler (``--profile``): live
  per-phase breakdown, measured H2D/collective hidden fractions,
  per-worker skew + straggler verdicts, MFU/roofline gauges; the live
  counterpart of the offline PIPELINE/OBS artifacts, gated by
  ``tools/perf_gate.py``.

Instrumented code calls the module-level hooks (``obs.span``,
``obs.instant``, ``obs.training_metrics()``, ``obs.fault``), which are
near-free no-ops until ``obs.start(...)`` — wired to ``--obs`` /
``--trace_out`` / ``--flight_recorder`` flags by
``add_cli_args``/``start_from_args`` — turns them on.  (``obs.health``
is imported on demand — it pulls jax; the rest of the package stays
import-light for CLI startup.)
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from collections import deque
from typing import Optional

from sparknet_tpu.obs import flight  # noqa: F401
from sparknet_tpu.obs import profile as profile  # noqa: F401
from sparknet_tpu.obs.exporter import JsonHTTPHandler, ObsExporter  # noqa: F401
from sparknet_tpu.obs.fleet import (  # noqa: F401
    DEFAULT_FLEET_PORT,
    FleetCollector,
)
from sparknet_tpu.obs.flight import FlightRecorder  # noqa: F401
from sparknet_tpu.obs.profile import RoundProfiler  # noqa: F401
from sparknet_tpu.obs.ship import Shipper  # noqa: F401
from sparknet_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricFamily,
    MetricsRegistry,
)
from sparknet_tpu.obs.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    install_tracer,
    instant,
    jsonl_path_for,
    set_phase_observer,
    set_ship,
    span,
    uninstall_tracer,
)

DEFAULT_OBS_PORT = 8380

# jitted callables whose _cache_size() feeds the jit-cache gauge; weak
# references, bounded — trainers register on construction and a bench
# that builds dozens must not pin them all in memory
_tracked_jits: "deque" = deque(maxlen=8)


def track_jit(jitted) -> None:
    """Register a jitted callable for the ``sparknet_jit_cache_size``
    gauge (sum of ``_cache_size()`` over the most recent registrants)."""
    try:
        _tracked_jits.append(weakref.ref(jitted))
    except TypeError:  # not weakref-able: skip rather than leak
        pass


def _jit_cache_size() -> int:
    total = 0
    for ref in list(_tracked_jits):
        fn = ref()
        if fn is None:
            continue
        try:
            total += int(fn._cache_size())
        except Exception:
            pass
    return total


def _device_bytes() -> float:
    """Bytes held by live jax arrays on this process's devices; guarded
    — any backend that can't report (or a mid-teardown runtime) reads 0
    rather than poisoning a scrape."""
    try:
        import jax

        return float(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return 0.0


def _host_rss_bytes() -> float:
    try:
        import resource

        return float(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )
    except Exception:
        return 0.0


class TrainingMetrics:
    """The training-side series, registered once per process on the
    shared registry (the serving stack registers its own ``serve_*``
    series on its registry the same way)."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        t0 = time.monotonic()
        self.uptime = registry.gauge(
            "sparknet_uptime_seconds", "seconds since telemetry start",
            fn=lambda: time.monotonic() - t0,
        )
        self.rounds = registry.counter(
            "sparknet_rounds_total",
            "training rounds completed (rate() gives rounds/s)",
        )
        self.iters = registry.counter(
            "sparknet_iters_total", "solver iterations completed"
        )
        self.phase_latency = registry.histogram(
            "sparknet_phase_latency_seconds",
            "wall seconds per round phase (assemble/h2d/execute/average/"
            "quantize/allreduce/dequantize/snapshot/restore/verify — "
            "the canonical phase set in analysis/registry.py)",
            labels=("phase",),
        )
        self.feed_queue_depth = registry.gauge(
            "sparknet_feed_queue_depth",
            "device batches ready in the round-feed prefetch queue",
        )
        self.feed_stalls = registry.counter(
            "sparknet_feed_stalls_total",
            "PrefetchStall watchdog fires (producer silent past timeout)",
        )
        self.retries = registry.counter(
            "sparknet_io_retries_total",
            "retry_call attempts that failed and were rescheduled",
        )
        self.snapshots = registry.counter(
            "sparknet_snapshots_total", "checkpoints written"
        )
        self.restores = registry.counter(
            "sparknet_restores_total", "checkpoints restored"
        )
        self.quarantined = registry.counter(
            "sparknet_snapshots_quarantined_total",
            "corrupt snapshots renamed *.corrupt by restore_newest_valid",
        )
        self.faults = registry.counter(
            "sparknet_faults_total",
            "chaos-injected faults observed, by kind",
            labels=("kind",),
        )
        # chunk-cache series (data/chunk_cache.py, --cache_dir) — zero
        # until a run fronts its object store with a ChunkCache
        self.cache_hits = registry.counter(
            "sparknet_cache_hits_total",
            "chunk-cache reads served from verified local entries",
        )
        self.cache_misses = registry.counter(
            "sparknet_cache_misses_total",
            "chunk-cache reads that fetched from the backing object "
            "store (cold, evicted, stale-etag, or quarantined entries)",
        )
        self.cache_evictions = registry.counter(
            "sparknet_cache_evictions_total",
            "chunk-cache entries LRU-evicted at the byte budget",
        )
        self.cache_bytes = registry.counter(
            "sparknet_cache_bytes_total",
            "bytes served through the chunk cache, by source "
            "(hit = local disk, miss = network fetch); an I/O-flat "
            "multi-epoch run's miss series goes flat after epoch 1",
            labels=("src",),
        )
        self.collective_bytes = registry.counter(
            "sparknet_collective_bytes_total",
            "modeled interconnect payload bytes moved by the parameter-"
            "averaging collective (ring factor x compressed payload), "
            "by compression mode",
            labels=("compress",),
        )
        self.quant_error = registry.gauge(
            "sparknet_quant_error_max_abs",
            "last round's max |delta - dequant(delta)| quantization "
            "error of the compressed averaging collective, by "
            "compression mode (parallel/comm.py delta quantization)",
            labels=("compress",),
        )
        self.quant_snr_db = registry.gauge(
            "sparknet_quant_snr_db",
            "last round's delta-vs-quantization-error SNR in dB "
            "(10*log10(|delta|^2/|err|^2); capped at 300 when the "
            "error underflows to 0), by compression mode",
            labels=("compress",),
        )
        self.kernel_path = registry.gauge(
            "sparknet_kernel_path",
            "1 when the named hot path rides its fused Pallas kernel, "
            "0 on the dense/XLA fallback (the ops/pallas_attention."
            "lowerable() routing gate; kernel=attention|epilogue)",
            labels=("kernel",),
        )
        self.kernel_fused_chunks = registry.counter(
            "sparknet_kernel_fused_chunks_total",
            "fused averaging-epilogue kernel launches by the comm "
            "plane (one per comm chunk per stage per round; "
            "stage=encode|apply — ops/pallas_comm.py)",
            labels=("stage",),
        )
        # round-anatomy profiler series (obs/profile.py, --profile) —
        # zero until a RoundProfiler is installed
        self.hidden_fraction = registry.gauge(
            "sparknet_hidden_fraction",
            "measured fraction of overlap-capable work hidden under "
            "consumer execute last round: kind=h2d (RoundFeed producer "
            "assemble+H2D) or kind=comm (CommPlane chunked allreduce)",
            labels=("kind",),
        )
        self.worker_skew = registry.gauge(
            "sparknet_worker_skew",
            "last round's per-worker attributed-time max/median ratio "
            "(1.0 = homogeneous workers)",
        )
        self.straggler_worker = registry.gauge(
            "sparknet_straggler_worker",
            "dp index of the worker the profiler called a straggler "
            "last round (-1 = none)",
        )
        self.straggler_rounds = registry.counter(
            "sparknet_straggler_rounds_total",
            "rounds whose straggler verdict fired (skew past threshold)",
        )
        self.achieved_flops = registry.gauge(
            "sparknet_achieved_flops",
            "modeled achieved FLOP/s last round (analytic utils/flops.py "
            "MXU count / measured round wall)",
        )
        self.mfu = registry.gauge(
            "sparknet_mfu",
            "model FLOP utilization vs the chip's bf16 peak (0 when the "
            "peak is unknown, e.g. CPU)",
        )
        self.jit_cache = registry.gauge(
            "sparknet_jit_cache_size",
            "compiled programs behind tracked jitted fns (constant "
            "after warmup iff no recompiles)",
            fn=_jit_cache_size,
        )
        self.device_bytes = registry.gauge(
            "sparknet_device_bytes",
            "bytes held by live jax arrays (jax.live_arrays accounting)",
            fn=_device_bytes,
        )
        self.host_rss = registry.gauge(
            "sparknet_host_rss_bytes", "peak resident set size",
            fn=_host_rss_bytes,
        )
        # training-health series (obs/health.py numerics audit) — zero
        # until a run enables the audit (--health)
        self.grad_norm = registry.gauge(
            "sparknet_grad_norm",
            "global L2 norm of the last audited iteration's raw "
            "gradients (pre-clip)",
        )
        self.nonfinite = registry.counter(
            "sparknet_nonfinite_total",
            "non-finite values seen by the numerics audit "
            "(grads + params + loss)",
        )
        self.update_ratio = registry.gauge(
            "sparknet_update_ratio",
            "per-param-group update/param L2 ratio of the last audited "
            "iteration",
            labels=("group",),
        )
        self.health_anomalies = registry.counter(
            "sparknet_health_anomalies_total",
            "divergence-sentry anomaly verdicts, by kind",
            labels=("kind",),
        )
        self.health_rollbacks = registry.counter(
            "sparknet_health_rollbacks_total",
            "sentry-triggered rollbacks to a verified snapshot",
        )
        # elastic-membership series (runtime/membership.py, --elastic)
        # — zero until a run arms the membership controller
        self.membership_epoch = registry.gauge(
            "sparknet_membership_epoch",
            "current membership view epoch (bumps once per roster "
            "change applied at a round boundary)",
        )
        self.membership_workers = registry.gauge(
            "sparknet_membership_workers",
            "dp workers per membership state (live carry mask weight; "
            "leaving/dead/joining are excluded from the average)",
            labels=("state",),
        )
        self.membership_transitions = registry.counter(
            "sparknet_membership_transitions_total",
            "membership state transitions applied at round boundaries, "
            "by kind (leave/late/death/join_request/rejoin)",
            labels=("kind",),
        )
        # two-tier hierarchical averaging series (parallel/hierarchy.py,
        # --slices/--cross_slice_every) — zero on flat (single-tier)
        # runs
        self.hierarchy_rounds = registry.counter(
            "sparknet_hierarchy_rounds_total",
            "averaging rounds by tier: intra = within-slice (ICI) "
            "average only, cross = the every-K-rounds global (DCN) "
            "average",
            labels=("tier",),
        )
        self.hierarchy_bytes = registry.counter(
            "sparknet_hierarchy_bytes_total",
            "modeled collective payload bytes by tier (ring factor x "
            "payload; the cross series is what the two-tier schedule "
            "divides by K vs an every-round flat run)",
            labels=("tier",),
        )
        # fleet-shipper series (obs/ship.py, --ship_to) — zero until a
        # run ships to a fleet collector
        self.ship_events = registry.counter(
            "sparknet_ship_events_total",
            "run-log events enqueued for shipping to the fleet "
            "collector (includes later-dropped ones)",
        )
        self.ship_dropped = registry.counter(
            "sparknet_ship_dropped_total",
            "buffered events dropped (oldest first) at the shipper's "
            "bound while the collector was unreachable",
        )
        self.ship_pushes = registry.counter(
            "sparknet_ship_pushes_total",
            "successful pushes to the fleet collector",
        )
        self.ship_push_failures = registry.counter(
            "sparknet_ship_push_failures_total",
            "pushes that exhausted their retry budget (collector "
            "unreachable; events stayed buffered)",
        )
        # run-journal / crash-recovery series (io/journal.py +
        # journaled resume paths) — zero until a run arms --journal
        self.journal_records = registry.counter(
            "sparknet_journal_records_total",
            "run-journal records appended, by kind (intent = round "
            "write-ahead, commit = durable round boundary)",
            labels=("kind",),
        )
        self.journal_truncated = registry.counter(
            "sparknet_journal_truncated_total",
            "torn journal tails truncated on open (a kill landed "
            "mid-append; the partial frame failed its CRC)",
        )
        self.recover_replayed = registry.counter(
            "sparknet_recover_replayed_rounds_total",
            "rounds re-executed after a journal-guided resume (the "
            "in-flight round whose commit never landed; at most one "
            "per recovery when every boundary snapshots)",
        )
        # transformer-LM workload series (apps/lm_app.py, --sp) — zero
        # for the CNN apps
        self.lm_tokens = registry.counter(
            "sparknet_lm_tokens_total",
            "tokens trained by the LM workload (dp workers x tau x "
            "batch x seq_len per round)",
        )
        self.lm_ring_bytes = registry.counter(
            "sparknet_lm_ring_hop_bytes_total",
            "modeled ring-attention KV exchange bytes (sequence "
            "parallelism: K+V shards x (sp-1) hops x layers, "
            "forward + transposed backward; zero when sp=1)",
        )
        # bounded-staleness averaging series (parallel/stale.py,
        # --stale_bound) — zero on the synchronous round
        self.staleness = registry.gauge(
            "sparknet_staleness",
            "per-worker staleness at the last averaging boundary "
            "(boundary index minus the worker's own round; 0 on the "
            "synchronous path, bounded by --stale_bound otherwise)",
            labels=("worker",),
        )
        self.stale_arrivals = registry.counter(
            "sparknet_stale_arrivals_total",
            "boundary fold-ins per worker (the arrival mask: the "
            "worker's finished tau-window entered this boundary's "
            "staleness-weighted mean)",
            labels=("worker",),
        )
        self.stale_skipped = registry.counter(
            "sparknet_stale_skipped_total",
            "boundaries a worker sat out (window still in flight; its "
            "contribution folds in at a later boundary instead of "
            "stalling this one)",
            labels=("worker",),
        )
        self.stale_forced_waits = registry.counter(
            "sparknet_stale_forced_waits_total",
            "arrivals forced by the staleness bound (a live worker hit "
            "lag B and the boundary blocked for it — the bounded "
            "synchronous cost; ~0 is the stale bench's win condition)",
        )
        self.stale_boundaries_skipped = registry.counter(
            "sparknet_stale_boundaries_skipped_total",
            "averaging boundaries skipped outright because no worker "
            "had arrived (state untouched, no collective dispatched)",
        )


_lock = threading.Lock()
_training: Optional[TrainingMetrics] = None
_unhealthy_reason: Optional[str] = None
# the active divergence sentry (obs/health.py) — /healthz exports its
# state so an orchestrator can tell "stalled" from "diverged"
_sentry = None
# the active elastic membership controller (runtime/membership.py) —
# /healthz exports its view so an orchestrator can tell "slice 1 is
# leaving" from "the job is wedged"
_membership = None
# the active burn-rate SLO evaluator (obs/slo.py, --slo) — /healthz
# exports objective statuses + recent alert transitions
_slo_evaluator = None


def enable_training_metrics() -> TrainingMetrics:
    """Create (idempotently) the process-wide training registry +
    series, and wire phase-cat spans into the per-phase histogram."""
    global _training
    with _lock:
        if _training is None:
            _training = TrainingMetrics(MetricsRegistry())
            fam = _training.phase_latency
            set_phase_observer(
                lambda name, dur_s: fam.labels(name).observe(dur_s)
            )
    return _training


def training_metrics() -> Optional[TrainingMetrics]:
    """The enabled training metrics, or None — instrumented code guards
    with one read: ``tm = obs.training_metrics();  if tm: ...``."""
    return _training


def _reset_training_metrics_for_tests() -> None:
    """Drop the process singleton so a test gets fresh counters; NOT
    for production code (instrumented sites cache nothing, so the swap
    is safe mid-process)."""
    global _training, _unhealthy_reason, _sentry, _membership
    global _slo_evaluator
    with _lock:
        _training = None
        _unhealthy_reason = None
        _sentry = None
        _membership = None
        _slo_evaluator = None
        set_phase_observer(None)
        set_ship(None)
    flight.uninstall()
    profile.uninstall()


def set_sentry(sentry) -> None:
    """Register the run's HealthSentry (None clears).  /healthz and
    flight bundles read its ``state_dict()``."""
    global _sentry
    _sentry = sentry


def set_membership(controller) -> None:
    """Register the run's MembershipController (None clears) —
    /healthz gains a ``membership`` block with the current view."""
    global _membership
    _membership = controller


def membership_state() -> Optional[dict]:
    """The active membership controller's exported view, or None."""
    m = _membership
    if m is None:
        return None
    return m.state_dict()


def sentry_state() -> Optional[dict]:
    """The active sentry's exported state, or None when no sentry."""
    s = _sentry
    if s is None:
        return None
    return s.state_dict()


def profile_state() -> Optional[dict]:
    """The active round profiler's exported state (straggler verdict,
    hidden fractions), or None — the /healthz "profile" block."""
    return profile.state()


def set_slo_evaluator(evaluator) -> None:
    """Register the run's SLO evaluator (None clears) — /healthz gains
    an ``slo`` block with objective statuses and recent alerts."""
    global _slo_evaluator
    _slo_evaluator = evaluator


def slo_state() -> Optional[dict]:
    """The active SLO evaluator's compact state, or None."""
    ev = _slo_evaluator
    if ev is None:
        return None
    return ev.state()


def fault(kind: str, **args) -> None:
    """Tag a fault: an instant event on the trace (so fault ->
    recovery latency is readable off the timeline) + the per-kind
    counter when metrics are on + a flight-recorder postmortem dump
    when one is installed (faults are exactly the moments whose recent
    history a postmortem wants)."""
    instant(f"fault_{kind}", cat="fault", **args)
    tm = _training
    if tm is not None:
        tm.faults.labels(kind).inc()
    flight.dump_if_active(f"fault_{kind}", extra=args or None)


def report_unhealthy(reason: str) -> None:
    """Flip /healthz to 503 (stalled feed / wedged round)."""
    global _unhealthy_reason
    _unhealthy_reason = reason


def report_healthy() -> None:
    """A round completed: clear the unhealthy flag."""
    global _unhealthy_reason
    if _unhealthy_reason is not None:
        _unhealthy_reason = None


def health_reason() -> Optional[str]:
    return _unhealthy_reason


# ----------------------------------------------------------------------
# CLI wiring: every training entry point gets the same two flags


def add_cli_args(parser) -> None:
    parser.add_argument(
        "--obs", action="store_true",
        help="serve live Prometheus /metrics + /healthz for this run "
        "(sidecar on --obs_port)",
    )
    parser.add_argument(
        "--obs_port", type=int, default=DEFAULT_OBS_PORT,
        help="telemetry sidecar port (0 = ephemeral)",
    )
    parser.add_argument(
        "--trace_out", "--trace-out", default=None, metavar="TRACE.json",
        help="write a Chrome trace (load in Perfetto: ui.perfetto.dev) "
        "of round phases to this path, plus a .jsonl structured run log",
    )
    parser.add_argument(
        "--health", nargs="?", const="warn", default=None,
        choices=["warn", "halt", "rollback"], metavar="POLICY",
        help="enable the in-graph numerics audit + divergence sentry "
        "(warn|halt|rollback; bare --health = warn).  rollback restores "
        "the newest verified snapshot and skips the poisoned window "
        "(needs snapshot machinery; loops without it degrade to halt)",
    )
    parser.add_argument(
        "--health_policy", default=None,
        choices=["warn", "halt", "rollback"],
        help="sentry policy (overrides --health's value)",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="install the round-anatomy profiler (obs/profile.py): "
        "live per-phase breakdown, measured H2D/collective hidden "
        "fractions, per-worker skew + straggler verdicts, and "
        "MFU/roofline gauges on /metrics, /healthz and the JSONL run "
        "log; a summary table prints when the run closes",
    )
    parser.add_argument(
        "--profile_out", default=None, metavar="SUMMARY.json",
        help="write the end-of-run RoundProfiler.summary() as JSON "
        "(implies --profile); feed it to tools/perf_gate.py --live to "
        "compare this run against the committed baselines",
    )
    parser.add_argument(
        "--slo", action="store_true",
        help="retain metric history in the in-process TSDB "
        "(obs/tsdb.py ring buffers, staged 1s/10s/60s rollups) and "
        "evaluate burn-rate SLOs over it (obs/slo.py): the sidecar "
        "gains /query, /slo and /signals plus an slo /healthz block "
        "(implies --obs)",
    )
    parser.add_argument(
        "--ship_to", default=None, metavar="http://HOST:PORT",
        help="ship this process's metric deltas + run-log events to a "
        "fleet collector (obs/ship.py; dedicated thread, bounded "
        "buffer, retry backoff — training never blocks on the network)",
    )
    parser.add_argument(
        "--fleet_collector", nargs="?",
        const=f"127.0.0.1:{DEFAULT_FLEET_PORT}", default=None,
        metavar="HOST:PORT",
        help="start the fleet collector in this process (obs/fleet.py: "
        "cross-host metric/event merge, clock-aligned /trace + "
        "/runlog, global /fleet + /metrics with live|late|dead "
        "attribution).  Without --ship_to this process also ships to "
        "its own collector",
    )
    parser.add_argument(
        "--host_id", default=None,
        help="this process's identity in the fleet view (default: "
        "$SPARKNET_HOST_ID, else hostname:pid)",
    )
    parser.add_argument(
        "--flight_recorder", nargs="?",
        const=flight.DEFAULT_BUNDLE_PATH, default=None,
        metavar="BUNDLE.json",
        help="keep a bounded in-memory ring of recent spans/metric "
        "samples/health verdicts and dump it as a postmortem JSON "
        "bundle on crash, SIGTERM, feed stall, sentry halt, or chaos "
        "fault (fold it with tools/health_report.py)",
    )


class ObsRun:
    """Handle for one run's telemetry; ``close()`` is idempotent —
    stops the sidecar and writes the trace file.

    Deliberately NOT torn down: the training-metrics registry and the
    span->histogram observer.  They are process-wide and shared (the
    Prometheus model: counters are cumulative over the PROCESS's
    lifetime and survive run boundaries — ``rate()`` handles restarts;
    a later ``--obs`` run in the same process scrapes continuing
    totals, not zeros).  The residual cost of the observer once metrics
    have ever been enabled is one histogram observe per phase span —
    microseconds per round (measured in ``OBS_r09.json``)."""

    def __init__(self, exporter=None, tracer=None, trace_out=None,
                 metrics: Optional[TrainingMetrics] = None,
                 recorder: Optional[FlightRecorder] = None,
                 profiler: Optional["RoundProfiler"] = None,
                 echo=None, profile_out: Optional[str] = None,
                 shipper: Optional["Shipper"] = None,
                 collector: Optional["FleetCollector"] = None,
                 sampler=None):
        self.exporter = exporter
        self.tracer = tracer
        self.trace_out = trace_out
        self.metrics = metrics
        self.recorder = recorder
        self.profiler = profiler
        self.profile_out = profile_out
        self.shipper = shipper
        self.collector = collector
        self.sampler = sampler
        self._echo = echo
        self._closed = False

    @property
    def address(self):
        return self.exporter.address if self.exporter is not None else None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.profiler is not None:
            # print the round-anatomy summary BEFORE tearing telemetry
            # down — a --profile run with no tracer still gets its table
            if self._echo is not None and self.profiler.rounds_profiled:
                try:
                    self._echo(profile_summary_text(self.profiler))
                except Exception:  # noqa: BLE001 — teardown must not die
                    pass
            if self.profile_out:
                try:
                    with open(self.profile_out, "w") as f:
                        json.dump(self.profiler.summary(), f, indent=1)
                    if self._echo is not None:
                        self._echo(
                            "obs: profile summary -> %s (fold with "
                            "tools/perf_gate.py --live)" % self.profile_out
                        )
                except Exception:  # noqa: BLE001 — teardown must not die
                    pass
            profile.uninstall(self.profiler)
        if self.sampler is not None:
            # final sample + evaluator pass, then detach: a later run in
            # this process must not inherit this run's alert state
            self.sampler.stop()
            set_slo_evaluator(None)
        if self.exporter is not None:
            self.exporter.close()
        if self.tracer is not None:
            if get_tracer() is self.tracer:
                uninstall_tracer()
            if self.trace_out:
                self.tracer.save(self.trace_out)
            self.tracer.close()
        if self.recorder is not None:
            # clean close: detach WITHOUT dumping (bundles are
            # postmortems; any already-dumped one stays on disk)
            flight.uninstall(self.recorder)
        if self.shipper is not None:
            # detach the trace hook FIRST (no events enqueue during the
            # final flush), then stop — stop() ships the buffered tail
            from sparknet_tpu.obs import trace as _trace

            if _trace._ship is self.shipper:
                set_ship(None)
            self.shipper.stop()
        if self.collector is not None:
            # after the shipper's final flush, so a local collector
            # sees this run's tail before the listener goes down
            self.collector.close()
        # the run's divergence sentry is scoped to the run as well: a
        # later run in this process must not inherit a halted /healthz
        # or embed this run's verdicts in its flight bundles
        set_sentry(None)
        # ... and so is its membership controller (same scoping rule)
        set_membership(None)


def profile_summary_text(profiler) -> str:
    """Human one-screen rendering of a profiler summary (the --profile
    end-of-run table)."""
    s = profiler.summary()
    lines = ["profile: round anatomy over %d round(s)" % s["rounds"]]
    for name, p in s["phases"].items():
        lines.append(
            "  %-10s p50 %9.2f ms  p90 %9.2f ms  max %9.2f ms  [%s]"
            % (name, p["p50_ms"], p["p90_ms"], p["max_ms"], p["bound"])
        )
    for key, label in (
        ("hidden_frac_h2d", "H2D hidden fraction"),
        ("hidden_frac_comm", "collective hidden fraction"),
    ):
        if s.get(key):
            lines.append(
                "  %s: p50 %.3f (min %.3f)"
                % (label, s[key]["p50"], s[key]["min"])
            )
    if s.get("worker_skew"):
        lines.append(
            "  worker skew (max/median): p50 %.3f max %.3f; straggler "
            "rounds %d%s"
            % (
                s["worker_skew"]["p50"], s["worker_skew"]["max"],
                s["straggler_rounds"],
                " (last: worker %s @ round %s)"
                % (s["last_straggler_worker"], s["last_straggler_round"])
                if s["last_straggler_worker"] is not None else "",
            )
        )
    if s.get("achieved_flops_per_s"):
        mfu = s.get("mfu")
        lines.append(
            "  achieved %.2f GFLOP/s%s"
            % (
                s["achieved_flops_per_s"] / 1e9,
                "  (MFU %.2f%%)" % (100 * mfu) if mfu else
                "  (no bf16 peak on this platform: MFU n/a)",
            )
        )
    return "\n".join(lines)


def start(
    metrics: bool = False,
    port: int = DEFAULT_OBS_PORT,
    host: str = "127.0.0.1",
    trace_out: Optional[str] = None,
    flight_out: Optional[str] = None,
    profile_rounds: bool = False,
    profile_out: Optional[str] = None,
    ship_to: Optional[str] = None,
    fleet_collector: Optional[str] = None,
    host_id: Optional[str] = None,
    slo: bool = False,
    echo=print,
) -> ObsRun:
    """Turn telemetry on for this run: ``metrics=True`` starts the
    /metrics + /healthz sidecar; ``trace_out`` installs the tracer;
    ``flight_out`` installs the crash flight recorder (bundle path);
    ``profile_rounds`` installs the round-anatomy profiler;
    ``fleet_collector`` ("HOST:PORT") starts the cross-host fleet
    collector in this process; ``ship_to`` (a collector URL) ships this
    process's metric deltas + run-log events there — with a collector
    but no ``ship_to`` the process ships to its own collector.
    ``slo=True`` (implies metrics) arms the in-process TSDB sampler +
    burn-rate SLO evaluator, and the sidecar additionally serves
    /query, /slo and /signals.
    metrics/trace/profile/ship also enable the training metric series
    (spans feed the per-phase histogram; the shipper snapshots it).
    Returns an ``ObsRun`` to ``close()`` in the run's ``finally``."""
    profile_rounds = profile_rounds or bool(profile_out)
    metrics = metrics or slo
    if not any((metrics, trace_out, flight_out, profile_rounds, ship_to,
                fleet_collector)):
        return ObsRun()
    recorder = None
    if flight_out:
        recorder = flight.install(FlightRecorder(path=flight_out))
        if echo is not None:
            echo(f"obs: flight recorder armed -> {flight_out}")
    profiler = None
    if profile_rounds:
        profiler = profile.install(RoundProfiler())
        if echo is not None:
            echo(
                "obs: round-anatomy profiler on (phase breakdown, "
                "hidden fractions, straggler verdicts)"
            )
    collector = None
    if fleet_collector:
        from sparknet_tpu.obs.fleet import parse_hostport

        chost, cport = parse_hostport(fleet_collector)
        collector = FleetCollector(host=chost, port=cport).start()
        if echo is not None:
            echo(
                "obs: fleet collector on %s/fleet (merged /metrics, "
                "clock-aligned /trace + /runlog)" % collector.url
            )
        if not ship_to:
            ship_to = collector.url  # one flag = a self-shipping fleet
    if not any((metrics, trace_out, profile_rounds, ship_to)):
        return ObsRun(recorder=recorder, collector=collector, echo=echo)
    tm = enable_training_metrics()
    sampler = None
    evaluator = None
    tsdb = None
    if slo:
        from sparknet_tpu.obs.slo import SLOEvaluator, TsdbSampler
        from sparknet_tpu.obs.tsdb import TSDB

        tsdb = TSDB(registry=tm.registry)
        evaluator = SLOEvaluator(
            tsdb, registry=tm.registry, live_registry=tm.registry,
            host=host_id,
        )
        set_slo_evaluator(evaluator)
        sampler = TsdbSampler(
            tsdb, tm.registry, evaluator=evaluator,
            host=host_id or "local",
        ).start()
        if echo is not None:
            echo(
                "obs: SLO plane armed — TSDB sampler + burn-rate "
                "evaluator (/query, /slo, /signals)"
            )
    exporter = None
    if metrics:
        exporter = ObsExporter(
            tm.registry, host=host, port=port, health_fn=health_reason,
            tsdb=tsdb, slo=evaluator,
        ).start()
        if echo is not None:
            h, p = exporter.address
            echo(f"obs: serving /metrics and /healthz on http://{h}:{p}")
    tracer = None
    if trace_out:
        tracer = install_tracer(Tracer(jsonl_path=jsonl_path_for(trace_out)))
        if echo is not None:
            echo(
                f"obs: tracing round phases -> {trace_out} "
                f"(+ {jsonl_path_for(trace_out)})"
            )
    shipper = None
    if ship_to:
        shipper = Shipper(
            ship_to, host=host_id, registry=tm.registry
        ).start()
        set_ship(shipper)
        if echo is not None:
            echo(
                "obs: shipping metric deltas + run-log events to "
                "%s as host %r" % (shipper.url, shipper.host)
            )
    return ObsRun(exporter, tracer, trace_out, tm, recorder, profiler, echo,
                  profile_out=profile_out, shipper=shipper,
                  collector=collector, sampler=sampler)


def start_from_args(args, echo=print) -> ObsRun:
    return start(
        metrics=getattr(args, "obs", False),
        port=getattr(args, "obs_port", DEFAULT_OBS_PORT),
        trace_out=getattr(args, "trace_out", None),
        flight_out=getattr(args, "flight_recorder", None),
        profile_rounds=getattr(args, "profile", False),
        profile_out=getattr(args, "profile_out", None),
        ship_to=getattr(args, "ship_to", None),
        fleet_collector=getattr(args, "fleet_collector", None),
        host_id=getattr(args, "host_id", None),
        slo=getattr(args, "slo", False),
        echo=echo,
    )
