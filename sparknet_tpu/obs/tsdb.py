"""In-process time-series store — the retention half of the obs plane.

Every fleet/obs view so far is an instantaneous snapshot: ``/metrics``
answers "what is the counter NOW", never "how fast has it been moving
for the last hour".  This module gives the collector (and the
single-host exporter) a memory: fixed-interval ring buffers per
series×host with staged downsampling — raw 1 s buckets cascade into
10 s and 60 s rollups, each bucket carrying min/max/sum/count/last —
**bounded-memory by construction**: every stage is a preallocated
``array('d')`` ring, a new series is admitted only while the accounted
byte budget holds, and nothing ever grows per-sample.

Design points:

- **series identity** is the full inline-labeled sample name exactly as
  ``MetricsRegistry.snapshot()`` keys it (``m{cause="queue_full"}``) ×
  the reporting host — the same vocabulary the fleet merge already
  stores in ``HostState.counters``/``gauges``, so recording a push is a
  dict walk, not a re-parse.
- **counters are stored as cumulative values** (each bucket's ``last``
  is the running total at that bucket); per-bucket **rate** is derived
  at query time from consecutive ``last`` samples with Prometheus
  counter-reset semantics (a drop restarts from zero, history is never
  un-counted).  Gauges use the same bucket statistics with ``last`` as
  the newest level.
- **downsampling is exact**, not resampled: every record lands in ALL
  stages at once, so a 60 s bucket's ``sum``/``count``/``min``/``max``
  are the fold of exactly the raw samples in its span — the
  raw-vs-rollup agreement ``bench.py --mode=slo`` pins is an identity,
  not an approximation.
- **queries are served sparse**: empty buckets are skipped, the stage
  is chosen as the finest one that covers the requested range at (or
  above) the requested step, and the response declares the step it
  actually used.

``obs/slo.py`` evaluates burn-rate objectives over this store;
``obs/fleet.py`` records every merged push into it and serves
``GET /query``.
"""

from __future__ import annotations

import re
import threading
from array import array
from typing import Dict, List, Optional, Sequence, Tuple

# staged retention: (bucket seconds, bucket count) — 1 s raw for 5 min,
# 10 s rollups for 70 min, 60 s rollups for 7 h (the 6 h burn-rate
# window fits the coarsest stage with headroom)
DEFAULT_STAGES: Tuple[Tuple[float, int], ...] = (
    (1.0, 300),
    (10.0, 420),
    (60.0, 420),
)
DEFAULT_BUDGET_BYTES = 32 << 20
# fixed per-series overhead charged against the budget beyond the rings
# (dict slots, key strings, object headers — a deliberate overestimate)
SERIES_OVERHEAD_BYTES = 512

_LE_RE = re.compile(r'le="([^"]+)"')


class _Stage:
    """One fixed-step ring of rollup buckets for one series."""

    __slots__ = ("step", "cap", "mn", "mx", "sm", "ct", "last", "newest")

    def __init__(self, step: float, cap: int):
        self.step = float(step)
        self.cap = int(cap)
        zeros = [0.0] * self.cap
        self.mn = array("d", zeros)
        self.mx = array("d", zeros)
        self.sm = array("d", zeros)
        self.last = array("d", zeros)
        self.ct = array("q", [0] * self.cap)
        self.newest: Optional[int] = None  # absolute bucket index

    def nbytes(self) -> int:
        return sum(
            a.buffer_info()[1] * a.itemsize
            for a in (self.mn, self.mx, self.sm, self.last, self.ct)
        )

    def record(self, t: float, v: float) -> None:
        b = int(t // self.step)
        if self.newest is None:
            self.newest = b
        elif b > self.newest:
            span = b - self.newest
            if span >= self.cap:
                for i in range(self.cap):
                    self.ct[i] = 0
            else:
                for k in range(self.newest + 1, b + 1):
                    self.ct[k % self.cap] = 0
            self.newest = b
        elif b <= self.newest - self.cap:
            return  # older than this stage retains
        i = b % self.cap
        if self.ct[i] == 0:
            self.mn[i] = self.mx[i] = self.sm[i] = v
            self.ct[i] = 1
        else:
            if v < self.mn[i]:
                self.mn[i] = v
            if v > self.mx[i]:
                self.mx[i] = v
            self.sm[i] += v
            self.ct[i] += 1
        self.last[i] = v

    def buckets(self, from_t: float, to_t: float):
        """Non-empty ``(bucket_start_s, mn, mx, sm, ct, last)`` rows in
        ``[from_t, to_t]``, oldest first."""
        if self.newest is None:
            return
        lo = max(int(from_t // self.step), self.newest - self.cap + 1)
        hi = min(int(to_t // self.step), self.newest)
        for b in range(lo, hi + 1):
            i = b % self.cap
            if self.ct[i]:
                yield (
                    b * self.step, self.mn[i], self.mx[i], self.sm[i],
                    self.ct[i], self.last[i],
                )


class Series:
    """All retention stages for one series×host."""

    __slots__ = ("kind", "stages", "nbytes", "last_t")

    def __init__(self, kind: str, stages: Sequence[Tuple[float, int]]):
        self.kind = kind  # "counter" | "gauge"
        self.stages = [_Stage(step, cap) for step, cap in stages]
        self.nbytes = (
            sum(s.nbytes() for s in self.stages) + SERIES_OVERHEAD_BYTES
        )
        self.last_t = float("-inf")

    def record(self, t: float, v: float) -> None:
        if t > self.last_t:
            self.last_t = t
        for s in self.stages:
            s.record(t, v)


def _counter_increase(rows: List[tuple], from_t: float) -> Tuple[float, float]:
    """(increase, covered_span_s) of a cumulative counter over the
    window, from its bucket ``last`` samples (rows may start before
    ``from_t`` to provide the baseline).  Reset semantics: a drop means
    the post-reset value IS the increment."""
    inc = 0.0
    prev_v: Optional[float] = None
    prev_t: Optional[float] = None
    t_first_in = None
    t_last_in = None
    for t, _mn, _mx, _sm, _ct, last in rows:
        if prev_v is not None and t >= from_t:
            inc += last if last < prev_v else last - prev_v
            if t_first_in is None:
                t_first_in = prev_t
            t_last_in = t
        prev_v, prev_t = last, t
    span = (t_last_in - t_first_in) if t_last_in is not None else 0.0
    return inc, span


class TSDB:
    """The bounded store: ``record`` on every push, ``query`` for the
    HTTP plane, windowed folds for the SLO evaluator."""

    def __init__(
        self,
        budget_bytes: int = DEFAULT_BUDGET_BYTES,
        stages: Sequence[Tuple[float, int]] = DEFAULT_STAGES,
        registry=None,
    ):
        self.budget_bytes = int(budget_bytes)
        self.stage_spec = tuple(
            (float(step), int(cap))
            for step, cap in sorted(stages, key=lambda sc: sc[0])
        )
        self._lock = threading.Lock()
        # name -> host -> Series
        self._series: Dict[str, Dict[str, Series]] = {}
        self._bytes = 0
        self._nseries = 0
        self._samples = 0
        self._dropped = 0
        self._m_bytes = self._m_series = None
        self._m_samples = self._m_dropped = None
        self._exported_samples = 0
        self._exported_dropped = 0
        if registry is not None:
            r = registry
            self._m_bytes = r.get("sparknet_tsdb_resident_bytes") or r.gauge(
                "sparknet_tsdb_resident_bytes",
                "accounted bytes resident in the time-series store "
                "(rings + per-series overhead; bounded by the budget)",
            )
            self._m_series = r.get("sparknet_tsdb_series") or r.gauge(
                "sparknet_tsdb_series",
                "series x host ring sets currently allocated",
            )
            self._m_samples = (
                r.get("sparknet_tsdb_samples_total") or r.counter(
                    "sparknet_tsdb_samples_total",
                    "samples folded into the store (one per series per "
                    "recorded push)",
                )
            )
            self._m_dropped = (
                r.get("sparknet_tsdb_dropped_series_total") or r.counter(
                    "sparknet_tsdb_dropped_series_total",
                    "new-series admissions refused at the byte budget "
                    "(existing series keep recording)",
                )
            )

    # ------------------------------------------------------------------
    # write side
    def record(self, name: str, host: str, value: float, t: float,
               kind: str = "gauge") -> bool:
        """Fold one sample; returns False when a NEW series was refused
        at the byte budget (existing series always record)."""
        with self._lock:
            return self._record_locked(name, host, float(value), t, kind)

    def _record_locked(self, name, host, value, t, kind) -> bool:
        hosts = self._series.get(name)
        if hosts is None:
            hosts = self._series[name] = {}
        sr = hosts.get(host)
        if sr is None:
            sr = Series(kind, self.stage_spec)
            if self._bytes + sr.nbytes > self.budget_bytes:
                self._dropped += 1
                if not hosts:
                    del self._series[name]
                return False
            hosts[host] = sr
            self._bytes += sr.nbytes
            self._nseries += 1
        sr.record(t, value)
        self._samples += 1
        return True

    def record_snapshot(
        self,
        host: str,
        counters: Dict[str, float],
        gauges: Dict[str, float],
        t: float,
    ) -> None:
        """Fold one host's merged sample maps (the fleet ``ingest``
        path / the single-host sampler path) in one lock hold."""
        with self._lock:
            for name, v in counters.items():
                self._record_locked(name, host, float(v), t, "counter")
            for name, v in gauges.items():
                self._record_locked(name, host, float(v), t, "gauge")
        self.refresh_metrics()

    def refresh_metrics(self) -> None:
        """Push the store's own accounting into its registry gauges."""
        if self._m_bytes is None:
            return
        with self._lock:
            nbytes, nseries = self._bytes, self._nseries
            samples, dropped = self._samples, self._dropped
        self._m_bytes.set(nbytes)
        self._m_series.set(nseries)
        if samples > self._exported_samples:
            self._m_samples.inc(samples - self._exported_samples)
            self._exported_samples = samples
        if dropped > self._exported_dropped:
            self._m_dropped.inc(dropped - self._exported_dropped)
            self._exported_dropped = dropped

    # ------------------------------------------------------------------
    # introspection
    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._bytes,
                "series": self._nseries,
                "samples_total": self._samples,
                "dropped_series_total": self._dropped,
                "stages": [
                    {"step_s": step, "buckets": cap,
                     "retention_s": step * cap}
                    for step, cap in self.stage_spec
                ],
            }

    def series_names(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(
                n for n in self._series if n.startswith(prefix)
            )

    def hosts(self) -> List[str]:
        with self._lock:
            out = set()
            for hosts in self._series.values():
                out.update(hosts)
            return sorted(out)

    def latest(self, name: str, host: Optional[str] = None) -> Optional[float]:
        """Newest ``last`` across the finest stage holding data (summed
        across hosts when ``host`` is None — counter semantics)."""
        with self._lock:
            hosts = self._series.get(name)
            if not hosts:
                return None
            total, seen = 0.0, False
            for h, sr in hosts.items():
                if host is not None and h != host:
                    continue
                for st in sr.stages:
                    if st.newest is not None:
                        total += st.last[st.newest % st.cap]
                        seen = True
                        break
            return total if seen else None

    # ------------------------------------------------------------------
    # read side
    def _pick_stage_spec(
        self, range_s: float, step_s: Optional[float],
        reach_s: Optional[float] = None,
    ) -> int:
        """Index of the finest stage at/above the requested step whose
        retention covers the range (else the coarsest candidate).
        ``reach_s`` is how far back from the series' NEWEST data the
        window's oldest edge sits: a ring only retains relative to
        what it last recorded, so a historic window (``now`` in the
        past — the signals' previous-window reads) must fall to a
        stage whose retention actually reaches it."""
        need = max(float(range_s), reach_s or 0.0)
        cands = [
            i for i, (step, _cap) in enumerate(self.stage_spec)
            if step_s is None or step >= float(step_s) - 1e-9
        ] or [len(self.stage_spec) - 1]
        for i in cands:
            step, cap = self.stage_spec[i]
            if step * cap >= need:
                return i
        return cands[-1]

    def query(
        self,
        name: str,
        host: Optional[str] = None,
        range_s: float = 300.0,
        step_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Optional[Dict]:
        """The ``GET /query`` payload: sparse rollup points over
        ``[now - range_s, now]``.  ``host=None`` aggregates across
        hosts (min of mins, max of maxes, pooled sum/count, ``last``
        and ``rate`` summed — the fleet-total read).  Returns None for
        an unknown series."""
        with self._lock:
            hosts = self._series.get(name)
            if not hosts:
                return None
            picked = [
                (h, sr) for h, sr in sorted(hosts.items())
                if host is None or h == host
            ]
            if not picked:
                return None
            kind = picked[0][1].kind
            newest = max(sr.last_t for _h, sr in picked)
            if now is None:
                now = newest if newest > float("-inf") else 0.0
            from_t = now - float(range_s)
            si = self._pick_stage_spec(
                float(range_s), step_s,
                reach_s=newest - from_t if newest > from_t else None,
            )
            step = self.stage_spec[si][0]
            merged: Dict[float, List[float]] = {}
            for _h, sr in picked:
                st = sr.stages[si]
                rows = list(st.buckets(from_t - step, now))
                prev_last: Optional[float] = None
                prev_t: Optional[float] = None
                for t, mn, mx, sm, ct, last in rows:
                    rate = None
                    if kind == "counter" and prev_last is not None:
                        inc = (
                            last if last < prev_last else last - prev_last
                        )
                        dt = t - prev_t
                        rate = inc / dt if dt > 0 else None
                    prev_last, prev_t = last, t
                    if t < from_t:
                        continue
                    agg = merged.get(t)
                    if agg is None:
                        merged[t] = [mn, mx, sm, ct, last,
                                     rate if rate is not None else 0.0,
                                     1 if rate is not None else 0]
                    else:
                        agg[0] = min(agg[0], mn)
                        agg[1] = max(agg[1], mx)
                        agg[2] += sm
                        agg[3] += ct
                        agg[4] += last
                        if rate is not None:
                            agg[5] += rate
                            agg[6] += 1
        points = []
        for t in sorted(merged):
            mn, mx, sm, ct, last, rate, nrate = merged[t]
            points.append({
                "t": round(t, 3),
                "min": mn,
                "max": mx,
                "mean": sm / ct if ct else 0.0,
                "count": int(ct),
                "last": last,
                "rate": (rate if nrate else None),
            })
        return {
            "series": name,
            "host": host or "fleet",
            "kind": kind,
            "step_s": step,
            "from_s": round(now - float(range_s), 3),
            "to_s": round(now, 3),
            "points": points,
        }

    def window_delta(
        self,
        name: str,
        window_s: float,
        now: float,
        host: Optional[str] = None,
    ) -> Tuple[float, float]:
        """Counter increase over ``[now - window_s, now]`` (summed
        across hosts when ``host`` is None) with reset semantics, plus
        the covered span actually observed (0 when there are not two
        samples to difference)."""
        from_t = now - float(window_s)
        total, span = 0.0, 0.0
        with self._lock:
            hosts = self._series.get(name)
            if not hosts:
                return 0.0, 0.0
            picked = [
                sr for h, sr in hosts.items()
                if host is None or h == host
            ]
            if not picked:
                return 0.0, 0.0
            newest = max(sr.last_t for sr in picked)
            si = self._pick_stage_spec(
                float(window_s), None,
                reach_s=newest - from_t if newest > from_t else None,
            )
            step = self.stage_spec[si][0]
            for sr in picked:
                st = sr.stages[si]
                # one bucket of lookback supplies the baseline sample
                rows = list(st.buckets(from_t - step * st.cap, now))
                inc, sp = _counter_increase(rows, from_t)
                total += inc
                span = max(span, sp)
        return total, span

    def window_delta_prefix(
        self,
        prefix: str,
        window_s: float,
        now: float,
        host: Optional[str] = None,
    ) -> Tuple[float, float]:
        """Summed ``window_delta`` over every series whose full sample
        name starts with ``prefix`` — the label-family fold (all shed
        causes, all phases)."""
        total, span = 0.0, 0.0
        for name in self.series_names(prefix):
            inc, sp = self.window_delta(name, window_s, now, host=host)
            total += inc
            span = max(span, sp)
        return total, span

    def window_stats(
        self,
        name: str,
        window_s: float,
        now: float,
        host: Optional[str] = None,
    ) -> Optional[Dict[str, float]]:
        """min/max/mean/last of a gauge over the window (pooled across
        hosts when ``host`` is None; ``last`` sums — the fleet-level
        read for additive gauges like queue depth)."""
        res = self.query(
            name, host=host, range_s=window_s, step_s=None, now=now
        )
        if res is None or not res["points"]:
            return None
        pts = res["points"]
        tot_ct = sum(p["count"] for p in pts)
        return {
            "min": min(p["min"] for p in pts),
            "max": max(p["max"] for p in pts),
            "mean": (
                sum(p["mean"] * p["count"] for p in pts) / tot_ct
                if tot_ct else 0.0
            ),
            "last": pts[-1]["last"],
        }

    def slope_per_s(
        self,
        name: str,
        window_s: float,
        now: float,
        host: Optional[str] = None,
    ) -> float:
        """Least-squares slope (value units per second) of the bucket
        means over the window — the trend primitive behind the scaling
        signals.  0.0 with fewer than two points."""
        res = self.query(
            name, host=host, range_s=window_s, step_s=None, now=now
        )
        if res is None or len(res["points"]) < 2:
            return 0.0
        pts = res["points"]
        n = len(pts)
        t0 = pts[0]["t"]
        xs = [p["t"] - t0 for p in pts]
        ys = [p["mean"] for p in pts]
        mean_x = sum(xs) / n
        mean_y = sum(ys) / n
        den = sum((x - mean_x) ** 2 for x in xs)
        if den <= 0:
            return 0.0
        return sum(
            (x - mean_x) * (y - mean_y) for x, y in zip(xs, ys)
        ) / den

    def histogram_window(
        self,
        hist: str,
        window_s: float,
        now: float,
        host: Optional[str] = None,
    ) -> Optional[Dict]:
        """Windowed view of a (label-free) histogram's shipped bucket
        counters: ``{"le": [(le, increase), ...] cumulative ascending,
        "count": N, "sum": S}`` — the input to bucket-quantile and
        threshold-fraction folds.  None when no count moved."""
        count, _ = self.window_delta(f"{hist}_count", window_s, now, host)
        if count <= 0:
            return None
        total_sum, _ = self.window_delta(f"{hist}_sum", window_s, now, host)
        les: List[Tuple[float, float]] = []
        for name in self.series_names(f"{hist}_bucket{{"):
            m = _LE_RE.search(name)
            if not m:
                continue
            raw = m.group(1)
            le = float("inf") if raw == "+Inf" else float(raw)
            inc, _ = self.window_delta(name, window_s, now, host)
            les.append((le, inc))
        les.sort(key=lambda p: p[0])
        return {"le": les, "count": count, "sum": total_sum}


def bucket_quantile(les: List[Tuple[float, float]], q: float) -> float:
    """Quantile from cumulative ``(le, windowed_increase)`` rows, the
    Prometheus ``histogram_quantile`` fold: linear interpolation inside
    the winning bucket, the +Inf bucket reporting its lower bound."""
    if not les:
        return 0.0
    total = les[-1][1]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in les:
        if c >= rank:
            if le == float("inf"):
                return prev_le
            width = le - prev_le
            in_bucket = c - prev_c
            if in_bucket <= 0 or width <= 0:
                return le
            return prev_le + width * (rank - prev_c) / in_bucket
        prev_le, prev_c = le, c
    return prev_le
