"""Round-span tracing: Chrome trace-event JSON + a structured JSONL log.

A ``Tracer`` collects *complete* events (``ph: "X"``) from ``span()``
context managers and *instant* events (``ph: "i"``) from ``instant()``,
each stamped with the real OS thread id — so when the pipelined
``RoundFeed`` assembles round r+1 on its producer thread while round r
executes on the consumer, the two span tracks interleave **visually**
in Perfetto (chrome://tracing loads the same file).  Thread-name
metadata events label each track ("roundfeed-producer" vs
"MainThread").

Alongside the Chrome JSON (written once, at ``save()``), every event
can stream to a JSONL run log as it completes — one self-contained JSON
object per line, crash-durable (flushed per line), greppable, and
parseable by ``tools/parse_log.py`` (the structured successor to the
flat ``training_log_<ts>.txt``).

Cost discipline: the module-level ``span()``/``instant()`` fast path is
a shared no-op when no tracer is installed (one global read), so
instrumented hot paths pay ~nothing by default; with tracing on, a span
is two ``perf_counter`` reads and one list append under a lock —
``bench.py --mode=obs`` measures the end-to-end round-time overhead
(<2% acceptance, ``OBS_r09.json``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class Tracer:
    """Collects trace events; thread-safe; bounded (``max_events``
    guards a runaway run — the newest events win a dropped-count note)."""

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        max_events: int = 500_000,
    ):
        self._t0 = time.perf_counter()
        self._epoch = time.time()
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0
        self._max_events = int(max_events)
        self._thread_names: Dict[int, str] = {}
        self._pid = os.getpid()
        # truncate: one Tracer = one run's log, exactly like save()
        # rewrites the Chrome JSON — re-tracing to the same --trace_out
        # must not interleave two runs' records in one .jsonl
        self._jsonl = open(jsonl_path, "w") if jsonl_path else None
        self.jsonl_path = jsonl_path

    # ------------------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _note_thread(self, tid: int) -> None:
        # called under self._lock
        if tid not in self._thread_names:
            name = threading.current_thread().name
            self._thread_names[tid] = name
            self._events.append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid, "args": {"name": name},
            })

    def _emit(self, ev: dict, jsonl_rec: dict) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                self._dropped += 1
            else:
                self._note_thread(ev["tid"])
                self._events.append(ev)
            f = self._jsonl
        if f is not None:
            # one self-contained object per line, flushed — the run log
            # survives a crash up to the last completed event
            try:
                f.write(json.dumps(jsonl_rec) + "\n")
                f.flush()
            except ValueError:  # closed mid-shutdown: drop, don't die
                pass

    # ------------------------------------------------------------------
    def complete(self, name: str, cat: str, t_start_us: float,
                 dur_us: float, args: Optional[dict] = None) -> None:
        """Record a finished span (chrome ``ph: "X"``)."""
        tid = threading.get_ident()
        ev = {
            "name": name, "cat": cat, "ph": "X",
            "ts": t_start_us, "dur": dur_us,
            "pid": self._pid, "tid": tid,
        }
        if args:
            ev["args"] = args
        rec = {
            "kind": "span", "name": name, "cat": cat,
            "ts_s": round(t_start_us / 1e6, 6),
            "dur_ms": round(dur_us / 1e3, 4),
            "thread": threading.current_thread().name,
        }
        if args:
            rec["args"] = args
        self._emit(ev, rec)

    def instant(self, name: str, cat: str = "event",
                args: Optional[dict] = None) -> None:
        """Record a point event (chrome ``ph: "i"``, thread-scoped) —
        fault injections, retries, recoveries."""
        ts = self._now_us()
        tid = threading.get_ident()
        ev = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": ts, "pid": self._pid, "tid": tid,
        }
        if args:
            ev["args"] = args
        rec = {
            "kind": "instant", "name": name, "cat": cat,
            "ts_s": round(ts / 1e6, 6),
            "thread": threading.current_thread().name,
        }
        if args:
            rec["args"] = args
        self._emit(ev, rec)

    # ------------------------------------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def save(self, path: str) -> str:
        """Write the Chrome trace-event JSON (object form, Perfetto- and
        chrome://tracing-loadable)."""
        with self._lock:
            doc = {
                "traceEvents": list(self._events),
                "displayTimeUnit": "ms",
                "otherData": {
                    "producer": "sparknet_tpu.obs",
                    "epoch_unix_s": self._epoch,
                    "dropped_events": self._dropped,
                },
            }
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def close(self) -> None:
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None


# ----------------------------------------------------------------------
# module-level fast path: install_tracer() flips span()/instant() from
# shared no-ops to recording — instrumented code never holds a Tracer

_tracer: Optional[Tracer] = None
# observes (name, dur_s) of phase-cat spans into the metrics layer when
# training metrics are enabled (set by obs/__init__; None = off)
_phase_observer = None
# observes EVERY completed span with its interval and thread —
# fn(name, cat, t0_s, t1_s, thread_name, args) where t0/t1 are
# perf_counter values (comparable across threads in one process).  The
# RoundProfiler (obs/profile.py) installs itself here to fold the span
# stream into per-round phase/overlap accounting; None = off
_span_observer = None
# the installed FlightRecorder's event ring (obs/flight.py; None = off)
# — spans/instants feed it even when no Tracer is recording
_flight = None
# the installed fleet Shipper (obs/ship.py; None = off) — spans/
# instants feed its bounded buffer the same way, stamped with wall
# time so the collector can clock-align N hosts' records
_ship = None


def install_tracer(tracer: Tracer) -> Tracer:
    global _tracer
    _tracer = tracer
    return tracer


def uninstall_tracer() -> Optional[Tracer]:
    global _tracer
    t, _tracer = _tracer, None
    return t


def get_tracer() -> Optional[Tracer]:
    return _tracer


def set_phase_observer(fn) -> None:
    global _phase_observer
    _phase_observer = fn


def set_span_observer(fn) -> None:
    """Point span() completions at a profiler (obs/profile.py owns the
    install/uninstall lifecycle).  ``fn(name, cat, t0_s, t1_s,
    thread_name, args)`` runs on the thread that closed the span."""
    global _span_observer
    _span_observer = fn


def set_flight(recorder) -> None:
    """Point span()/instant() at a flight-recorder ring (obs/flight.py
    owns the install/uninstall lifecycle)."""
    global _flight
    _flight = recorder


def set_ship(shipper) -> None:
    """Point span()/instant() at a fleet shipper's buffer (obs/ship.py;
    the ObsRun owns the install/uninstall lifecycle)."""
    global _ship
    _ship = shipper


class _NullSpan:
    """The disabled-path span: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name: str, cat: str, args: Optional[dict]):
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        dur_s = t1 - self._t0
        t = _tracer
        if t is not None:
            t.complete(
                self.name, self.cat,
                (self._t0 - t._t0) * 1e6, dur_s * 1e6, self.args,
            )
        f = _flight
        if f is not None:
            rec = {
                "kind": "span", "name": self.name, "cat": self.cat,
                "t_s": round(time.time(), 3),
                "dur_ms": round(dur_s * 1e3, 4),
                "thread": threading.current_thread().name,
            }
            if self.args:
                rec["args"] = self.args
            f.record_event(rec)
        sh = _ship
        if sh is not None:
            # full-resolution wall START time: the collector subtracts
            # the per-host clock offset from t_s when merging, so the
            # span lands on the fleet timeline where it began
            rec = {
                "kind": "span", "name": self.name, "cat": self.cat,
                "t_s": time.time() - dur_s,
                "dur_ms": round(dur_s * 1e3, 4),
                "thread": threading.current_thread().name,
            }
            if self.args:
                rec["args"] = self.args
            sh.record_event(rec)
        obs = _phase_observer
        if obs is not None and self.cat == "phase":
            obs(self.name, dur_s)
        so = _span_observer
        if so is not None:
            so(
                self.name, self.cat, self._t0, t1,
                threading.current_thread().name, self.args,
            )
        return False


def span(name: str, cat: str = "phase", **args):
    """Context manager timing one phase of work.  ``cat="phase"`` spans
    also feed the per-phase latency histogram when training metrics are
    enabled.  Near-free when tracing, metrics AND flight recording are
    off."""
    if (
        _tracer is None
        and _phase_observer is None
        and _flight is None
        and _span_observer is None
        and _ship is None
    ):
        return _NULL_SPAN
    return _Span(name, cat, args or None)


def instant(name: str, cat: str = "event", **args) -> None:
    """Record a tagged point event (no-op when tracing and flight
    recording are off)."""
    t = _tracer
    if t is not None:
        t.instant(name, cat, args or None)
    f = _flight
    if f is not None:
        rec = {
            "kind": "instant", "name": name, "cat": cat,
            "t_s": round(time.time(), 3),
            "thread": threading.current_thread().name,
        }
        if args:
            rec["args"] = args
        f.record_event(rec)
    sh = _ship
    if sh is not None:
        rec = {
            "kind": "instant", "name": name, "cat": cat,
            "t_s": time.time(),
            "thread": threading.current_thread().name,
        }
        if args:
            rec["args"] = args
        sh.record_event(rec)


def jsonl_path_for(trace_out: str) -> str:
    """``run.trace.json`` -> ``run.trace.jsonl`` (the structured run
    log that rides along with every Chrome trace)."""
    if trace_out.endswith(".json"):
        return trace_out[: -len(".json")] + ".jsonl"
    return trace_out + ".jsonl"
