"""Request anatomy: end-to-end per-request tracing for the serve plane.

The training side answers "why was round r slow?" with ``RoundProfiler``
(obs/profile.py) folding the live span stream.  The serve plane that now
runs autoregressive generation (serve/generate.py + serve/batcher.py)
exposed only aggregate histograms — ``sparknet_gen_ttft_seconds`` says
p99 is high, nothing says WHY: queue wait, KV-pool pressure, prefill,
decode, or the chunked stream write.  This module is the serving
counterpart of the round profiler, the same recipe applied per request:

- **Request IDs.**  ``maybe_rid()`` mints an id at admission (the HTTP
  handler or ``StreamBatcher.submit_stream``) ONLY while some trace sink
  is installed — the disabled path stays the shared-no-op ``span()``
  fast path plus one module-global read.  The id rides every span the
  request touches: ``queue_wait`` (submit -> decode-slot admit),
  ``kv_reserve`` (worst-case block reservation), the engine's ``gen``
  spans (``prefill``, and ``decode_step`` with the active set's ids),
  ``stream_write`` (one chunked-NDJSON write), and a whole-lifetime
  ``request`` envelope — all cat ``req`` except the two existing
  ``gen`` spans, all through ``obs.trace.span`` so the Tracer JSONL run
  log, the flight ring, and the PR-10 fleet shipper get them for free.
- **Shed instants.**  Every admission refusal emits a ``shed`` instant
  tagged with its cause (``queue_full`` | ``kv_reserve`` |
  ``draining``) via ``note_shed`` — the same causes the 429/503
  response header and the ``sparknet_gen_streams_shed_total{cause=}``
  label carry, so admission-pressure attribution survives aggregation.
- **RequestProfiler.**  Installed through the same
  ``trace.set_span_observer`` seam the RoundProfiler uses (composing
  with any observer already installed), it folds the stream live into
  per-stage p50/p95/p99, TTFT/TPOT decomposition, a queue- vs kv- vs
  prefill- vs decode- vs write-bound verdict per rolling window, and
  per-replica skew that NAMES the slow replica.  Verdicts feed
  ``/metrics`` (the ``sparknet_req_*`` gauges), the ``/healthz``
  request-profile block (``state()``), the JSONL run log + flight ring
  (``obs.instant``), and — because the gauges and instants ride the
  shared registry/shipper — ``GET /fleet`` on the collector.
- **One folding implementation.**  ``tools/request_report.py`` replays
  a run-log ``.jsonl`` or a fleet bundle through the SAME ``on_span`` /
  ``on_shed`` entry points and reads the same ``summary()`` /
  ``requests_table()`` — the offline report cannot drift from the live
  profiler.

Cost discipline: with no sinks installed the serve plane pays one
module-global read per hook (``bench.py --mode=servetrace`` pins the
traced-vs-untraced overhead inside the PR-4/PR-5 noise-floor contract,
SERVEOBS_r22.json); with tracing on, a span costs the usual two
``perf_counter`` reads and ``on_span`` a few dict ops under a lock.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Dict, List, Optional

from sparknet_tpu.obs import trace as _trace
from sparknet_tpu.obs.metrics import MetricsRegistry

# the per-request stages the profiler attributes (decode_step/prefill
# arrive on cat="gen"; the rest on cat="req")
REQUEST_STAGES = (
    "queue_wait", "kv_reserve", "prefill", "decode", "stream_write",
)

SHED_CAUSES = ("queue_full", "kv_reserve", "draining")

# verdict -> the numeric code sparknet_req_bound_stage exports (the
# sparknet_delivery_phase idiom: gauges carry numbers, docs the legend)
BOUND_CODE = {
    "idle": 0, "queue": 1, "kv": 2, "prefill": 3, "decode": 4, "write": 5,
}

_rid_counter = itertools.count(1)
_rid_lock = threading.Lock()


def mint_rid() -> str:
    """A process-unique request id (host-qualified later by the fleet
    shipper's host tag — two hosts' ``req-000007`` never collide in a
    merged bundle because the folder qualifies them)."""
    with _rid_lock:
        n = next(_rid_counter)
    return f"req-{n:06d}"


def tracing_enabled() -> bool:
    """True when ANY span sink is installed (tracer, flight ring, fleet
    shipper, or a span observer) — the condition under which minting a
    request id buys anything."""
    return (
        _trace._tracer is not None
        or _trace._flight is not None
        or _trace._ship is not None
        or _trace._span_observer is not None
    )


def maybe_rid(rid: Optional[str] = None) -> Optional[str]:
    """Pass an existing id through; mint one only when tracing is on.
    The disabled path is one function call and four global reads —
    the serve plane's zero-overhead contract."""
    if rid is not None:
        return rid
    if tracing_enabled():
        return mint_rid()
    return None


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class RequestProfiler:
    """Folds the request-span stream into per-stage percentiles,
    TTFT/TPOT decomposition, bound-stage verdicts, and per-replica skew.

    Parameters
    ----------
    window:
        Completed requests (and recent shed causes) the rolling
        verdict/percentile window covers.
    skew_threshold / skew_floor_s:
        A replica is named slow when its mean request time exceeds the
        replica median by BOTH the ratio and the absolute gap — the
        RoundProfiler's two-condition guard against microsecond noise.
    kv_shed_threshold:
        Window fraction of arrivals shed for ``kv_reserve`` above which
        the verdict is ``kv`` regardless of stage shares (a squeezed
        arena sheds instead of queuing — time-share alone cannot see it).
    registry:
        Optional shared MetricsRegistry; the ``sparknet_req_*`` series
        register on it (the serve plane passes its /metrics registry).
    export_every:
        Completions between gauge/instant verdict exports.
    """

    def __init__(
        self,
        *,
        window: int = 256,
        skew_threshold: float = 1.5,
        skew_floor_s: float = 0.02,
        kv_shed_threshold: float = 0.05,
        registry: Optional[MetricsRegistry] = None,
        export_every: int = 8,
    ):
        self.skew_threshold = float(skew_threshold)
        self.skew_floor_s = float(skew_floor_s)
        self.kv_shed_threshold = float(kv_shed_threshold)
        self.export_every = max(1, int(export_every))
        self._lock = threading.Lock()
        # rid -> accumulating record (bounded: a leaked stream must not
        # grow this forever — oldest half evicted at the bound)
        self._live: Dict[str, dict] = {}
        # rid -> finalized record still accepting late stream_write
        # folds (the terminal event's write lands after the request
        # span closes; the deque holds the same dict, so late folds
        # still show in the table)
        self._recent: Dict[str, dict] = {}
        self._done: deque = deque(maxlen=int(window))
        # per-stage rolling duration windows (seconds, unsorted)
        self._stage_win: Dict[str, deque] = {
            s: deque(maxlen=int(window) * 4) for s in REQUEST_STAGES
        }
        self._stage_win["request"] = deque(maxlen=int(window) * 4)
        # recent shed causes (windowed verdict input) + lifetime counts
        self._shed_win: deque = deque(maxlen=int(window))
        self.sheds: Dict[str, int] = {}
        self.requests_profiled = 0
        self._since_export = 0

        self._m_stage = None
        self._m_bound = None
        self._m_skew = None
        self._m_slow = None
        self._m_completed = None
        if registry is not None:
            m = registry
            self._m_stage = m.histogram(
                "sparknet_req_stage_seconds",
                "per-request stage latency folded live by the request "
                "profiler (queue_wait/kv_reserve/prefill/decode/"
                "stream_write)",
                labels=("stage",),
            )
            self._m_bound = m.gauge(
                "sparknet_req_bound_stage",
                "the window verdict's binding stage (0 idle, 1 queue, "
                "2 kv, 3 prefill, 4 decode, 5 write)",
            )
            self._m_skew = m.gauge(
                "sparknet_req_replica_skew",
                "max/median mean-request-time ratio across replicas in "
                "the window",
            )
            self._m_slow = m.gauge(
                "sparknet_req_slow_replica",
                "replica index named slow by the window verdict (-1 "
                "none)",
            )
            self._m_completed = m.counter(
                "sparknet_req_completed_total",
                "requests finalized by the request profiler",
            )

    # ------------------------------------------------------------------
    # span stream (installed via trace.set_span_observer; the offline
    # report replays run-log records through this same entry point)
    def on_span(self, name, cat, t0, t1, thread, args) -> None:
        if cat == "req":
            if name == "request":
                self._finalize(t0, t1, args or {})
                return
            if name not in ("queue_wait", "kv_reserve", "stream_write"):
                return
            dur = t1 - t0
            a = args or {}
            rid = a.get("req")
            with self._lock:
                self._stage_win[name].append(dur)
                if rid is not None:
                    rec = self._rec(rid)
                    rec["stages"][name] = (
                        rec["stages"].get(name, 0.0) + dur
                    )
                    if name == "queue_wait":
                        rec["t_submit"] = t0
                        if a.get("replica") is not None:
                            rec["replica"] = int(a["replica"])
                    elif name == "stream_write":
                        rec["writes"] += 1
            if self._m_stage is not None:
                self._m_stage.labels(name).observe(dur)
            return
        if cat != "gen":
            return
        dur = t1 - t0
        a = args or {}
        if name == "prefill":
            rid = a.get("req")
            with self._lock:
                self._stage_win["prefill"].append(dur)
                if rid is not None:
                    rec = self._rec(rid)
                    rec["stages"]["prefill"] = (
                        rec["stages"].get("prefill", 0.0) + dur
                    )
                    rec["t_first"] = t1
            if self._m_stage is not None:
                self._m_stage.labels("prefill").observe(dur)
        elif name == "decode_step":
            reqs = a.get("reqs") or ()
            with self._lock:
                self._stage_win["decode"].append(dur)
                for rid in reqs:
                    rec = self._rec(rid)
                    rec["stages"]["decode"] = (
                        rec["stages"].get("decode", 0.0) + dur
                    )
                    rec["decode_steps"] += 1
            if self._m_stage is not None:
                self._m_stage.labels("decode").observe(dur)

    def on_shed(self, cause: str) -> None:
        """One admission refusal (the shared folding entry — live via
        ``note_shed``, offline via the report's instant replay)."""
        cause = str(cause)
        with self._lock:
            self.sheds[cause] = self.sheds.get(cause, 0) + 1
            self._shed_win.append(cause)

    # ------------------------------------------------------------------
    def _rec(self, rid) -> dict:
        """The accumulating record for ``rid`` (caller holds the lock).
        Late spans for an already-finalized request fold into the SAME
        dict the done window holds."""
        rec = self._live.get(rid)
        if rec is None:
            rec = self._recent.get(rid)
        if rec is None:
            if len(self._live) >= 512:
                for k in list(self._live)[:256]:
                    self._live.pop(k, None)
            rec = self._live[rid] = {
                "rid": rid, "stages": {}, "replica": None,
                "t_submit": None, "t_first": None,
                "decode_steps": 0, "writes": 0, "tokens": None,
                "total_s": None, "outcome": None,
            }
        return rec

    def _finalize(self, t0, t1, args: dict) -> None:
        rid = args.get("req")
        if rid is None:
            return
        with self._lock:
            rec = self._live.pop(rid, None)
            if rec is None:
                # a resumed stream (fleet replica death) closes a SECOND
                # lifetime span under the same rid: lifetimes add and
                # the last outcome wins — one request, one row
                rec = self._recent.get(rid)
                if rec is None:
                    return
                rec["total_s"] += t1 - t0
                if args.get("tokens") is not None:
                    rec["tokens"] = int(args["tokens"])
                if args.get("outcome") is not None:
                    rec["outcome"] = str(args["outcome"])
                d = rec["stages"].get("decode", 0.0)
                toks = rec["tokens"] or 0
                rec["tpot_s"] = d / (toks - 1) if toks > 1 else None
                return
            rec["total_s"] = t1 - t0
            if rec["t_submit"] is None:
                rec["t_submit"] = t0
            if args.get("tokens") is not None:
                rec["tokens"] = int(args["tokens"])
            if args.get("outcome") is not None:
                rec["outcome"] = str(args["outcome"])
            if args.get("replica") is not None and rec["replica"] is None:
                rec["replica"] = int(args["replica"])
            if rec["t_first"] is not None and rec["t_submit"] is not None:
                rec["ttft_s"] = max(0.0, rec["t_first"] - rec["t_submit"])
            else:
                rec["ttft_s"] = None
            d = rec["stages"].get("decode", 0.0)
            toks = rec["tokens"] or 0
            rec["tpot_s"] = d / (toks - 1) if toks > 1 else None
            self._done.append(rec)
            if len(self._recent) >= 128:
                for k in list(self._recent)[:64]:
                    self._recent.pop(k, None)
            self._recent[rid] = rec
            self._stage_win["request"].append(rec["total_s"])
            self.requests_profiled += 1
            self._since_export += 1
            do_export = self._since_export >= self.export_every
            if do_export:
                self._since_export = 0
        if self._m_completed is not None:
            self._m_completed.inc()
        if do_export:
            self._export()

    # ------------------------------------------------------------------
    # verdicts
    def _window_verdict(self, recs, shed_win) -> dict:
        """(caller must NOT hold the lock for the export path) — fold
        the done window + recent sheds into the binding-stage verdict."""
        totals = {s: 0.0 for s in REQUEST_STAGES}
        for r in recs:
            for s, v in r["stages"].items():
                if s in totals:
                    totals[s] += v
        kv_sheds = sum(1 for c in shed_win if c == "kv_reserve")
        arrivals = len(recs) + len(shed_win)
        kv_shed_frac = kv_sheds / arrivals if arrivals else 0.0
        if kv_shed_frac >= self.kv_shed_threshold:
            verdict = "kv"
        elif not recs or sum(totals.values()) <= 0:
            verdict = "idle"
        else:
            shares = {
                "queue": totals["queue_wait"],
                "kv": totals["kv_reserve"],
                "prefill": totals["prefill"],
                "decode": totals["decode"],
                "write": totals["stream_write"],
            }
            verdict = max(sorted(shares), key=lambda k: shares[k])
        total = sum(totals.values())
        return {
            "verdict": verdict,
            "kv_shed_frac": round(kv_shed_frac, 4),
            "stage_shares": {
                s: round(v / total, 4) if total > 0 else 0.0
                for s, v in totals.items()
            },
        }

    def _replica_verdict(self, recs) -> dict:
        by_rep: Dict[int, List[float]] = {}
        for r in recs:
            if r["replica"] is not None and r["total_s"] is not None:
                by_rep.setdefault(int(r["replica"]), []).append(
                    r["total_s"]
                )
        if len(by_rep) < 2:
            return {
                "replicas": {
                    str(i): {
                        "requests": len(v),
                        "mean_ms": round(
                            sum(v) / len(v) * 1e3, 3
                        ) if v else 0.0,
                    }
                    for i, v in sorted(by_rep.items())
                },
                "skew": None, "slow_replica": None,
            }
        means = {i: sum(v) / len(v) for i, v in by_rep.items()}
        vals = sorted(means.values())
        # lower median: with an even replica count the upper-median
        # index would BE the slow replica, reading skew as 1.0
        med = vals[(len(vals) - 1) // 2]
        worst = max(means, key=lambda i: means[i])
        mx = means[worst]
        skew = mx / med if med > 0 else float("inf") if mx > 0 else 1.0
        slow = (
            worst
            if skew > self.skew_threshold
            and (mx - med) > self.skew_floor_s
            else None
        )
        return {
            "replicas": {
                str(i): {
                    "requests": len(by_rep[i]),
                    "mean_ms": round(means[i] * 1e3, 3),
                }
                for i in sorted(by_rep)
            },
            "skew": round(skew, 3),
            "slow_replica": slow,
        }

    # ------------------------------------------------------------------
    def summary(self) -> dict:
        """Rolling window percentiles + verdicts (the servetrace bench
        artifact and the offline report read this)."""
        with self._lock:
            recs = list(self._done)
            shed_win = list(self._shed_win)
            stage_win = {
                s: sorted(w) for s, w in self._stage_win.items()
            }
            sheds = dict(self.sheds)
            lifetime = self.requests_profiled
        stages = {}
        for s, vals in stage_win.items():
            stages[s] = {
                "count": len(vals),
                "p50_ms": round(_pct(vals, 0.50) * 1e3, 3),
                "p95_ms": round(_pct(vals, 0.95) * 1e3, 3),
                "p99_ms": round(_pct(vals, 0.99) * 1e3, 3),
                "max_ms": round(vals[-1] * 1e3, 3) if vals else 0.0,
            }
        ttfts = sorted(
            r["ttft_s"] for r in recs if r.get("ttft_s") is not None
        )
        tpots = sorted(
            r["tpot_s"] for r in recs if r.get("tpot_s") is not None
        )
        out = {
            "requests": len(recs),
            "requests_profiled": lifetime,
            "stages": stages,
            "ttft_ms": {
                "p50": round(_pct(ttfts, 0.5) * 1e3, 3),
                "p95": round(_pct(ttfts, 0.95) * 1e3, 3),
                "p99": round(_pct(ttfts, 0.99) * 1e3, 3),
            } if ttfts else None,
            "tpot_ms": {
                "p50": round(_pct(tpots, 0.5) * 1e3, 3),
                "p95": round(_pct(tpots, 0.95) * 1e3, 3),
            } if tpots else None,
            "sheds": sheds,
        }
        out.update(self._window_verdict(recs, shed_win))
        out.update(self._replica_verdict(recs))
        return out

    def requests_table(self, n: int = 10) -> List[dict]:
        """Slowest-``n`` completed requests with their stage breakdown
        and replica attribution — the live source the offline
        ``tools/request_report.py`` table shares."""
        with self._lock:
            recs = [r for r in self._done if r["total_s"] is not None]
        recs.sort(key=lambda r: r["total_s"], reverse=True)
        rows = []
        for r in recs[: max(0, int(n))]:
            rows.append({
                "rid": r["rid"],
                "total_ms": round(r["total_s"] * 1e3, 3),
                "ttft_ms": (
                    round(r["ttft_s"] * 1e3, 3)
                    if r.get("ttft_s") is not None else None
                ),
                "tpot_ms": (
                    round(r["tpot_s"] * 1e3, 3)
                    if r.get("tpot_s") is not None else None
                ),
                "tokens": r["tokens"],
                "replica": r["replica"],
                "outcome": r["outcome"],
                "decode_steps": r["decode_steps"],
                "stages_ms": {
                    s: round(v * 1e3, 3)
                    for s, v in sorted(r["stages"].items())
                },
            })
        return rows

    def state_dict(self) -> dict:
        """The /healthz request-profile block: enough for an
        orchestrator (or ROADMAP item 4's autoscaler) to see the
        binding stage and the slow replica without a trace dump."""
        s = self.summary()
        return {
            "requests_profiled": s["requests_profiled"],
            "window_requests": s["requests"],
            "verdict": s["verdict"],
            "kv_shed_frac": s["kv_shed_frac"],
            "ttft_ms": s["ttft_ms"],
            "tpot_ms": s["tpot_ms"],
            "sheds": s["sheds"],
            "replica_skew": s["skew"],
            "slow_replica": s["slow_replica"],
        }

    # ------------------------------------------------------------------
    def _export(self) -> None:
        """One verdict to the gauges + the run log/flight ring/shipper
        (the ``obs.instant`` fan-out) — GET /fleet reads the gauges per
        host and names the slow replica fleet-wide."""
        s = self.summary()
        if self._m_bound is not None:
            self._m_bound.set(BOUND_CODE.get(s["verdict"], 0))
        if self._m_skew is not None and s["skew"] is not None:
            self._m_skew.set(s["skew"])
        if self._m_slow is not None:
            self._m_slow.set(
                s["slow_replica"] if s["slow_replica"] is not None else -1
            )
        from sparknet_tpu import obs as _obs

        _obs.instant(
            "reqprofile", cat="req",
            verdict=s["verdict"],
            kv_shed_frac=s["kv_shed_frac"],
            requests=s["requests"],
            skew=s["skew"],
            slow_replica=s["slow_replica"],
        )


# ----------------------------------------------------------------------
# module-level install surface (the obs/profile.py pattern: hooks are
# near-free no-ops until a profiler is installed)

_active: Optional[RequestProfiler] = None
_prev_observer = None


def install(profiler: RequestProfiler) -> RequestProfiler:
    """Make ``profiler`` the process's request profiler.  The span
    observer seam holds ONE function, so installing COMPOSES with any
    observer already there (a --profile training run's RoundProfiler
    keeps seeing its spans) and ``uninstall`` restores it."""
    global _active, _prev_observer
    _active = profiler
    _prev_observer = _trace._span_observer
    if _prev_observer is None:
        _trace.set_span_observer(profiler.on_span)
    else:
        prev = _prev_observer

        def _both(name, cat, t0, t1, thread, args):
            prev(name, cat, t0, t1, thread, args)
            profiler.on_span(name, cat, t0, t1, thread, args)

        _trace.set_span_observer(_both)
    return profiler


def uninstall(profiler: Optional[RequestProfiler] = None) -> None:
    global _active, _prev_observer
    if profiler is not None and profiler is not _active:
        return
    _active = None
    _trace.set_span_observer(_prev_observer)
    _prev_observer = None


def active() -> Optional[RequestProfiler]:
    return _active


def state() -> Optional[dict]:
    """The active profiler's /healthz block, or None."""
    p = _active
    if p is None:
        return None
    return p.state_dict()


def note_shed(cause: str, rid: Optional[str] = None,
              replica: Optional[int] = None) -> None:
    """One admission refusal: a ``shed`` instant (run log + flight +
    shipper) tagged with its cause, and the live profiler's window.
    Near-free when nothing is installed."""
    p = _active
    if p is not None:
        p.on_shed(cause)
    args = {"cause": cause}
    if rid is not None:
        args["req"] = rid
    if replica is not None:
        args["replica"] = replica
    _trace.instant("shed", cat="req", **args)
