"""Per-host telemetry shipper — the fleet observability push side.

Every observability surface before this PR was per-process: the metrics
registry, the tracer, the health sentry and the round profiler all see
ONE host.  The reference SparkNet design is driver-centric — the Scala
driver sees the whole fleet every round — and elastic membership
(ROADMAP 1) and serve autoscaling (ROADMAP 3) both need that view.
``Shipper`` is the per-host half: it pushes

- **metric deltas** — counter increments since the last successful push
  (``MetricsRegistry.snapshot()`` + ``counter_deltas()``, reset-safe),
  plus current gauge values;
- **run-log events** — the same span/instant records the flight
  recorder rings (``obs/trace.py`` feeds the shipper exactly like it
  feeds the flight ring), stamped with wall-clock time so the collector
  can merge N hosts' traces onto one clock-aligned timeline;
- **a round heartbeat** — the newest absolute round observed in span
  args, the signal the collector's late/dead attribution consumes;

over HTTP to a ``FleetCollector`` (``obs/fleet.py``).

Degradation contract (the part that keeps training safe):

- shipping runs on its OWN named thread (``obs-shipper``) — a training
  thread never blocks on the network; ``record_event`` is a bounded
  deque append under a lock;
- when the collector is unreachable the push retries under a small
  ``utils/retry`` budget, then the events stay buffered and the loop
  backs off exponentially (capped); counter deltas are not lost either
  — the previous snapshot only advances on a successful push, so the
  next push carries the accumulated delta;
- the buffer is bounded: overflow drops the OLDEST events and counts
  them (``sparknet_ship_dropped_total`` + the payload's
  ``dropped_total``), so a long outage costs bounded memory and an
  honest loss count instead of an OOM.

Test/chaos seams (documented, like the object-store fault hook):
``SPARKNET_SHIP_INTERVAL_S`` overrides the flush cadence and
``SPARKNET_SHIP_CLOCK_SKEW_S`` skews this host's reported wall clock —
the seam ``bench.py --mode=fleet`` uses to prove the collector's clock
alignment recovers a known offset.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.request
import uuid
from collections import deque
from typing import Dict, List, Optional

DEFAULT_INTERVAL_S = 0.5
DEFAULT_CAPACITY = 8192
DEFAULT_MAX_BATCH = 1024
# push attempts within one flush (fail fast, keep buffering); the flush
# loop adds its own exponential inter-flush backoff on top
_PUSH_TIMEOUT_S = 2.0
_BACKOFF_CAP_S = 5.0


def default_host_id() -> str:
    """Stable-enough per-process host identity: the env override first
    (multi-process launchers set it per worker), else host:pid."""
    return os.environ.get(
        "SPARKNET_HOST_ID", f"{socket.gethostname()}:{os.getpid()}"
    )


class Shipper:
    """Pushes this process's metric deltas + run-log events to a fleet
    collector from a dedicated thread.  Construct, ``start()``, and
    ``stop()`` in the run's ``finally`` (stop attempts one final
    flush so a clean shutdown ships its tail)."""

    def __init__(
        self,
        collector_url: str,
        host: Optional[str] = None,
        interval_s: Optional[float] = None,
        capacity: int = DEFAULT_CAPACITY,
        max_batch: int = DEFAULT_MAX_BATCH,
        registry=None,
    ):
        self.url = collector_url.rstrip("/")
        if "://" not in self.url:
            self.url = "http://" + self.url
        self.host = host or default_host_id()
        env_iv = os.environ.get("SPARKNET_SHIP_INTERVAL_S")
        self.interval_s = float(
            interval_s if interval_s is not None
            else (env_iv or DEFAULT_INTERVAL_S)
        )
        # test/bench seam: a skewed host clock (the whole host's wall
        # clock reads shifted) — collector alignment must recover it
        self.clock_skew_s = float(
            os.environ.get("SPARKNET_SHIP_CLOCK_SKEW_S", "0") or 0.0
        )
        self.capacity = int(capacity)
        self.max_batch = int(max_batch)
        self._registry = registry  # None -> the training registry, lazily
        self.boot_id = uuid.uuid4().hex
        self._lock = threading.Lock()
        self._buf: deque = deque()
        self._prev_counters: Dict[str, float] = {}
        self._seq = 0
        self._max_round: Optional[int] = None
        # cumulative shipper-side accounting (also mirrored onto the
        # sparknet_ship_* registry series when metrics are enabled)
        self.events_total = 0
        self.dropped_total = 0
        self.pushes_total = 0
        self.push_failures_total = 0
        self.resets_seen: List[str] = []
        self._stop_evt = threading.Event()
        self._backoff_s = 0.0
        self._drain_deadline: Optional[float] = None
        self._thread = threading.Thread(
            target=self._loop, name="obs-shipper", daemon=True
        )

    # ------------------------------------------------------------------
    # hot-path side: called by the trace layer on training threads
    def record_event(self, rec: Dict) -> None:
        """Buffer one span/instant record (the trace layer's JSONL
        shape).  Bounded, never blocks; the shipper's own thread's
        events are skipped (a push's spans must not feed the next
        push's payload forever)."""
        if threading.current_thread() is self._thread:
            return
        args = rec.get("args")
        r = args.get("round") if isinstance(args, dict) else None
        with self._lock:
            self.events_total += 1
            if isinstance(r, int) and (
                self._max_round is None or r > self._max_round
            ):
                self._max_round = r
            self._buf.append(rec)
            while len(self._buf) > self.capacity:
                self._buf.popleft()
                self.dropped_total += 1

    def note_round(self, r: int) -> None:
        """Explicit round heartbeat (drivers whose spans don't carry
        ``round=`` args can still feed the late/dead attribution)."""
        with self._lock:
            if self._max_round is None or int(r) > self._max_round:
                self._max_round = int(r)

    # ------------------------------------------------------------------
    def start(self) -> "Shipper":
        self._thread.start()
        return self

    def stop(self, flush_timeout_s: float = 5.0) -> None:
        """Signal the ship thread and wait for its final DRAIN: the
        exit path flushes repeatedly (bounded by ``flush_timeout_s``)
        until the buffer is empty — a backlog larger than one batch is
        not silently abandoned on a clean exit — and the last payload
        carries ``final: true``, the terminal heartbeat that tells the
        collector this host FINISHED (it is never later classified
        ``dead`` for going quiet)."""
        self._drain_deadline = time.monotonic() + float(flush_timeout_s)
        self._stop_evt.set()
        self._thread.join(timeout=flush_timeout_s + 1.0)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def buffered(self) -> int:
        with self._lock:
            return len(self._buf)

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s + self._backoff_s):
            ok = self._flush()
            if ok:
                self._backoff_s = 0.0
            else:
                # exponential inter-flush backoff, capped — an
                # unreachable collector must not be hammered at the
                # flush cadence
                self._backoff_s = min(
                    _BACKOFF_CAP_S, max(self.interval_s, self._backoff_s * 2)
                )
        self._drain_tail()  # bounded final drain + terminal heartbeat

    def _drain_tail(self) -> None:
        """Clean-exit drain: flush until the buffer is empty (each
        push moves at most ``max_batch`` events — one final flush used
        to strand a larger backlog) or the stop() deadline passes.
        The LAST push is marked ``final`` so the collector records the
        host as finished instead of letting the dead-after deadline
        condemn a cleanly-exited process."""
        deadline = self._drain_deadline or (time.monotonic() + 5.0)
        while True:
            # the LAST push of the drain is always the final one: when
            # the remaining backlog fits one batch, or when the
            # deadline forces an early exit (a timed-out drain still
            # delivers the terminal heartbeat; only a DOWN collector —
            # a failed push — exits without one, and a down collector
            # could not have received it anyway)
            final = (
                self.buffered() <= self.max_batch
                or time.monotonic() >= deadline
            )
            ok = self._flush(final=final)
            if not ok:
                # collector down: _flush already spent its retry
                # budget — a clean exit must not stall on an outage
                # (whatever remains stays accounted in dropped/lost)
                return
            if final:
                return  # terminal heartbeat delivered

    def _snapshot(self):
        reg = self._registry
        if reg is None:
            from sparknet_tpu import obs as _obs

            tm = _obs.training_metrics()
            reg = tm.registry if tm is not None else None
        if reg is None:
            return {"counters": {}, "gauges": {}}
        return reg.snapshot()

    def _flush(self, final: bool = False) -> bool:
        """Compose one push from the buffered events + the counter
        delta since the last SUCCESSFUL push; returns success.  On
        failure everything stays buffered (events re-queued, snapshot
        not advanced) so nothing is lost while the collector is down —
        only a buffer overflow drops (and counts) events.  ``final``
        marks the payload as this host's terminal heartbeat."""
        from sparknet_tpu.obs.metrics import counter_deltas
        from sparknet_tpu.utils import retry as _retry

        with self._lock:
            pending = []
            while self._buf and len(pending) < self.max_batch:
                pending.append(self._buf.popleft())
            max_round = self._max_round
            # the accounting the collector's lost-event check consumes:
            # enqueued events MINUS the ones still buffered here (they
            # are neither delivered nor lost yet — a backlog larger
            # than one batch must not read as loss)
            events_total = self.events_total - len(self._buf)
            dropped_total = self.dropped_total
        if self.clock_skew_s:
            # the skewed-clock seam covers the whole host clock: event
            # stamps ship as this host's (skewed) wall time too, so the
            # collector's alignment is what un-skews them (copies —
            # the buffered originals stay true for a failed-push requeue)
            skewed = []
            for rec in pending:
                t = rec.get("t_s")
                if isinstance(t, (int, float)):
                    rec = dict(rec, t_s=t + self.clock_skew_s)
                skewed.append(rec)
            ship_events = skewed
        else:
            ship_events = pending
        snap = self._snapshot()
        deltas, resets = counter_deltas(
            self._prev_counters, snap["counters"]
        )
        payload = {
            "v": 1,
            "host": self.host,
            "boot_id": self.boot_id,
            "seq": self._seq,
            "t_send": time.time() + self.clock_skew_s,
            "round": max_round,
            "counters": deltas,
            "gauges": snap["gauges"],
            "events": ship_events,
            "events_total": events_total,
            "dropped_total": dropped_total,
            "resets": resets,
            "final": bool(final),
        }
        body = json.dumps(payload, default=str).encode("utf-8")
        policy = _retry.RetryPolicy(
            max_attempts=3, base_s=0.05, cap_s=0.5, budget_s=2.0
        )

        def attempt():
            req = urllib.request.Request(
                self.url + "/push", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=_PUSH_TIMEOUT_S) as rsp:
                rsp.read()

        try:
            _retry.retry_call(attempt, policy=policy)
        except Exception:  # noqa: BLE001 — collector down: keep buffering
            with self._lock:
                self.push_failures_total += 1
                # requeue in order; the bound then drops the OLDEST
                self._buf.extendleft(reversed(pending))
                while len(self._buf) > self.capacity:
                    self._buf.popleft()
                    self.dropped_total += 1
            self._mirror_metrics()
            return False
        self._prev_counters = snap["counters"]
        with self._lock:
            self._seq += 1
            self.pushes_total += 1
            if resets:
                self.resets_seen.extend(resets)
        self._mirror_metrics()
        return True

    def _mirror_metrics(self) -> None:
        """Mirror the shipper's own accounting onto the sparknet_ship_*
        series (no-op until training metrics are enabled).  Counters are
        monotonic: set via inc-by-difference."""
        from sparknet_tpu import obs as _obs

        tm = _obs.training_metrics()
        if tm is None:
            return
        for counter, value in (
            (tm.ship_events, self.events_total),
            (tm.ship_dropped, self.dropped_total),
            (tm.ship_pushes, self.pushes_total),
            (tm.ship_push_failures, self.push_failures_total),
        ):
            d = value - counter.value
            if d > 0:
                counter.inc(d)
