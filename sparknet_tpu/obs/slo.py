"""Declarative SLOs + multi-window burn-rate alerting over the TSDB.

An ``SLO`` names an objective over series the fleet already ships:

- **availability** — non-shed fraction: bad = the
  ``sparknet_gen_streams_shed_total{cause=...}`` family's windowed
  increase, total = admitted streams plus the sheds (a refused stream
  never reached the admitted counter);
- **latency** — a TTFT/TPOT/stage threshold evaluated from the shipped
  histogram *bucket* counters: the windowed increase of the
  ``le >= threshold`` bucket is the good count (the threshold snaps to
  the next bucket boundary — rollup semantics, disclosed in the row),
  falling back to a windowed-mean test when no buckets shipped;
- **round_time / straggler-free** — the train-side objectives over
  ``sparknet_rounds_total`` / ``sparknet_straggler_rounds_total``.

Evaluation is the classic multi-window multi-burn-rate discipline
(Google SRE workbook): burn rate = (bad fraction over window) /
(1 - target); the default policy pages at **14.4x over 5 m AND 1 h**
and warns at **1x over 6 h**.  Requiring the long window keeps a blip
from paging; requiring the short one makes the page reset quickly once
the burn stops.

Alert transitions are emitted four ways at once: a run-log instant
(``slo_alert``, cat ``slo`` — flight-ring entries ride the same trace
stream), the ``sparknet_slo_*`` metric families, the ``/slo`` JSON
view, and a ``/healthz`` block (``obs.slo_state()``).  Pages
additionally trigger a flight-recorder postmortem dump when one is
armed.

``signals()`` is the scaling-signal API — ``GET /signals`` returns the
exact decision inputs ROADMAP item 4's autoscaler consumes
(admission-pressure trend, queue-depth slope, p99 trend, per-host
round-rate, error-budget remaining), each derived from the same TSDB
series ``/query`` serves, so a controller can audit any input it acts
on.

``tools/slo_report.py`` replays run logs through THIS evaluator —
offline reports cannot drift from the live ``/slo`` view because they
are the same code.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sparknet_tpu.obs.tsdb import TSDB, bucket_quantile

# the three evaluation windows (seconds): short/mid gate the page rule,
# long carries the warn rule and the error-budget ledger
WINDOW_SHORT_S = 300.0
WINDOW_MID_S = 3600.0
WINDOW_LONG_S = 21600.0


@dataclass(frozen=True)
class BurnRule:
    """One alerting rule: fire ``severity`` when the burn rate meets
    ``burn`` over EVERY window in ``windows``."""

    severity: str  # "page" | "warn"
    burn: float
    windows: Tuple[float, ...]


DEFAULT_POLICY: Tuple[BurnRule, ...] = (
    BurnRule("page", 14.4, (WINDOW_SHORT_S, WINDOW_MID_S)),
    BurnRule("warn", 1.0, (WINDOW_LONG_S,)),
)

_SEVERITY_RANK = {"no_data": -1, "ok": 0, "warn": 1, "page": 2}
_STATUS_GAUGE = {"no_data": -1.0, "ok": 0.0, "warn": 1.0, "page": 2.0}


def window_label(w: float) -> str:
    w = int(w)
    if w % 3600 == 0:
        return "%dh" % (w // 3600)
    if w % 60 == 0:
        return "%dm" % (w // 60)
    return "%ds" % w


class SLO:
    """One declarative objective; ``indicator`` returns the windowed
    ``(bad, total)`` event counts, or None when no events moved."""

    def __init__(
        self,
        name: str,
        kind: str,
        target: float,
        description: str = "",
        bad_series: Optional[str] = None,
        bad_is_prefix: bool = False,
        total_series: Optional[str] = None,
        bad_outside_total: bool = False,
        hist: Optional[str] = None,
        threshold_s: Optional[float] = None,
        rounds_series: Optional[str] = None,
    ):
        if kind not in ("availability", "latency", "round_time"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.description = description
        self.bad_series = bad_series
        self.bad_is_prefix = bad_is_prefix
        self.total_series = total_series
        self.bad_outside_total = bad_outside_total
        self.hist = hist
        self.threshold_s = threshold_s
        self.rounds_series = rounds_series

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    # ------------------------------------------------------------------
    @classmethod
    def availability(cls, name, target, bad, total, description="",
                     bad_is_prefix=False, bad_outside_total=True):
        """``bad_outside_total=True`` when the bad counter's events are
        NOT included in the total counter (a shed stream never reached
        the admitted total); False when they are (a straggler round IS
        a round)."""
        return cls(
            name, "availability", target, description,
            bad_series=bad, bad_is_prefix=bad_is_prefix,
            total_series=total, bad_outside_total=bad_outside_total,
        )

    @classmethod
    def latency(cls, name, target, hist, threshold_s, description=""):
        return cls(
            name, "latency", target, description,
            hist=hist, threshold_s=float(threshold_s),
        )

    @classmethod
    def round_time(cls, name, target, rounds, threshold_s,
                   description=""):
        return cls(
            name, "round_time", target, description,
            rounds_series=rounds, threshold_s=float(threshold_s),
        )

    # ------------------------------------------------------------------
    def indicator(
        self, tsdb: TSDB, window_s: float, now: float,
        host: Optional[str] = None,
    ) -> Optional[Tuple[float, float]]:
        if self.kind == "availability":
            if self.bad_is_prefix:
                bad, _ = tsdb.window_delta_prefix(
                    self.bad_series, window_s, now, host=host
                )
            else:
                bad, _ = tsdb.window_delta(
                    self.bad_series, window_s, now, host=host
                )
            total, _ = tsdb.window_delta(
                self.total_series, window_s, now, host=host
            )
            if self.bad_outside_total:
                total += bad
            return (bad, total) if total > 0 else None
        if self.kind == "latency":
            hw = tsdb.histogram_window(self.hist, window_s, now, host=host)
            if hw is None:
                return None
            total = hw["count"]
            les = hw["le"]
            if les:
                good = 0.0
                for le, inc in les:
                    if le >= self.threshold_s - 1e-12:
                        good = inc
                        break
                return (max(0.0, total - good), total)
            # no bucket series shipped: windowed-mean fallback (the
            # whole window is good or bad as one event batch)
            mean = hw["sum"] / total
            return (total if mean > self.threshold_s else 0.0, total)
        # round_time: seconds-per-round over the covered span.  A
        # single round in the window is unjudgeable — its "span" is
        # whatever rollup-bucket granularity it landed in, not a
        # measured cadence — so cold starts report no_data instead of
        # a spurious first-eval alert.
        delta, span = tsdb.window_delta(
            self.rounds_series, window_s, now, host=host
        )
        if delta < 2 or span <= 0:
            return None
        rt = span / delta
        return (delta if rt > self.threshold_s else 0.0, delta)


def default_slos(
    ttft_threshold_s: float = 0.5,
    tpot_threshold_s: float = 0.05,
    round_time_threshold_s: float = 30.0,
) -> List[SLO]:
    """The stock objective set over series the stack already emits."""
    return [
        SLO.availability(
            "serve-availability", 0.999,
            bad="sparknet_gen_streams_shed_total{",
            bad_is_prefix=True,
            total="sparknet_gen_streams_total",
            bad_outside_total=True,
            description="non-shed fraction of arriving generation "
            "streams (sheds by any cause count against the budget)",
        ),
        SLO.latency(
            "serve-ttft-p99", 0.99,
            hist="sparknet_gen_ttft_seconds",
            threshold_s=ttft_threshold_s,
            description="fraction of streams whose submit->first-token "
            "latency beat the threshold",
        ),
        SLO.latency(
            "serve-tpot-p99", 0.99,
            hist="sparknet_gen_intertoken_seconds",
            threshold_s=tpot_threshold_s,
            description="fraction of decode steps whose inter-token "
            "gap beat the threshold",
        ),
        SLO.round_time(
            "train-round-time", 0.99,
            rounds="sparknet_rounds_total",
            threshold_s=round_time_threshold_s,
            description="rounds completing under the per-round "
            "wall-clock threshold (windowed seconds-per-round)",
        ),
        SLO.availability(
            "train-straggler-free", 0.9,
            bad="sparknet_straggler_rounds_total",
            total="sparknet_rounds_total",
            bad_outside_total=False,
            description="fraction of rounds without a straggler "
            "verdict",
        ),
    ]


class SLOEvaluator:
    """Evaluates the objective set over the TSDB, remembers alert
    transitions, exports the metric families, and serves the
    ``/slo`` + ``/signals`` payloads."""

    def __init__(
        self,
        tsdb: TSDB,
        slos: Optional[List[SLO]] = None,
        registry=None,
        policy: Tuple[BurnRule, ...] = DEFAULT_POLICY,
        eval_interval_s: float = 15.0,
        host: Optional[str] = None,
        live_registry=None,
        signal_window_s: float = WINDOW_SHORT_S,
    ):
        self.tsdb = tsdb
        self.slos = list(default_slos() if slos is None else slos)
        self.policy = tuple(
            sorted(policy, key=lambda r: -_SEVERITY_RANK[r.severity])
        )
        self.eval_interval_s = float(eval_interval_s)
        self.host = host
        self.live_registry = live_registry
        self.signal_window_s = float(signal_window_s)
        self.alerts: deque = deque(maxlen=256)
        self._status: Dict[str, str] = {}
        self._eval_lock = threading.Lock()
        self._last_eval_t: Optional[float] = None
        self._last_payload: Optional[Dict] = None
        self._windows = tuple(sorted({
            w for rule in self.policy for w in rule.windows
        }))
        self._m_burn = self._m_budget = None
        self._m_status = self._m_alerts = None
        self._sig_pressure = self._sig_qslope = None
        self._sig_p99trend = self._sig_roundrate = None
        self._sig_budget_min = None
        if registry is not None:
            r = registry
            self._m_burn = r.get("sparknet_slo_burn_rate") or r.gauge(
                "sparknet_slo_burn_rate",
                "error-budget burn rate per objective and window "
                "(1.0 = burning exactly the budget; the page rule "
                "fires at 14.4x over the short AND mid windows)",
                labels=("slo", "window"),
            )
            self._m_budget = (
                r.get("sparknet_slo_error_budget_remaining") or r.gauge(
                    "sparknet_slo_error_budget_remaining",
                    "fraction of the error budget left over the long "
                    "window (1.0 = untouched, 0.0 = exhausted)",
                    labels=("slo",),
                )
            )
            self._m_status = r.get("sparknet_slo_status") or r.gauge(
                "sparknet_slo_status",
                "objective state (-1 no data, 0 ok, 1 warn, 2 page)",
                labels=("slo",),
            )
            self._m_alerts = (
                r.get("sparknet_slo_alerts_total") or r.counter(
                    "sparknet_slo_alerts_total",
                    "alert transitions by objective and severity "
                    "(page/warn on entry, recover on return to ok)",
                    labels=("slo", "severity"),
                )
            )
            self._sig_pressure = (
                r.get("sparknet_signal_admission_pressure") or r.gauge(
                    "sparknet_signal_admission_pressure",
                    "fraction of arriving streams refused at admission "
                    "over the signal window (sheds / arrivals)",
                )
            )
            self._sig_qslope = (
                r.get("sparknet_signal_queue_depth_slope") or r.gauge(
                    "sparknet_signal_queue_depth_slope",
                    "least-squares slope of the serve queue-depth "
                    "gauge over the signal window (streams per second)",
                )
            )
            self._sig_p99trend = (
                r.get("sparknet_signal_p99_trend") or r.gauge(
                    "sparknet_signal_p99_trend",
                    "windowed TTFT p99 vs the preceding window "
                    "(1.0 = flat, >1 = degrading)",
                )
            )
            self._sig_roundrate = (
                r.get("sparknet_signal_round_rate") or r.gauge(
                    "sparknet_signal_round_rate",
                    "per-host training rounds per second over the "
                    "signal window",
                    labels=("host",),
                )
            )
            self._sig_budget_min = (
                r.get("sparknet_signal_error_budget_min") or r.gauge(
                    "sparknet_signal_error_budget_min",
                    "smallest error-budget-remaining fraction across "
                    "the objective set (the autoscaler's caution "
                    "input)",
                )
            )

    # ------------------------------------------------------------------
    def maybe_evaluate(self, now: Optional[float] = None) -> Optional[Dict]:
        """Rate-limited ``evaluate`` — the per-push hook."""
        now = time.time() if now is None else float(now)
        if (
            self._last_eval_t is not None
            and now - self._last_eval_t < self.eval_interval_s
        ):
            return None
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> Dict:
        """One full evaluation pass: burn rates per window, policy
        fold, alert transitions, metric export.  Returns the ``/slo``
        payload."""
        now = time.time() if now is None else float(now)
        with self._eval_lock:
            self._last_eval_t = (
                now if self._last_eval_t is None
                else max(self._last_eval_t, now)
            )
            rows = []
            for slo in self.slos:
                rows.append(self._evaluate_one(slo, now))
            payload = {
                "t": now,
                "host": self.host or "fleet",
                "policy": [
                    {
                        "severity": r.severity,
                        "burn": r.burn,
                        "windows": [window_label(w) for w in r.windows],
                    }
                    for r in self.policy
                ],
                "slos": rows,
                "alerts": list(self.alerts)[-32:],
            }
            self._last_payload = payload
            return payload

    def _evaluate_one(self, slo: SLO, now: float) -> Dict:
        frac: Dict[float, Optional[float]] = {}
        for w in self._windows:
            ind = slo.indicator(self.tsdb, w, now, host=self.host)
            if ind is None:
                frac[w] = None
            else:
                bad, total = ind
                frac[w] = (bad / total) if total > 0 else None
        burn = {
            w: (None if frac[w] is None else frac[w] / slo.budget)
            for w in self._windows
        }
        status = "ok"
        if all(frac[w] is None for w in self._windows):
            status = "no_data"
        else:
            for rule in self.policy:  # page first (severity-sorted)
                if all(
                    burn[w] is not None and burn[w] >= rule.burn
                    for w in rule.windows
                ):
                    status = rule.severity
                    break
        long_w = self._windows[-1]
        budget_remaining = (
            1.0 if frac[long_w] is None
            else max(0.0, 1.0 - frac[long_w] / slo.budget)
        )
        self._transition(slo, status, burn, now)
        if self._m_burn is not None:
            for w in self._windows:
                self._m_burn.labels(slo.name, window_label(w)).set(
                    burn[w] or 0.0
                )
            self._m_budget.labels(slo.name).set(budget_remaining)
            self._m_status.labels(slo.name).set(_STATUS_GAUGE[status])
        row = {
            "name": slo.name,
            "kind": slo.kind,
            "target": slo.target,
            "description": slo.description,
            "status": status,
            "budget_remaining": round(budget_remaining, 6),
            "windows": {
                window_label(w): {
                    "bad_frac": (
                        None if frac[w] is None else round(frac[w], 6)
                    ),
                    "burn": (
                        None if burn[w] is None else round(burn[w], 3)
                    ),
                }
                for w in self._windows
            },
        }
        if slo.threshold_s is not None:
            row["threshold_s"] = slo.threshold_s
        return row

    def _transition(self, slo: SLO, status: str, burn, now: float) -> None:
        prev = self._status.get(slo.name, "ok")
        self._status[slo.name] = status
        eff_prev = "ok" if prev == "no_data" else prev
        eff = "ok" if status == "no_data" else status
        if eff == eff_prev:
            return
        severity = eff if eff in ("warn", "page") else "recover"
        rec = {
            "t": round(now, 3),
            "slo": slo.name,
            "severity": severity,
            "from": eff_prev,
            "to": eff,
            "burn": {
                window_label(w): (None if b is None else round(b, 3))
                for w, b in burn.items()
            },
        }
        self.alerts.append(rec)
        if self._m_alerts is not None:
            self._m_alerts.labels(slo.name, severity).inc()
        from sparknet_tpu.obs import trace as _trace

        _trace.instant(
            "slo_alert", cat="slo", slo=slo.name, severity=severity,
            prev=eff_prev, burn=rec["burn"],
        )
        if severity == "page":
            from sparknet_tpu.obs import flight as _flight

            _flight.dump_if_active(
                "slo_page", extra={"slo": slo.name, "burn": rec["burn"]}
            )

    # ------------------------------------------------------------------
    def state(self) -> Dict:
        """The compact /healthz block (statuses + recent alerts)."""
        statuses = dict(self._status) or {
            s.name: "no_data" for s in self.slos
        }
        worst = max(
            statuses.values(),
            key=lambda s: _SEVERITY_RANK[s],
            default="no_data",
        )
        return {
            "status": worst,
            "slos": statuses,
            "alerts": list(self.alerts)[-5:],
            "evaluated_t": self._last_eval_t,
        }

    # ------------------------------------------------------------------
    def signals(self, now: Optional[float] = None) -> Dict:
        """The scaling-signal payload (``GET /signals``): every value
        is derived from TSDB series ``/query`` also serves, so a
        consumer can audit any input."""
        now = self._last_eval_t if now is None else float(now)
        if now is None:
            now = time.time()
        w = self.signal_window_s
        host = self.host
        tsdb = self.tsdb
        shed, _ = tsdb.window_delta_prefix(
            "sparknet_gen_streams_shed_total{", w, now, host=host
        )
        admitted, _ = tsdb.window_delta(
            "sparknet_gen_streams_total", w, now, host=host
        )
        arrivals = admitted + shed
        pressure = shed / arrivals if arrivals > 0 else 0.0
        shed_p, _ = tsdb.window_delta_prefix(
            "sparknet_gen_streams_shed_total{", w, now - w, host=host
        )
        adm_p, _ = tsdb.window_delta(
            "sparknet_gen_streams_total", w, now - w, host=host
        )
        arr_p = adm_p + shed_p
        pressure_prev = shed_p / arr_p if arr_p > 0 else 0.0
        queue_series = "sparknet_gen_active_streams"
        if queue_series not in self.tsdb.series_names(queue_series):
            queue_series = "sparknet_feed_queue_depth"
        qslope = tsdb.slope_per_s(queue_series, w, now, host=host)
        p99 = p99_prev = 0.0
        hw = tsdb.histogram_window(
            "sparknet_gen_ttft_seconds", w, now, host=host
        )
        if hw is not None:
            p99 = bucket_quantile(hw["le"], 0.99)
        hw_p = tsdb.histogram_window(
            "sparknet_gen_ttft_seconds", w, now - w, host=host
        )
        if hw_p is not None:
            p99_prev = bucket_quantile(hw_p["le"], 0.99)
        p99_trend = (p99 / p99_prev) if p99_prev > 0 else (
            0.0 if p99 == 0 else 1.0
        )
        p99_live = None
        if self.live_registry is not None:
            h = self.live_registry.get("sparknet_gen_ttft_seconds")
            if h is not None and hasattr(h, "window_quantile"):
                p99_live = h.window_quantile(0.99, window_s=w)
        round_rate: Dict[str, float] = {}
        for h in tsdb.hosts():
            delta, span = tsdb.window_delta(
                "sparknet_rounds_total", w, now, host=h
            )
            if span > 0:
                round_rate[h] = round(delta / span, 6)
        budgets: Dict[str, float] = {}
        long_w = self._windows[-1]
        for slo in self.slos:
            ind = slo.indicator(self.tsdb, long_w, now, host=host)
            if ind is None:
                budgets[slo.name] = 1.0
            else:
                bad, total = ind
                f = bad / total if total > 0 else 0.0
                budgets[slo.name] = round(
                    max(0.0, 1.0 - f / slo.budget), 6
                )
        budget_min = min(budgets.values()) if budgets else 1.0
        if self._sig_pressure is not None:
            self._sig_pressure.set(pressure)
            self._sig_qslope.set(qslope)
            self._sig_p99trend.set(p99_trend)
            for h, rr in round_rate.items():
                self._sig_roundrate.labels(h).set(rr)
            self._sig_budget_min.set(budget_min)
        out = {
            "t": now,
            "window_s": w,
            "admission_pressure": round(pressure, 6),
            "admission_pressure_trend": round(
                pressure - pressure_prev, 6
            ),
            "queue_depth_series": queue_series,
            "queue_depth_slope_per_s": round(qslope, 6),
            "ttft_p99_s": round(p99, 6),
            "ttft_p99_trend": round(p99_trend, 4),
            "round_rate_per_s": round_rate,
            "error_budget_remaining": budgets,
            "error_budget_min": round(budget_min, 6),
        }
        if p99_live is not None:
            out["ttft_p99_live_s"] = round(p99_live, 6)
        return out


class TsdbSampler:
    """Single-host retention loop: snapshots the process registry into
    the TSDB every interval and runs the SLO evaluator — the piece
    that gives a ``--slo`` run without a fleet collector the same
    ``/query`` + ``/slo`` surface."""

    def __init__(
        self,
        tsdb: TSDB,
        registry,
        evaluator: Optional[SLOEvaluator] = None,
        host: str = "local",
        interval_s: float = 1.0,
    ):
        self.tsdb = tsdb
        self.registry = registry
        self.evaluator = evaluator
        self.host = host
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_error: Optional[BaseException] = None

    def sample_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        snap = self.registry.snapshot()
        self.tsdb.record_snapshot(
            self.host, snap["counters"], snap["gauges"], now
        )
        if self.evaluator is not None:
            self.evaluator.maybe_evaluate(now)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:  # noqa: BLE001 — telemetry must not die
                self.last_error = e

    def start(self) -> "TsdbSampler":
        self._thread = threading.Thread(
            target=self._run, name="obs-tsdb-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        # one final sample so short runs land their tail
        try:
            self.sample_once()
        except Exception as e:  # noqa: BLE001 — teardown must not die
            self.last_error = e
