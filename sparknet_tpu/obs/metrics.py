"""Framework-wide metrics: counters/gauges/histograms + Prometheus text.

Promoted from ``serve/metrics.py`` (round 6) to the shared observability
layer: training, the data plane, and serving all register series on ONE
``MetricsRegistry`` shape, and ``obs/exporter.py`` gives any run a
``/metrics`` endpoint.  ``serve.metrics`` remains a thin re-export so
nothing in the serving stack changed call sites.

Stdlib-only (no prometheus_client in the image): each metric is a small
lock-guarded accumulator, and ``MetricsRegistry.render()`` emits the
Prometheus text exposition format (``# HELP``/``# TYPE`` + samples).
Histograms keep cumulative buckets (the Prometheus ``le`` convention)
plus a bounded reservoir of recent observations so p50/p95/p99 can be
reported without a scrape-side quantile engine.

Labels: ``registry.histogram(name, labels=("phase",))`` returns a
FAMILY; ``family.labels("execute")`` returns the child instrument for
that label value (created once, cached).  Per-phase latency is one
histogram family — ``sparknet_phase_latency_seconds{phase="h2d"}`` —
not N ad-hoc instruments.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

# default latency buckets (seconds): 1 ms .. 30 s, roughly log-spaced
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers print bare, floats as repr."""
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    """Monotonic counter (``requests_total`` style)."""

    TYPE = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self.labelstr = ""  # e.g. 'phase="execute"' for family children
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, float]]:
        name = (
            f"{self.name}{{{self.labelstr}}}" if self.labelstr else self.name
        )
        return [(name, self.value)]


class Gauge:
    """Set-to-current-value metric (``queue_depth`` style); ``fn`` makes
    it a callback gauge sampled at render time."""

    TYPE = "gauge"

    def __init__(self, name: str, help: str = "", fn=None):
        self.name, self.help = name, help
        self.labelstr = ""
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, float]]:
        name = (
            f"{self.name}{{{self.labelstr}}}" if self.labelstr else self.name
        )
        return [(name, self.value)]


class Histogram:
    """Cumulative-bucket histogram + bounded reservoir for quantiles.

    The reservoir is a ring of the last ``reservoir`` observations —
    quantiles are over the recent window, which is what a serving
    dashboard wants (steady-state p99, not cold-start-polluted
    all-time p99).

    ``window_quantile`` narrows further to a TIME window: observations
    also enter a timestamped ring (same ``reservoir`` bound), and the
    quantile is taken over only the last ``window_s`` seconds — a long
    run's p99 stops diluting a fresh regression (the count-bounded
    reservoir of a month-old server still remembers last week).  The
    SLO latency evaluator (``obs/slo.py``) reads this view.
    """

    TYPE = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
        reservoir: int = 4096,
    ):
        self.name, self.help = name, help
        self.labelstr = ""
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._count = 0
        self._ring: List[float] = []
        self._ring_cap = int(reservoir)
        self._ring_pos = 0
        # (t_mono, v) pairs for the sliding TIME window; maxlen shares
        # the reservoir bound so memory stays fixed either way
        self._timed: deque = deque(maxlen=self._ring_cap)
        # sorted view of the ring, built lazily on the first quantile
        # read and kept until the next observation — a scrape reading
        # p50/p95/p99 sorts ONCE, not once per quantile
        self._sorted: Optional[List[float]] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for i, le in enumerate(self.buckets):
                if v <= le:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if len(self._ring) < self._ring_cap:
                self._ring.append(v)
            else:
                self._ring[self._ring_pos] = v
                self._ring_pos = (self._ring_pos + 1) % self._ring_cap
            self._timed.append((time.monotonic(), v))
            self._sorted = None  # invalidate the cached sorted view

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """q in [0, 1] over the recent-observation reservoir (0.0 when
        empty); nearest-rank on the sorted window.  The sort happens at
        most once per observation batch: consecutive quantile reads
        (p50/p95/p99 in one scrape) share the cached sorted view, which
        ``observe`` invalidates."""
        with self._lock:
            if self._sorted is None:
                self._sorted = sorted(self._ring)
            window = self._sorted  # replaced, never mutated, on observe
        if not window:
            return 0.0
        idx = min(len(window) - 1, max(0, int(q * len(window))))
        return window[idx]

    def window_quantile(
        self, q: float, window_s: float = 60.0,
        now: Optional[float] = None,
    ) -> float:
        """Nearest-rank quantile over only the observations of the
        last ``window_s`` seconds (0.0 when none) — the sliding-window
        view the SLO latency evaluator reads.  ``now`` overrides the
        clock for tests; observations older than the window are
        dropped from the timed ring on the way."""
        now = time.monotonic() if now is None else now
        cutoff = now - float(window_s)
        with self._lock:
            while self._timed and self._timed[0][0] < cutoff:
                self._timed.popleft()
            vals = sorted(v for _t, v in self._timed)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, int(q * len(vals))))
        return vals[idx]

    def window_count(self, window_s: float = 60.0,
                     now: Optional[float] = None) -> int:
        """Observations inside the sliding time window."""
        now = time.monotonic() if now is None else now
        cutoff = now - float(window_s)
        with self._lock:
            while self._timed and self._timed[0][0] < cutoff:
                self._timed.popleft()
            return len(self._timed)

    def samples(self) -> List[Tuple[str, float]]:
        with self._lock:
            counts, total, s = list(self._counts), self._count, self._sum
        pre = f"{self.labelstr}," if self.labelstr else ""
        suf = f"{{{self.labelstr}}}" if self.labelstr else ""
        out: List[Tuple[str, float]] = []
        cum = 0
        for le, c in zip(self.buckets, counts):
            cum += c
            out.append((f'{self.name}_bucket{{{pre}le="{_fmt(le)}"}}', cum))
        out.append((f'{self.name}_bucket{{{pre}le="+Inf"}}', total))
        out.append((f"{self.name}_sum{suf}", s))
        out.append((f"{self.name}_count{suf}", total))
        return out


class MetricFamily:
    """A labeled family of one instrument class: ``labels(v1, ...)``
    returns the child instrument for that label-value tuple (created on
    first use, cached).  Renders as ONE ``# TYPE`` block whose samples
    carry the label set — the Prometheus family convention."""

    def __init__(self, cls, name: str, help: str,
                 label_names: Sequence[str], **kwargs):
        if not label_names:
            raise ValueError("a MetricFamily needs at least one label name")
        self._cls = cls
        self.TYPE = cls.TYPE
        self.name, self.help = name, help
        self._label_names = tuple(label_names)
        self._kwargs = kwargs
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values):
        key = tuple(str(v) for v in values)
        if len(key) != len(self._label_names):
            raise ValueError(
                f"{self.name}: expected labels {self._label_names}, "
                f"got {len(key)} value(s)"
            )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._cls(self.name, self.help, **self._kwargs)
                child.labelstr = ",".join(
                    f'{n}="{_escape_label(v)}"'
                    for n, v in zip(self._label_names, key)
                )
                self._children[key] = child
        return child

    def children(self) -> List[object]:
        with self._lock:
            return list(self._children.values())

    def samples(self) -> List[Tuple[str, float]]:
        out: List[Tuple[str, float]] = []
        for child in self.children():
            out.extend(child.samples())
        return out


class MetricsRegistry:
    """Holds the process's metrics and renders the /metrics payload."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name!r}")
            self._metrics[metric.name] = metric
        return metric

    def counter(
        self, name: str, help: str = "",
        labels: Optional[Sequence[str]] = None,
    ):
        if labels:
            return self.register(MetricFamily(Counter, name, help, labels))
        return self.register(Counter(name, help))

    def gauge(
        self, name: str, help: str = "", fn=None,
        labels: Optional[Sequence[str]] = None,
    ):
        if labels:
            if fn is not None:
                # one shared callback cannot distinguish children, so
                # the combination would render dead 0-valued samples —
                # fail loudly; labeled gauges use set()/inc() per child
                raise ValueError(
                    f"{name}: callback gauges cannot be labeled — "
                    "use set() on labels(...) children instead"
                )
            return self.register(MetricFamily(Gauge, name, help, labels))
        return self.register(Gauge(name, help, fn=fn))

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
        labels: Optional[Sequence[str]] = None,
    ):
        if labels:
            return self.register(
                MetricFamily(Histogram, name, help, labels, buckets=buckets)
            )
        return self.register(Histogram(name, help, buckets=buckets))

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """One point-in-time read of every sample, keyed by the full
        sample name (labels inline — ``m{kind="x"}`` — so label
        families survive the round trip): ``{"counters": {...},
        "gauges": {...}}``.  Histogram samples (buckets/sum/count) are
        cumulative and fold under ``counters``.  This is the shipper's
        read side (``obs/ship.py``): two snapshots + ``counter_deltas``
        give the increment to push."""
        with self._lock:
            metrics = list(self._metrics.values())
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        for m in metrics:
            target = counters if m.TYPE in ("counter", "histogram") else gauges
            for name, value in m.samples():
                target[name] = float(value)
        return {"counters": counters, "gauges": gauges}

    def render(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            for sample_name, value in m.samples():
                lines.append(f"{sample_name} {_fmt(value)}")
        return "\n".join(lines) + "\n"


def counter_deltas(
    prev: Dict[str, float], cur: Dict[str, float]
) -> Tuple[Dict[str, float], List[str]]:
    """Monotonic-counter deltas between two ``snapshot()["counters"]``
    reads, with Prometheus counter-reset semantics: a sample whose
    value DROPPED restarted from zero (process restart, fresh
    registry), so the new value IS the increment — history is never
    un-counted.  Returns ``(deltas, reset_sample_names)``; zero deltas
    are omitted (a quiet fleet ships empty payloads, not every name
    every push).  Samples present in ``prev`` but missing from ``cur``
    are ignored (a swapped registry's old families just stop
    shipping)."""
    deltas: Dict[str, float] = {}
    resets: List[str] = []
    for name, value in cur.items():
        before = prev.get(name, 0.0)
        if value < before:
            resets.append(name)
            d = value
        else:
            d = value - before
        if d:
            deltas[name] = d
    return deltas, resets
