"""Training-health sentry: in-graph numerics audit + divergence policy.

The parameter-averaging loop has a failure mode the round-span/metrics
layer can see but not diagnose: one worker's diverging local SGD
(NaN/Inf grads, loss spike) silently poisons the ``psum`` average for
every worker, and the only record afterward is a flat loss curve.  This
module closes the loop — detect, record, recover:

- **audit** (pure jnp, fused into the jitted step): per-iteration global
  grad L2 norm (the same reduction ``clip_gradients`` already pays —
  computed once, shared), per-param-group param norm and update/param
  ratio, and non-finite counts over grads/params/loss.  Enabled by
  ``Solver(audit=True)``; the stats are pure READOUTS — the training
  trajectory is bit-identical with the audit on or off
  (``tests/test_health.py``).
- **in-graph worker masking** (``ParameterAveragingTrainer``): a dp
  worker whose local window produced any non-finite grad/param is
  excluded from that round's average *inside the jitted round* — the
  poison never reaches the ``psum`` — and the masked slot is overwritten
  with the survivor mean (it rejoins healthy next round).  Composes with
  the fault-tolerance ``live_mask``.
- **HealthSentry** (host side): consumes the stats tree each round,
  keeps a loss EMA and flags spikes by z-score, feeds the shared metrics
  registry (``sparknet_grad_norm``, ``sparknet_nonfinite_total``,
  ``sparknet_update_ratio{group}``) and the JSONL run log, records every
  verdict into the flight recorder, and acts per policy:

  ``warn``      log + metrics only; training continues.
  ``halt``      dump a flight bundle, flip /healthz to 503, raise
                ``SentryHalt`` (the driver exits WITHOUT snapshotting
                the poisoned weights).
  ``rollback``  restore the newest verified snapshot
                (``io/checkpoint.restore_newest_valid``) and continue
                with the NEXT round's data — the poisoned window is
                skipped and ``state.iter`` rewinds, so the LR schedule
                replays from the restore point (the LR-backoff /
                skip-window semantics); after ``max_rollbacks`` the
                sentry escalates to halt.

Cost: the audit itself is a handful of fused reductions inside the
existing program (``bench.py --mode=health`` A/Bs it — HEALTH_r10.json);
the sentry adds one small per-round device_get of scalar stats.  On the
axon relay ANY device->host read degrades the put lane (PERF.md), so
``--health`` is opt-in and documented as such.
"""

from __future__ import annotations

import math
import os
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

POLICIES = ("warn", "halt", "rollback")


class SentryHalt(RuntimeError):
    """The divergence sentry halted the run (policy ``halt``, or
    ``rollback`` with no restore point / rollback budget exhausted)."""

    def __init__(self, round_index: int, reason: str):
        super().__init__(f"sentry halt at round {round_index}: {reason}")
        self.round_index = round_index
        self.reason = reason


# ----------------------------------------------------------------------
# in-graph audit (pure jnp — traced into the jitted step bodies)


def nonfinite_count(tree):
    """int32 count of non-finite values across a pytree (0 if empty)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0, jnp.int32)
    total = None
    for l in leaves:
        c = jnp.sum(~jnp.isfinite(l)).astype(jnp.int32)
        total = c if total is None else total + c
    return total


def audit_iteration(grads, params, new_params, loss, grad_norm):
    """Per-iteration stats tree, computed INSIDE the jitted step (pure
    readouts of values the update already produced — nothing feeds back
    into the training math).  ``grad_norm`` is the raw pre-clip global
    L2 the solver already computes for ``clip_gradients``.

    Division discipline: the update/param ratio is 0 (not NaN) when a
    group's param norm is zero — all-zero grads / freshly-zeroed blobs
    never poison the audit itself."""
    import jax.numpy as jnp

    stats = {
        "grad_norm": jnp.asarray(grad_norm, jnp.float32),
        "nonfinite_grads": nonfinite_count(grads),
        "nonfinite_params": nonfinite_count(new_params),
        "nonfinite_loss": jnp.sum(~jnp.isfinite(loss)).astype(jnp.int32),
        "param_norm": {},
        "update_ratio": {},
    }
    for key, blobs in new_params.items():
        psq = None
        usq = None
        for w_new, w_old in zip(blobs, params[key]):
            wn = w_new.astype(jnp.float32)
            dw = wn - w_old.astype(jnp.float32)
            p = jnp.sum(jnp.square(wn))
            u = jnp.sum(jnp.square(dw))
            psq = p if psq is None else psq + p
            usq = u if usq is None else usq + u
        pnorm = jnp.sqrt(psq)
        unorm = jnp.sqrt(usq)
        stats["param_norm"][key] = pnorm
        stats["update_ratio"][key] = jnp.where(
            pnorm > 0.0, unorm / jnp.maximum(pnorm, 1e-12), 0.0
        )
    return stats


# ----------------------------------------------------------------------
# host side: verdicts + the sentry


class HealthVerdict:
    """One round's health readout (host floats, JSON-safe via
    ``as_dict``)."""

    def __init__(
        self,
        round_index: int,
        loss: float,
        zscore: float,
        grad_norm: float,
        nonfinite_grads: int,
        nonfinite_params: int,
        nonfinite_loss: int,
        per_worker_nonfinite: Optional[List[int]],
        masked_workers: List[int],
        reasons: List[str],
    ):
        self.round_index = round_index
        self.loss = loss
        self.zscore = zscore
        self.grad_norm = grad_norm
        self.nonfinite_grads = nonfinite_grads
        self.nonfinite_params = nonfinite_params
        self.nonfinite_loss = nonfinite_loss
        self.per_worker_nonfinite = per_worker_nonfinite
        self.masked_workers = masked_workers
        self.reasons = list(reasons)
        self.action = "none"  # filled by the sentry: none|warn|masked|
        #                       rollback|halt

    @property
    def nonfinite_total(self) -> int:
        return (
            self.nonfinite_grads + self.nonfinite_params + self.nonfinite_loss
        )

    @property
    def ok(self) -> bool:
        return not self.reasons

    def as_dict(self) -> Dict:
        return {
            "round": self.round_index,
            "loss": self.loss,
            "zscore": round(self.zscore, 3),
            "grad_norm": self.grad_norm,
            "nonfinite": self.nonfinite_total,
            "nonfinite_grads": self.nonfinite_grads,
            "nonfinite_params": self.nonfinite_params,
            "nonfinite_loss": self.nonfinite_loss,
            "per_worker_nonfinite": self.per_worker_nonfinite,
            "masked_workers": self.masked_workers,
            "ok": self.ok,
            "reasons": self.reasons,
            "action": self.action,
        }


class HealthSentry:
    """Consumes round audit stats, classifies, and acts per policy.

    Loop glue: ``guarded_round(trainer, state, batches)`` for the
    parameter-averaging trainer and ``guarded_step(stepper, state,
    batches)`` for ``Solver``/``AllReduceTrainer`` both return the plain
    ``(state, losses)`` the unguarded loops already unpack — the stats
    tree is consumed here.  ``observe(r, losses, stats)`` is the lower-
    level entry for loops that drive the trainer themselves (chaos)."""

    def __init__(
        self,
        policy: str = "warn",
        *,
        z_threshold: float = 6.0,
        ema_beta: float = 0.9,
        warmup_rounds: int = 3,
        cooldown_rounds: int = 3,
        max_rollbacks: int = 3,
        restore_fn: Optional[Callable[[], Tuple[object, str]]] = None,
        echo=None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"health policy {policy!r} not in {'|'.join(POLICIES)}"
            )
        self.policy = policy
        self.z_threshold = float(z_threshold)
        self.ema_beta = float(ema_beta)
        self.warmup_rounds = int(warmup_rounds)
        self.cooldown_rounds = int(cooldown_rounds)
        self.max_rollbacks = int(max_rollbacks)
        # restore_fn() -> (ready-to-train state, snapshot path) — see
        # make_restore_fn; None means ``rollback`` degrades to halt
        self.restore_fn = restore_fn
        self._echo = echo
        # EMA of the round-mean loss + EMA variance (spike z-score)
        self._ema: Optional[float] = None
        self._emvar = 0.0
        self._seen = 0
        self._cooldown = 0
        # per-round EMA snapshots (bounded ring): the z-score lens AT a
        # past round, so a bounded-staleness arrival is judged at its
        # OWN round index — a lag-L worker's (legitimately higher) loss
        # compared against round b's EMA would read as a spike
        self._ema_ring: "OrderedDict[int, Tuple[Optional[float], float, int]]" = (
            OrderedDict()
        )
        # exported state (the /healthz surface)
        self.last_anomaly_round: Optional[int] = None
        # last round INDEX observed — resumed runs pass absolute
        # indices, so rounds_since_anomaly must not lean on the count
        self.last_round: Optional[int] = None
        self.rounds_observed = 0
        self.anomalies = 0
        self.rollbacks = 0
        self.halted = False
        self.halt_reason: Optional[str] = None
        self.verdicts: List[HealthVerdict] = []

    # ------------------------------------------------------------------
    def _say(self, msg: str) -> None:
        if self._echo is not None:
            self._echo("health: " + msg)

    def state_dict(self) -> Dict:
        """The /healthz sentry block — orchestrators read this to tell
        "training stalled" from "training diverged"."""
        last = self.last_anomaly_round
        return {
            "policy": self.policy,
            "last_anomaly_round": last,
            "rounds_since_anomaly": (
                None
                if last is None or self.last_round is None
                else max(0, self.last_round - last)
            ),
            "anomalies": self.anomalies,
            "rollbacks": self.rollbacks,
            "halted": self.halted,
            "halt_reason": self.halt_reason,
        }

    # ------------------------------------------------------------------
    # full job state (crash consistency): the sentry's carried scalars.
    # A resume that drops these silently restarts the loss-EMA warmup
    # and forgets an active cooldown — the z-score lens changes, so a
    # spike right after restart reads differently than it would have in
    # the uninterrupted run.  Journaled beside params by the recover
    # loop (io/checkpoint extra_state; runtime/recover.py).
    def export_state(self) -> Dict:
        return {
            "ema": self._ema,
            "emvar": self._emvar,
            "seen": self._seen,
            "cooldown": self._cooldown,
            # the per-round lens ring (bounded-staleness judging): a
            # resume that drops it would re-judge a replayed stale
            # arrival with an empty lens
            "ema_ring": {
                str(r): [v[0], v[1], v[2]]
                for r, v in self._ema_ring.items()
            },
            "last_anomaly_round": self.last_anomaly_round,
            "last_round": self.last_round,
            "rounds_observed": self.rounds_observed,
            "anomalies": self.anomalies,
            "rollbacks": self.rollbacks,
        }

    def load_state(self, d: Dict) -> None:
        self._ema = None if d.get("ema") is None else float(d["ema"])
        self._emvar = float(d.get("emvar", 0.0))
        self._seen = int(d.get("seen", 0))
        self._cooldown = int(d.get("cooldown", 0))
        self._ema_ring = OrderedDict(
            (
                int(r),
                (
                    None if v[0] is None else float(v[0]),
                    float(v[1]),
                    int(v[2]),
                ),
            )
            for r, v in sorted(
                (d.get("ema_ring") or {}).items(),
                key=lambda kv: int(kv[0]),
            )
        )
        lar = d.get("last_anomaly_round")
        self.last_anomaly_round = None if lar is None else int(lar)
        lr = d.get("last_round")
        self.last_round = None if lr is None else int(lr)
        self.rounds_observed = int(d.get("rounds_observed", 0))
        self.anomalies = int(d.get("anomalies", 0))
        self.rollbacks = int(d.get("rollbacks", 0))

    # ------------------------------------------------------------------
    # z-score machinery (host floats only)
    def _spike(self, z: float) -> bool:
        """Strictly ABOVE the threshold flags — a loss sitting exactly
        at the threshold does not (tested boundary)."""
        return z > self.z_threshold

    def _zscore(self, loss: float) -> float:
        return self._zscore_at(loss, (self._ema, self._emvar, self._seen))

    def _zscore_at(self, loss: float, lens) -> float:
        """z-score against an explicit (ema, emvar, seen) lens — the
        current one, or a past round's snapshot from ``_ema_ring``
        (bounded-staleness arrivals are judged at their OWN round)."""
        if lens is None:
            return 0.0
        ema, emvar, seen = lens
        if ema is None or seen < self.warmup_rounds:
            return 0.0
        # variance floor at 5% of the loss scale: with a near-constant
        # loss the EMA variance collapses and raw z would flag noise
        sigma = math.sqrt(max(0.0, emvar))
        denom = max(sigma, 0.05 * abs(ema) + 1e-8)
        return (loss - ema) / denom

    def _update_ema(self, loss: float) -> None:
        if not math.isfinite(loss):
            return  # never seed the EMA with poison
        if self._ema is None:
            self._ema = loss
            self._emvar = 0.0
        else:
            d = loss - self._ema
            self._ema += (1.0 - self.ema_beta) * d
            self._emvar = self.ema_beta * (
                self._emvar + (1.0 - self.ema_beta) * d * d
            )
        self._seen += 1

    def _reset_ema(self) -> None:
        self._ema = None
        self._emvar = 0.0
        self._seen = 0

    # ------------------------------------------------------------------
    def observe(
        self, round_index: int, losses, stats, *,
        arrived=None, worker_rounds=None,
    ) -> HealthVerdict:
        """Classify one round from its losses + audit stats tree.  The
        (small, scalar-only) stats fetch is the audit's one deliberate
        device->host sync per round.

        Bounded-staleness boundaries (``parallel/stale.py``) pass
        ``arrived`` (num_workers, bools: whose window folded in — the
        others' losses/stats are zeroed in-graph and must not drag the
        EMA) and ``worker_rounds`` (num_workers, ints: the absolute
        round each worker's folded window BELONGS to).  Each stale
        arrival is then judged against the EMA lens at its OWN round
        (the ``_ema_ring`` snapshot), not the boundary's — a lag-L
        worker's legitimately higher loss never trips a false
        spike, while a genuinely divergent one still does."""
        import jax

        from sparknet_tpu import obs as _obs
        from sparknet_tpu.obs import flight as _flight

        def _get_local(x):
            # multi-host: trainer stats/losses are dp-sharded across
            # processes and a plain device_get on a spanning jax.Array
            # raises.  Each process's sentry judges its ADDRESSABLE
            # workers — the same local-view rule Solver._drain_losses
            # uses for the loss window.
            if getattr(x, "is_fully_addressable", True):
                return np.asarray(jax.device_get(x))
            shards = [np.asarray(s.data) for s in x.addressable_shards]
            return np.concatenate(shards, axis=0)

        host = jax.tree_util.tree_map(_get_local, stats)
        loss_arr = np.asarray(_get_local(losses), np.float64)
        # arrival-aware loss view: the round-mean (and the EMA it
        # feeds) covers CURRENT-round arrivals; stale arrivals are
        # judged separately at their own round's lens below
        arr_mask = None
        wr = None
        stale_z = 0.0
        if (
            arrived is not None
            and loss_arr.ndim >= 2
            and np.asarray(arrived).reshape(-1).shape[0]
            == loss_arr.shape[0]
        ):
            arr_mask = np.asarray(arrived, bool).reshape(-1)
            if worker_rounds is not None:
                wr = np.asarray(worker_rounds, np.int64).reshape(-1)
            fresh = (
                arr_mask
                if wr is None
                else arr_mask & (wr >= round_index)
            )
            base = fresh if fresh.any() else arr_mask
            sel = loss_arr[base] if base.any() else loss_arr[arr_mask]
            loss = float(np.mean(sel)) if sel.size else float("nan")
            if wr is not None:
                for w in np.nonzero(arr_mask & (wr < round_index))[0]:
                    lens = self._ema_ring.get(int(wr[w]))
                    zw = self._zscore_at(
                        float(np.mean(loss_arr[w])), lens
                    )
                    stale_z = max(stale_z, zw)
        else:
            loss = (
                float(np.mean(loss_arr))
                if loss_arr.size
                else float("nan")
            )

        def total(name) -> int:
            return int(np.sum(np.asarray(host.get(name, 0))))

        nf_grads = total("nonfinite_grads")
        nf_params = total("nonfinite_params")
        # the audited step already counts the window's losses in-graph;
        # the host re-count covers stats trees that lack the series
        # (stubs, partial audits).  max(), not +: they see the SAME
        # losses, summing would double-report every poisoned round.
        nf_loss = max(
            total("nonfinite_loss"), int(np.sum(~np.isfinite(loss_arr)))
        )
        # per-worker attribution: trainer stats carry a leading workers
        # axis; single-process stats are (tau,) scalars per iter
        per_worker = None
        nf_w = np.asarray(host.get("nonfinite_grads", 0)) + np.asarray(
            host.get("nonfinite_params", 0)
        )
        if nf_w.ndim == 2:
            per_worker = [int(v) for v in nf_w.sum(axis=1)]
        masked = []
        if "masked" in host:
            m = np.asarray(host["masked"]).reshape(-1)
            masked = [int(w) for w in np.nonzero(m > 0)[0]]

        z = self._zscore(loss)
        reasons = []
        if nf_grads or nf_params or nf_loss:
            reasons.append("nonfinite")
        if self._cooldown > 0:
            self._cooldown -= 1
        elif self._spike(z) or self._spike(stale_z):
            # z: current-round arrivals vs the live EMA; stale_z: each
            # stale arrival vs the lens AT its own round — both real
            # divergence signals, neither a staleness artifact
            reasons.append("loss_spike")
        v = HealthVerdict(
            round_index, loss, z, self._last_scalar(host, "grad_norm"),
            nf_grads, nf_params, nf_loss, per_worker, masked, reasons,
        )
        # snapshot the pre-update lens for this round, then fold the
        # loss in: a future lag-L arrival whose window was round r is
        # judged against what the EMA was AT round r
        self._ema_ring[int(round_index)] = (
            self._ema, self._emvar, self._seen
        )
        while len(self._ema_ring) > 128:
            self._ema_ring.popitem(last=False)
        self._update_ema(loss)
        self.last_round = round_index
        self.rounds_observed += 1
        self.verdicts.append(v)
        if len(self.verdicts) > 4096:
            del self.verdicts[:2048]

        # metrics: the issue-named series on the shared registry
        tm = _obs.training_metrics()
        if tm is not None:
            tm.grad_norm.set(v.grad_norm)
            if v.nonfinite_total:
                tm.nonfinite.inc(v.nonfinite_total)
            ratios = host.get("update_ratio") or {}
            for group in ratios:
                tm.update_ratio.labels(group).set(
                    self._last_scalar(ratios, group)
                )
        # run log + flight ring: one health instant per round, so the
        # postmortem table is round-by-round even for healthy rounds
        _obs.instant("health", cat="health", **v.as_dict())
        _flight.record_verdict(v.as_dict())
        _flight.record_sample("loss", loss, round=round_index)
        _flight.record_sample("grad_norm", v.grad_norm, round=round_index)
        if not v.ok:
            self.anomalies += 1
            self.last_anomaly_round = round_index
            if tm is not None:
                for kind in v.reasons:
                    tm.health_anomalies.labels(kind).inc()
            _obs.instant(
                "health_anomaly", cat="health",
                round=round_index, reasons=v.reasons,
            )
            self._say(
                "round %d ANOMALY (%s): loss %.4g z %.2f nonfinite %d "
                "masked %s"
                % (
                    round_index, ",".join(v.reasons), loss, z,
                    v.nonfinite_total, masked,
                )
            )
        return v

    @staticmethod
    def _last_scalar(host: Dict, name: str) -> float:
        arr = np.asarray(host.get(name, np.nan), np.float64).reshape(-1)
        return float(arr[-1]) if arr.size else float("nan")

    # ------------------------------------------------------------------
    def _act(self, v: HealthVerdict, state):
        """Apply the policy to an anomalous verdict; returns the state
        to continue with (possibly restored)."""
        from sparknet_tpu import obs as _obs
        from sparknet_tpu.obs import flight as _flight

        absorbed = (
            v.masked_workers
            and v.per_worker_nonfinite is not None
            and len(v.masked_workers) < len(v.per_worker_nonfinite)
            and "loss_spike" not in v.reasons
        )
        if absorbed:
            # the in-graph mask already excluded the poisoned worker(s)
            # from the average; the weights are healthy — no escalation
            v.action = "masked"
            _flight.record_verdict(v.as_dict())  # refresh: action set
            self._say(
                "round %d: poisoned worker(s) %s masked out of the "
                "average; training continues"
                % (v.round_index, v.masked_workers)
            )
            return state
        if self.policy == "warn":
            v.action = "warn"
            _flight.record_verdict(v.as_dict())
            return state
        if self.policy == "rollback":
            if self.restore_fn is not None and (
                self.rollbacks < self.max_rollbacks
            ):
                try:
                    state, used = self.restore_fn()
                except (FileNotFoundError, RuntimeError) as e:
                    # no snapshot at all, or every candidate corrupt
                    # (SnapshotCorrupt) — nothing valid to roll back to
                    self._halt(v, f"rollback restore failed ({e})")
                self.rollbacks += 1
                self._cooldown = self.cooldown_rounds
                self._reset_ema()
                tm = _obs.training_metrics()
                if tm is not None:
                    tm.health_rollbacks.inc()
                v.action = "rollback"
                _flight.record_verdict(v.as_dict())  # refresh: action set
                _obs.instant(
                    "health_rollback", cat="health",
                    round=v.round_index, snapshot=os.path.basename(str(used)),
                )
                _flight.dump_if_active(
                    "sentry_rollback", extra={"round": v.round_index}
                )
                self._say(
                    "round %d: rolled back to %s; skipping the poisoned "
                    "window (LR schedule replays from the restore point)"
                    % (v.round_index, os.path.basename(str(used)))
                )
                return state
            why = (
                "rollback budget exhausted (%d)" % self.max_rollbacks
                if self.restore_fn is not None
                else "no restore point wired for rollback"
            )
            self._halt(v, why)
        self._halt(v, "policy=halt")

    def _halt(self, v: HealthVerdict, why: str):
        from sparknet_tpu import obs as _obs
        from sparknet_tpu.obs import flight as _flight

        v.action = "halt"
        _flight.record_verdict(v.as_dict())  # refresh BEFORE the dump
        self.halted = True
        self.halt_reason = f"{','.join(v.reasons)} at round {v.round_index}"
        _obs.report_unhealthy("sentry_halt: " + self.halt_reason)
        _flight.dump_if_active(
            "sentry_halt",
            extra={"round": v.round_index, "why": why},
        )
        self._say(f"HALT at round {v.round_index}: {why}")
        raise SentryHalt(v.round_index, why)

    # ------------------------------------------------------------------
    # loop glue — drop-in guards returning the plain (state, losses)
    def guarded_round(
        self, trainer, state, batches, *, rng=None, live_mask=None,
        round_index: Optional[int] = None,
    ):
        """One ``ParameterAveragingTrainer.round`` under the sentry."""
        r = self.rounds_observed if round_index is None else round_index
        state, losses, stats = trainer.round(
            state, batches, rng=rng, live_mask=live_mask,
            round_index=round_index,
        )
        v = self.observe(r, losses, stats)
        if not v.ok:
            state = self._act(v, state)
        return state, losses

    def guarded_step(
        self, stepper, state, batches, *, rng=None,
        round_index: Optional[int] = None,
    ):
        """One ``Solver.step`` / ``AllReduceTrainer.step`` window under
        the sentry."""
        r = self.rounds_observed if round_index is None else round_index
        state, losses, stats = stepper.step(state, batches, rng=rng)
        v = self.observe(r, losses, stats)
        if not v.ok:
            state = self._act(v, state)
        return state, losses


# ----------------------------------------------------------------------
# wiring helpers (the --health/--health_policy CLI surface)


def sentry_from_args(args, solver, restore_fn=None, echo=None):
    """Build (or skip) the sentry from parsed CLI args and flip the
    solver's audit on.  MUST run before a ``ParameterAveragingTrainer``
    is constructed from ``solver`` — the trainer bakes the audit arity
    into its shard_map output spec."""
    policy = getattr(args, "health_policy", None) or getattr(
        args, "health", None
    )
    if policy is None:
        return None
    from sparknet_tpu import obs as _obs

    solver.audit = True
    _obs.enable_training_metrics()
    sentry = HealthSentry(policy=policy, restore_fn=restore_fn, echo=echo)
    _obs.set_sentry(sentry)
    return sentry


def make_restore_fn(solver, prefix: str, trainer=None):
    """A ``restore_fn`` for rollback: newest VERIFIED snapshot under
    ``prefix`` (corrupt ones quarantined — ``restore_newest_valid``),
    re-placed for the caller's trainer (parameter-averaging broadcast /
    allreduce shard) or used directly for a single-process solver."""
    from sparknet_tpu.io import checkpoint

    def restore():
        st, used = checkpoint.restore_newest_valid(solver, prefix)
        if trainer is not None and hasattr(trainer, "broadcast_state"):
            st = trainer.broadcast_state(st)
        elif trainer is not None and hasattr(trainer, "shard_state"):
            st = trainer.shard_state(st)
        return st, used

    return restore
