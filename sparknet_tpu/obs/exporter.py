"""Opt-in telemetry sidecar: ``/metrics`` + ``/healthz`` for ANY run.

The serving stack has always exposed Prometheus text at ``/metrics``
(``serve/server.py``); this module gives the *training* side the same
scrape surface as a tiny stdlib HTTP sidecar — ``cli train --obs`` and
every app serve live rounds/s, per-phase latency, feed queue depth and
memory gauges while they run.  ``/healthz`` flips to 503 when the run
reports unhealthy (a ``PrefetchStall`` / stalled round — see
``obs.report_unhealthy``), so an orchestrator can restart a wedged
trainer the same way an LB drains a wedged replica.

``JsonHTTPHandler`` is the handler machinery shared with the serving
front-end (send/JSON helpers + quiet logging): ``serve/server.py``
subclasses it rather than duplicating the plumbing.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from sparknet_tpu.obs.metrics import MetricsRegistry


class JsonHTTPHandler(BaseHTTPRequestHandler):
    """Shared request-handler plumbing: length-correct sends, JSON
    helpers, HTTP/1.1 keep-alive, access logs off unless the bound
    server context says otherwise."""

    protocol_version = "HTTP/1.1"

    def _verbose(self) -> bool:
        return False

    def log_message(self, fmt, *args):
        if self._verbose():
            print(self.__class__.__name__ + ": " + fmt % args)

    def _send(self, code: int, payload: bytes, ctype: str,
              extra_headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _send_json(self, code: int, obj, extra_headers=()) -> None:
        self._send(
            code, json.dumps(obj).encode("utf-8"), "application/json",
            extra_headers,
        )

    # ------------------------------------------------------------------
    # Chunked transfer (HTTP/1.1) — the token-streaming send path
    # (serve/server.py POST /generate).  Content-Length framing cannot
    # stream an unknown-length body over keep-alive; chunked framing
    # can, and the 0-length terminal chunk keeps the connection clean.
    def _send_chunked_start(self, code: int, ctype: str,
                            extra_headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()

    def _send_chunk(self, payload: bytes) -> None:
        if not payload:
            return  # an empty chunk would terminate the stream
        self.wfile.write(b"%X\r\n" % len(payload) + payload + b"\r\n")
        self.wfile.flush()

    def _end_chunks(self) -> None:
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()


class _ObsHandler(JsonHTTPHandler):
    exporter: "ObsExporter"  # bound per-server via the factory below

    def do_GET(self):
        ex = self.exporter
        if self.path == "/metrics":
            self._send(
                200,
                ex.registry.render().encode("utf-8"),
                "text/plain; version=0.0.4",
            )
        elif self.path.startswith("/query") and ex.tsdb is not None:
            # single-host runs get the same rollup-history endpoint the
            # fleet collector serves (``--slo`` arms the sampler)
            q = parse_qs(urlparse(self.path).query)

            def _one(key, default=None):
                vals = q.get(key)
                return vals[0] if vals else default

            series = _one("series")
            if not series:
                self._send_json(400, {"error": "series= is required"})
                return
            try:
                range_s = float(_one("range", "300"))
                step = _one("step")
                step_s = float(step) if step is not None else None
            except ValueError as e:
                self._send_json(400, {"error": f"bad range/step: {e}"})
                return
            res = ex.tsdb.query(
                series, host=_one("host"), range_s=range_s, step_s=step_s
            )
            if res is None:
                self._send_json(
                    404, {"error": f"unknown series {series!r}"}
                )
                return
            res["tsdb"] = ex.tsdb.stats()
            self._send_json(200, res)
        elif self.path == "/slo" and ex.slo is not None:
            self._send_json(200, ex.slo.evaluate())
        elif self.path == "/signals" and ex.slo is not None:
            ex.slo.maybe_evaluate()
            self._send_json(200, ex.slo.signals())
        elif self.path == "/healthz":
            reason = ex.health_fn() if ex.health_fn is not None else None
            # divergence-sentry state rides along so an orchestrator can
            # tell "training stalled" (feed wedged -> reason set) from
            # "training diverged" (sentry halted -> 503 + sentry block)
            from sparknet_tpu import obs as _obs

            sentry = _obs.sentry_state()
            payload = {}
            if sentry is not None:
                payload["sentry"] = sentry
                if sentry.get("halted"):
                    reason = reason or (
                        "sentry_halt: " + str(sentry.get("halt_reason"))
                    )
            # round-anatomy block (--profile): straggler verdict +
            # hidden fractions, so an orchestrator can tell "healthy but
            # gated by worker 3" without scraping the full registry
            prof = _obs.profile_state()
            if prof is not None:
                payload["profile"] = prof
            # elastic-membership block (--elastic): the current view
            # epoch + per-worker states, so an orchestrator can tell
            # "slice 1 left and is rejoining" from "wedged" — a
            # degraded-but-training fleet stays 200
            member = _obs.membership_state()
            if member is not None:
                payload["membership"] = member
            # burn-rate SLO block (--slo): objective statuses + recent
            # alert transitions — a paging objective shows here without
            # scraping /slo (the run itself stays 200: an SLO page is a
            # capacity/objective verdict, not a wedged process)
            slo = _obs.slo_state()
            if slo is not None:
                payload["slo"] = slo
            if reason:
                payload.update({"status": "unhealthy", "reason": reason})
                self._send_json(503, payload)
            else:
                payload["status"] = "ok"
                self._send_json(200, payload)
        else:
            self._send_json(404, {"error": f"no route {self.path}"})


class ObsExporter:
    """Background ``/metrics`` + ``/healthz`` listener over a shared
    ``MetricsRegistry``.  ``health_fn() -> Optional[str]`` returns an
    unhealthy-reason string (None = healthy); port 0 binds an ephemeral
    port (tests), resolved via ``address``."""

    def __init__(
        self,
        registry: MetricsRegistry,
        host: str = "127.0.0.1",
        port: int = 8380,
        health_fn: Optional[Callable[[], Optional[str]]] = None,
        tsdb=None,
        slo=None,
    ):
        self.registry = registry
        self.health_fn = health_fn
        # retention plane (``--slo``): a TSDB + SLOEvaluator make this
        # sidecar serve /query, /slo and /signals like the fleet
        # collector does
        self.tsdb = tsdb
        self.slo = slo
        ex = self

        class BoundHandler(_ObsHandler):
            exporter = ex

        self.httpd = ThreadingHTTPServer((host, port), BoundHandler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        """(host, port) actually bound (port 0 resolves here)."""
        return self.httpd.server_address[:2]

    def start(self) -> "ObsExporter":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="obs-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
